"""Named-dataset model: columnar data + the metadata/lineage contract.

The reference's universal data plane is "one Mongo collection per file" where
document ``_id: 0`` is a metadata doc ``{filename, url|parent_filename,
time_created, finished, fields}`` and rows are ``_id: 1..N`` in CSV order
(reference database.py:157-168,205-213; docs/database_api.md:3-77). The
``finished`` flag flipping false→true is the system-wide async-completion
signal the client polls (database.py:177-181), and ``parent_filename``
records lineage for derived datasets.

This module keeps that *contract* — names, metadata-doc shape, finished-flag
semantics, row ``_id`` numbering — over a TPU-friendly *mechanism*: columns
are contiguous numpy arrays (zero-copy into ``jax.numpy``/device shards)
instead of per-row BSON documents.

Out-of-core: the reference's data plane is disk-backed Mongo and handles
collections larger than RAM (reference database.py:133-216). Here each
append becomes an immutable *chunk* that can live in host RAM, in a parquet
chunk file on disk, or both. Under a configured RAM budget
(``Settings.ram_budget_mb``) chunks are flushed to disk and evicted, and
streaming consumers (`iter_chunks`) process the dataset one chunk at a time
— ingest → histogram → projection run on datasets larger than host memory.
Chunk files are written via tmp+rename and recorded in an fsynced
``journal.jsonl``, making every chunk commit O(chunk) and crash-consistent
(a recovered dataset is always a journaled prefix of the appends).

Upgrade over the reference: a mid-flight crash in the reference leaves
``finished: false`` forever and clients poll infinitely (SURVEY.md §5); here
metadata carries an ``error`` field that job runners set on failure so
clients can fail fast.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
import weakref
import zlib
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, List, Optional

import numpy as np

from learningorchestra_tpu.catalog import readpipe
from learningorchestra_tpu.utils import failpoints, tracing

#: Columns are numpy arrays: numeric dtypes or ``object`` for strings/mixed.
Columns = Dict[str, np.ndarray]

#: Deterministic fault-injection sites (utils/failpoints.py). Each names
#: the exact I/O boundary a crash/torn-write test targets; zero overhead
#: unless armed via LO_TPU_FAILPOINTS.
FP_WRITE_CHUNK_PRE_RENAME = failpoints.declare(
    "catalog.write_chunk.pre_rename")
FP_JOURNAL_MID_APPEND = failpoints.declare("catalog.journal.mid_append")
FP_JOURNAL_PRE_SWAP = failpoints.declare("catalog.journal.pre_swap")
FP_CHUNK_PRE_READ = failpoints.declare("catalog.chunk.pre_read")


class ChunkCorrupt(RuntimeError):
    """A journaled chunk file failed its checksum (or vanished) and could
    not be repaired from the replica mirror — the precise,
    catalog-surface error that replaces an opaque parquet/arrow parse
    traceback deep inside a consumer."""

    def __init__(self, path: str, expected: Optional[int],
                 actual: Optional[int]):
        self.path = path
        self.expected = expected
        self.actual = actual
        what = ("is missing" if actual is None else
                f"checksum mismatch (journal crc32={expected}, "
                f"file crc32={actual})")
        super().__init__(
            f"chunk file {path} {what}; the dataset's journaled data is "
            "corrupt and no valid replica copy was available to repair "
            "from (see DatasetStore.scrub / docs/fault_tolerance.md)")


def crc32_file(path: str) -> int:
    """Streaming CRC32 of a file's bytes — the per-chunk integrity
    checksum recorded in the journal and verified on read/scrub."""
    crc = 0
    with open(path, "rb") as f:
        while True:
            block = f.read(1 << 20)
            if not block:
                return crc & 0xFFFFFFFF
            crc = zlib.crc32(block, crc)


@dataclass
class Metadata:
    """The ``_id: 0`` metadata document of a dataset."""

    name: str
    url: Optional[str] = None           # source URL for ingested datasets
    parent: Optional[str] = None        # lineage: parent dataset name
    time_created: str = ""
    finished: bool = False
    fields: List[str] = field(default_factory=list)
    error: Optional[str] = None         # set when an async job failed
    extra: Dict[str, Any] = field(default_factory=dict)  # e.g. model metrics

    def __post_init__(self):
        if not self.time_created:
            # Same human-readable stamp style as the reference
            # (database.py:206: time.strftime("%Y-%m-%d %H:%M:%S")).
            self.time_created = time.strftime("%Y-%m-%d %H:%M:%S")

    def to_doc(self) -> Dict[str, Any]:
        """Render as the reference-shaped metadata document (``_id: 0``)."""
        doc: Dict[str, Any] = {"_id": 0, "filename": self.name}
        if self.url is not None:
            doc["url"] = self.url
        if self.parent is not None:
            doc["parent_filename"] = self.parent
        doc["time_created"] = self.time_created
        doc["finished"] = self.finished
        doc["fields"] = list(self.fields)
        if self.error is not None:
            doc["error"] = self.error
        doc.update(self.extra)
        return doc

    @classmethod
    def from_doc(cls, doc: Dict[str, Any]) -> "Metadata":
        known = {"_id", "filename", "url", "parent_filename", "time_created",
                 "finished", "fields", "error"}
        return cls(
            name=doc["filename"],
            url=doc.get("url"),
            parent=doc.get("parent_filename"),
            time_created=doc.get("time_created", ""),
            finished=bool(doc.get("finished", False)),
            fields=list(doc.get("fields", [])),
            error=doc.get("error"),
            extra={k: v for k, v in doc.items() if k not in known},
        )


def _arr_bytes(a: np.ndarray) -> int:
    if a.dtype == object:
        # Estimate: pointer + small-string payload per element. Exact
        # accounting would walk every object; the budget is a soft bound.
        return len(a) * 64
    return int(a.nbytes)


class _Chunk:
    """One appended block of rows; in host RAM, in a parquet file, or both.

    In-RAM data is either materialized numpy columns (``cols``) or a
    ``pyarrow.RecordBatch`` (``arrow``) straight from the native parser —
    the ingest fast path that defers creating Python string objects until
    a reader actually needs them. Both drop to ``None`` when the chunk is
    evicted under a RAM budget; ``path`` is set once the chunk is durably
    flushed. Chunk files are immutable (written tmp+rename, never
    modified), so a disk-backed chunk can be re-read without coordination:
    readers snapshot ``cols``/``arrow`` into a local before testing it,
    and fall back to the file.

    ``src_off`` records the source-stream byte offset just past this
    chunk's last row (ingest chunks only) — journaled so an interrupted
    ingest can resume from the last committed byte (catalog/ingest.py
    ``resume_ingest``).
    """

    __slots__ = ("cols", "arrow", "path", "n_rows", "dtypes", "data_bytes",
                 "src_off", "_evictable", "crc32", "verify", "_verified")

    def __init__(self, cols: Columns):
        self.cols: Optional[Columns] = cols
        self.arrow = None
        self.path: Optional[str] = None
        self.n_rows = len(next(iter(cols.values())))
        self.dtypes: Dict[str, np.dtype] = {f: a.dtype
                                            for f, a in cols.items()}
        self.data_bytes = sum(_arr_bytes(a) for a in cols.values())
        self.src_off: Optional[int] = None
        self._evictable: Optional[bool] = None
        #: Journaled CRC32 of the chunk file's bytes (None for chunks
        #: never flushed, or restored from pre-checksum journals).
        self.crc32: Optional[int] = None
        #: Integrity callback (Dataset._verify_chunk) run before the
        #: first disk read of this chunk; None for purely in-memory use.
        self.verify: Optional[Callable] = None
        self._verified = False

    @classmethod
    def from_arrow(cls, batch, src_off: Optional[int] = None) -> "_Chunk":
        """Chunk backed by a pyarrow RecordBatch (ingest fast path)."""
        import pyarrow as pa

        c = cls.__new__(cls)
        c.cols = None
        c.arrow = batch
        c.path = None
        c.crc32 = None
        c.verify = None
        c._verified = False
        c.n_rows = batch.num_rows
        c.dtypes = {}
        for fld in batch.schema:
            if pa.types.is_string(fld.type) or pa.types.is_large_string(
                    fld.type):
                c.dtypes[fld.name] = np.dtype(object)
            else:
                c.dtypes[fld.name] = np.dtype(fld.type.to_pandas_dtype())
        c.data_bytes = int(batch.nbytes)
        c.src_off = src_off
        # Arrow batches hold only numbers/strings/nulls — exactly the
        # parquet value domain, so a disk round-trip is always faithful.
        c._evictable = True
        return c

    @classmethod
    def on_disk(cls, path: str, n_rows: int, dtypes: Dict[str, np.dtype],
                data_bytes: int, src_off: Optional[int] = None,
                crc32: Optional[int] = None) -> "_Chunk":
        """Handle for a journaled chunk file — no data read (lazy load)."""
        c = cls.__new__(cls)
        c.cols = None
        c.arrow = None
        c.path = path
        c.n_rows = n_rows
        c.dtypes = dict(dtypes)
        c.data_bytes = data_bytes
        c.src_off = src_off
        c._evictable = True
        c.crc32 = crc32
        c.verify = None
        c._verified = False
        return c

    @property
    def in_memory(self) -> bool:
        return self.cols is not None or self.arrow is not None

    @property
    def evictable(self) -> bool:
        """Whether a disk round-trip reproduces this chunk's values exactly.

        Parquet stores object columns as nullable strings, so a chunk whose
        object columns hold anything but str/None (e.g. float scores with
        None gaps from ``append_rows``) would come back with its numbers
        silently stringified — such chunks stay resident instead of
        evicting. (Cross-restart persistence still stringifies them; the
        guarantee here is no value drift *within* a process.)"""
        if self._evictable is None:
            cols = self.cols
            ok = True
            if cols is not None:
                for a in cols.values():
                    if a.dtype == object and not is_stringy(a):
                        ok = False
                        break
            self._evictable = ok
        return self._evictable

    def materialize(self, fields: Optional[List[str]] = None) -> Columns:
        """Column data for this chunk (optionally a field subset). Disk
        reads are never cached back onto the chunk object (streaming
        consumers stay bounded per dataset); they DO go through the
        byte-budgeted process-wide LRU chunk cache (catalog/readpipe.py),
        whose CRC-pinned keys and budget keep that sharing safe and
        bounded.

        Disk reads coerce to the chunk's *current* ``dtypes``: consolidation
        may have re-pointed an already-flushed chunk at dtype-promoted (or
        stringified) views before a budget eviction dropped them, leaving
        the journaled file with the pre-promotion dtype. Re-applying the
        ``_concat`` promotion rule here keeps streamed values identical to
        what consolidation yields (no in-process drift)."""
        cols = self.cols
        if cols is None:
            arrow = self.arrow
            if arrow is not None:
                # Arrow → numpy: strings become object arrays with None
                # for nulls (the catalog column domain), numerics stay
                # their dtypes. Not cached back: readers of an unevicted
                # arrow chunk are transient (consolidation caches its own
                # result).
                data = {name: col.to_numpy(zero_copy_only=False)
                        for name, col in zip(arrow.schema.names,
                                             arrow.columns)
                        if fields is None or name in fields}
                return ({f: data[f] for f in fields} if fields is not None
                        else data)
            # Warm-path: the byte-budgeted LRU chunk cache (readpipe)
            # keyed by (path, journal CRC32, field selection) — the raw
            # decoded read, shared across passes/datasets. A hit skips
            # the file read AND its first-read verification (the cached
            # bytes were verified when they were read); the dtype
            # coercion below still runs per call against the chunk's
            # CURRENT dtypes, so cached data can never drift from what a
            # fresh read would yield.
            fkey = None if fields is None else tuple(fields)
            data = readpipe.cache_get(self.path, self.crc32, fkey)
            if data is None:
                if not self._verified and self.verify is not None:
                    # First disk read: checksum the file (repairing from
                    # the replica on mismatch) before handing bytes to
                    # the arrow reader — corruption surfaces as
                    # ChunkCorrupt here, not as a parse traceback deep
                    # inside a fit.
                    self.verify(self)
                data = read_chunk_file(self.path, fields)
                readpipe.cache_put(
                    self.path, self.crc32, fkey, data,
                    sum(_arr_bytes(a) for a in data.values()))
            for f, a in data.items():
                want = self.dtypes.get(f)
                if want is not None and a.dtype != want:
                    data[f] = (stringify_numeric(a)
                               if (want == object and a.dtype != object)
                               else a.astype(want))
            return data
        if fields is not None:
            return {f: cols[f] for f in fields}
        return cols


class Dataset:
    """A named columnar dataset with reference-compatible row addressing.

    Rows are addressed ``_id = 1..N`` in insertion order; ``_id = 0`` is the
    metadata document. Appends are amortized O(1) via chunked column buffers
    so streaming CSV ingestion never re-copies the whole table per chunk.
    """

    def __init__(self, metadata: Metadata, columns: Optional[Columns] = None):
        self.metadata = metadata
        # Guards _chunks/_consolidated: ingestion appends from a job thread
        # while readers poll/consolidate the same dataset.
        self._data_lock = threading.Lock()
        self._chunks: List[_Chunk] = []
        self._consolidated: Optional[Columns] = None
        self._chunk_dir: Optional[str] = None
        self._journal_path: Optional[str] = None
        self._ram_budget: Optional[int] = None
        #: Prefetch window for streaming reads (iter_chunks / snapshot
        #: scans); None = the process default (LO_TPU_PREFETCH_CHUNKS).
        self._prefetch: Optional[int] = None
        #: Chunk files are named ``GGG-NNNNN.parquet``: the generation bumps
        #: on every rewrite (set_column) so filenames never collide across
        #: rewrites — old-generation files stay valid until the new journal
        #: is atomically swapped in, then get garbage-collected.
        self._gen = 0
        self._next_chunk_id = 0
        self._journal_records = 0
        #: Streaming readers (iter_chunks) holding a chunk snapshot; chunk
        #: file GC defers while any are active.
        self._active_readers = 0
        self._pending_gc = False
        #: Derived-artifact cache (design matrices): {key: (snapshot_id,
        #: value)}, valid only while the consolidation snapshot it was
        #: built from is current. See ``memo``.
        self._memo: Dict[Any, tuple] = {}
        #: Set when the chunk list was rebuilt in place (set_column) while
        #: on-disk chunk state existed: flushed chunk files no longer
        #: describe the data and the store must rewrite a fresh generation
        #: on the next save.
        self._rewrite_needed = False
        #: ``hook(chunk_basename, expected_crc) -> bool`` — attempts to
        #: restore a corrupt/missing chunk file (DatasetStore wires this
        #: to its replica mirror). None = no repair tier; corruption
        #: raises ChunkCorrupt directly.
        self._repair_hook: Optional[Callable[[str, Optional[int]], bool]] \
            = None
        if columns:
            self.append_columns(columns)

    # -- storage wiring (set by DatasetStore) --------------------------------

    def attach_storage(self, chunk_dir: str, journal_path: str,
                       ram_budget_bytes: Optional[int] = None,
                       prefetch_chunks: Optional[int] = None) -> None:
        """Wire the on-disk chunk tier: where flushed/evicted chunks go and
        how much column data may stay resident in host RAM.
        ``prefetch_chunks`` pins this dataset's streaming-read prefetch
        window (None = the process default)."""
        with self._data_lock:
            self._chunk_dir = chunk_dir
            self._journal_path = journal_path
            self._ram_budget = ram_budget_bytes or None
            if prefetch_chunks is not None:
                self._prefetch = prefetch_chunks
            self._maybe_evict_locked()

    def set_repair_hook(self, hook: Optional[Callable]) -> None:
        """Wire the corruption-repair tier (``hook(basename, crc) ->
        repaired?``) — called by DatasetStore with its replica mirror."""
        self._repair_hook = hook

    def _verify_chunk(self, chunk: "_Chunk") -> None:
        """Checksum one on-disk chunk before its bytes are trusted.

        Fires the ``catalog.chunk.pre_read`` failpoint (the bit-rot
        injection site), then compares the file's CRC32 against the
        journaled value. On mismatch — or a missing file — the repair
        hook (replica mirror) gets one shot at restoring it; if the file
        still doesn't verify, raises :class:`ChunkCorrupt`. Chunks from
        pre-checksum journals (``crc32`` is None) have nothing to verify
        and pass. Idempotent and safe to race: repair lands via
        tmp+rename, and the worst case is two threads both verifying.
        """
        failpoints.fire(FP_CHUNK_PRE_READ, path=chunk.path)
        expected = chunk.crc32
        if expected is None:
            chunk._verified = os.path.isfile(chunk.path)
            if not chunk._verified:
                if self._repair_hook is not None and self._repair_hook(
                        os.path.basename(chunk.path), None):
                    chunk._verified = True
                    return
                raise ChunkCorrupt(chunk.path, None, None)
            return
        actual = (crc32_file(chunk.path) if os.path.isfile(chunk.path)
                  else None)
        if actual == expected:
            chunk._verified = True
            return
        if self._repair_hook is not None and self._repair_hook(
                os.path.basename(chunk.path), expected):
            if os.path.isfile(chunk.path) \
                    and crc32_file(chunk.path) == expected:
                chunk._verified = True
                return
        raise ChunkCorrupt(chunk.path, expected, actual)

    @property
    def mem_bytes(self) -> int:
        """Estimated bytes of chunk data resident in host RAM."""
        with self._data_lock:
            return sum(c.data_bytes for c in self._chunks if c.in_memory)

    @property
    def data_bytes(self) -> int:
        """Estimated total bytes of column data (resident or spilled)."""
        with self._data_lock:
            return sum(c.data_bytes for c in self._chunks)

    # -- writes -------------------------------------------------------------

    def append_columns(self, columns: Columns,
                       src_off: Optional[int] = None) -> None:
        """Append a chunk of rows given as equal-length column arrays.
        ``src_off`` (ingest chunks) journals the source byte offset after
        this chunk's last row for resume."""
        if not columns:
            return
        lengths = {len(v) for v in columns.values()}
        if len(lengths) != 1:
            raise ValueError(f"ragged column chunk: {lengths}")
        cols = {k: np.asarray(v) for k, v in columns.items()}
        if not self.metadata.fields:
            self.metadata.fields = list(cols.keys())
        elif list(cols.keys()) != self.metadata.fields:
            missing = set(self.metadata.fields) - set(cols.keys())
            extra = set(cols.keys()) - set(self.metadata.fields)
            if missing or extra:
                raise ValueError(
                    f"chunk fields mismatch: missing={missing} extra={extra}")
            cols = {k: cols[k] for k in self.metadata.fields}  # reorder
        with self._data_lock:
            chunk = _Chunk(cols)
            chunk.src_off = src_off
            self._chunks.append(chunk)
            self._consolidated = None
            self._maybe_evict_locked()

    def append_arrow(self, batch, src_off: Optional[int] = None) -> None:
        """Append a chunk of rows as a ``pyarrow.RecordBatch`` (the native
        ingest fast path — no Python-object materialization). ``src_off``
        is the source-stream byte offset after this chunk's last row,
        journaled for ingest resume."""
        if batch.num_rows == 0:
            return
        names = list(batch.schema.names)
        if not self.metadata.fields:
            self.metadata.fields = names
        elif names != self.metadata.fields:
            missing = set(self.metadata.fields) - set(names)
            extra = set(names) - set(self.metadata.fields)
            if missing or extra:
                raise ValueError(
                    f"chunk fields mismatch: missing={missing} extra={extra}")
            batch = batch.select(self.metadata.fields)
        with self._data_lock:
            self._chunks.append(_Chunk.from_arrow(batch, src_off))
            self._consolidated = None
            self._maybe_evict_locked()

    def append_rows(self, rows: List[Dict[str, Any]]) -> None:
        """Append row dicts (used by result writers, e.g. predictions)."""
        if not rows:
            return
        fields = self.metadata.fields or list(rows[0].keys())
        cols: Columns = {}
        for f in fields:
            vals = [r.get(f) for r in rows]
            arr = np.asarray(vals)
            if arr.dtype.kind == "U":  # keep strings as object for None-safety
                arr = np.asarray(vals, dtype=object)
            cols[f] = arr
        self.append_columns(cols)

    def set_column(self, name: str, values: np.ndarray) -> None:
        """Replace/add a full column (used by type coercion). Atomic:
        snapshot, length-check, and replacement all happen under the data
        lock so a concurrent append can never be silently dropped.

        Materializes the dataset (coercion is inherently O(n)); previously
        flushed chunk files become stale and are rewritten on next save.
        """
        values = np.asarray(values)
        with self._data_lock:
            cols = dict(self._consolidate_locked())
            n = len(next(iter(cols.values()))) if cols else 0
            if n and len(values) != n:
                raise ValueError(
                    f"column length {len(values)} != num_rows {n}")
            cols[name] = values
            if name not in self.metadata.fields:
                self.metadata.fields.append(name)
            had_disk_state = (self._journal_records > 0
                              or any(c.path is not None
                                     for c in self._chunks))
            self._chunks = [_Chunk({f: cols[f]
                                    for f in self.metadata.fields})]
            self._consolidated = None
            # Only flag a rewrite when journaled files actually describe
            # stale data; a purely in-memory dataset just flushes normally.
            self._rewrite_needed = self._rewrite_needed or had_disk_state
            self._maybe_evict_locked()

    # -- chunk flushing / eviction ------------------------------------------

    def _write_chunk_file_locked(self, chunk: _Chunk) -> Dict[str, Any]:
        """Write one chunk to a new immutable parquet file (tmp + fsync +
        rename + dir fsync) and return its journal record. The caller
        commits the record to the journal."""
        assert self._chunk_dir is not None
        os.makedirs(self._chunk_dir, exist_ok=True)
        # Chunk files are Arrow IPC, uncompressed: writing is essentially
        # a buffer memcpy (~2.5x faster than parquet on the ingest-bound
        # one-core boxes this runs on) and reading is bulk buffer loads.
        # Legacy .parquet chunk files from older journals stay readable
        # (read_chunk_file dispatches on extension).
        fname = f"{self._gen:03d}-{self._next_chunk_id:05d}.arrow"
        self._next_chunk_id += 1
        final = os.path.join(self._chunk_dir, fname)
        tmp = final + ".tmp"
        if chunk.cols is None and chunk.arrow is not None:
            # Arrow chunks write straight from their buffers — no Python
            # string materialization on the ingest flush path.
            write_chunk_arrow_batch(tmp, chunk.arrow)
            dtypes = {f: str(dt) for f, dt in chunk.dtypes.items()}
        else:
            cols = chunk.materialize()
            write_chunk_arrow(tmp, cols, list(cols.keys()))
            # Record what was actually written (consolidation may have
            # promoted a view's dtype past what the chunk was appended
            # with).
            dtypes = {f: str(a.dtype) for f, a in cols.items()}
        # Checksum BEFORE the durability barrier: the journaled CRC32
        # describes what the writer intended, so storage-level damage
        # after this point (torn write, bit rot — or the failpoint below
        # simulating either) is detectable on every later read/scrub.
        crc = crc32_file(tmp)
        _fsync_file(tmp)
        failpoints.fire(FP_WRITE_CHUNK_PRE_RENAME, path=tmp)
        os.replace(tmp, final)
        _fsync_dir(self._chunk_dir)
        chunk.path = final
        chunk.crc32 = crc
        chunk.verify = self._verify_chunk
        chunk._verified = False
        rec = {"file": fname, "rows": chunk.n_rows,
               "bytes": chunk.data_bytes, "dtypes": dtypes, "crc32": crc}
        if chunk.src_off is not None:
            rec["src_off"] = chunk.src_off
        return rec

    def _commit_records_locked(self, records: List[Dict[str, Any]]) -> None:
        """Append journal lines for already-written chunk files with ONE
        fsync — the commit point. Files (and their renames) were fsynced
        before this, so a durable journal entry always references a
        durable file; a crash in between simply drops those chunks and
        recovery sees a consistent prefix (the reference's metadata-first
        idiom at chunk granularity, projection.py:78-123)."""
        if not records:
            return
        t0 = time.monotonic()
        with open(self._journal_path, "a") as f:
            for rec in records:
                f.write(json.dumps(rec) + "\n")
            f.flush()
            # Crash window under test: records written but not yet
            # durable — recovery must land on the journaled prefix
            # (_parse_journal_bytes tolerates a torn tail).
            failpoints.fire(FP_JOURNAL_MID_APPEND, path=self._journal_path)
            os.fsync(f.fileno())
        self._journal_records += len(records)
        # The durability tax of a traced ingest/build, attributed: one
        # span per journal commit (append + fsync). No-op untraced.
        tracing.record_span("journal.commit", time.monotonic() - t0,
                            attrs={"records": len(records),
                                   "dataset": self.metadata.name})

    def _flush_chunk_locked(self, chunk: _Chunk) -> None:
        """Write + journal-commit one chunk (eviction path)."""
        self._commit_records_locked([self._write_chunk_file_locked(chunk)])

    def flush_new_chunks(self) -> List[str]:
        """Flush every not-yet-persisted chunk (store.save's incremental
        commit). All chunk files are written first, then journaled with a
        single fsync — a per-save batch, so a streaming ingest that
        commits every few chunks pays one journal fsync per batch instead
        of one per chunk. Returns the chunk file paths written this call."""
        written = []
        with self._data_lock:
            if self._chunk_dir is None:
                return written
            records = []
            for c in self._chunks:
                if c.path is None:
                    records.append(self._write_chunk_file_locked(c))
                    written.append(c.path)
            self._commit_records_locked(records)
        return written

    def rewrite_generation(self) -> bool:
        with self._data_lock:
            return self._rewrite_generation_locked()

    def _rewrite_generation_locked(self) -> bool:
        """Atomically replace the on-disk chunk state after a set_column
        rebuild. Returns whether a rewrite ran.

        Crash-safe ordering: every new-generation chunk file is written and
        fsynced first (old files untouched), then the *whole* new journal is
        swapped in with one atomic rename. Whichever journal version
        survives a crash references files that exist — there is never a
        window where committed data is unrecoverable. Old-generation files
        are garbage-collected afterwards (deferred while streaming readers
        hold a chunk snapshot)."""
        if not self._rewrite_needed or self._chunk_dir is None:
            return False
        self._gen += 1
        self._next_chunk_id = 0
        records = [self._write_chunk_file_locked(c)
                   for c in self._chunks]
        tmp = self._journal_path + ".tmp"
        with open(tmp, "w") as f:
            for rec in records:
                f.write(json.dumps(rec) + "\n")
            f.flush()
            os.fsync(f.fileno())
        # Crash window under test: new-generation files durable, old
        # journal still in place — whichever journal survives references
        # files that exist.
        failpoints.fire(FP_JOURNAL_PRE_SWAP, path=tmp)
        os.replace(tmp, self._journal_path)
        _fsync_dir(os.path.dirname(self._journal_path))
        self._journal_records = len(records)
        self._rewrite_needed = False
        self._gc_locked()
        return True

    def _gc_locked(self) -> None:
        """Remove chunk files the journal no longer references (previous
        generations, orphaned tmp files). Deferred while streaming readers
        hold a chunk snapshot — their lazily-read files must stay valid."""
        if self._chunk_dir is None or not os.path.isdir(self._chunk_dir):
            return
        if self._active_readers:
            self._pending_gc = True
            return
        self._pending_gc = False
        referenced = {os.path.basename(c.path) for c in self._chunks
                      if c.path is not None}
        removed = []
        for fn in os.listdir(self._chunk_dir):
            if fn not in referenced:
                try:
                    os.remove(os.path.join(self._chunk_dir, fn))
                    removed.append(os.path.join(self._chunk_dir, fn))
                except FileNotFoundError:
                    pass
        if removed:
            # Prompt byte-reclaim only — cache keys are CRC-pinned, so a
            # stale entry could never be served wrongly, just held.
            readpipe.invalidate_files(removed)

    @property
    def rewrite_needed(self) -> bool:
        with self._data_lock:
            return self._rewrite_needed

    def journal_snapshot(self, gen: Optional[int] = None,
                         offset: int = 0) -> tuple:
        """Atomic journal snapshot for the store's mirror:
        ``(generation, total_size, data, is_delta)``.

        When ``gen`` matches the current generation, only bytes past
        ``offset`` are read and ``is_delta`` is True — the O(delta) path a
        per-chunk-checkpointing ingest needs (a full read per save would
        be O(total journal), quadratic across the ingest). Otherwise the
        whole journal is returned. Read under the data lock, so neither an
        eviction flush (journal append) nor an inline generation rewrite
        (journal *replacement*) can interleave: the returned bytes always
        end on a record boundary and belong to exactly the returned
        generation."""
        with self._data_lock:
            cur = self._gen
            data = b""
            if self._journal_path is not None:
                try:
                    with open(self._journal_path, "rb") as f:
                        if gen == cur and offset:
                            f.seek(offset)
                            data = f.read()
                            return cur, offset + len(data), data, True
                        data = f.read()
                except FileNotFoundError:
                    pass
            return cur, len(data), data, False

    def journal_size(self) -> tuple:
        """``(generation, journal_bytes)`` without reading the journal —
        the O(1) probe the store's replication lag accounting compares
        against per-peer acked watermarks."""
        with self._data_lock:
            size = 0
            if self._journal_path is not None:
                try:
                    size = os.path.getsize(self._journal_path)
                except OSError:
                    size = 0
            return self._gen, size

    def journal_files(self) -> List[str]:
        """Basenames of the chunk files the current state references —
        the store's GC/mirror source of truth."""
        with self._data_lock:
            return [os.path.basename(c.path) for c in self._chunks
                    if c.path is not None]

    def maybe_evict(self) -> None:
        with self._data_lock:
            self._maybe_evict_locked()

    def _maybe_evict_locked(self) -> None:
        """Drop in-memory chunk data (flushing first) until under budget.

        A pending rewrite (set_column) is committed inline first — flushing
        against the stale journal would corrupt recovery, and waiting for a
        store.save() that persist=False configurations never issue would
        disable the budget permanently.
        """
        if self._ram_budget is None or self._chunk_dir is None:
            return
        if self._rewrite_needed:
            self._rewrite_generation_locked()
        mem = sum(c.data_bytes for c in self._chunks if c.in_memory)
        if mem <= self._ram_budget:
            return
        # Pick victims first, then flush the unpersisted ones as ONE
        # journal batch (single fsync). Evict down to a low-water mark
        # (3/4 budget) rather than just under: appends trigger eviction
        # chunk-by-chunk, and without hysteresis a budgeted streaming
        # ingest would pay a journal fsync per appended chunk — the
        # low-water mark amortizes each fsync over budget/4 bytes.
        low_water = self._ram_budget - self._ram_budget // 4
        victims = []
        last_victim_idx = -1
        for idx, c in enumerate(self._chunks):
            if not c.in_memory or not c.evictable:
                continue
            victims.append(c)
            last_victim_idx = idx
            mem -= c.data_bytes
            if mem <= low_water:
                break
        # Journal IN APPEND ORDER: flush every still-unflushed chunk up to
        # the last victim — including skipped non-evictable ones (they
        # stay resident; flushing them here matches store.save semantics).
        # Journaling only the victims would write their records ahead of
        # earlier chunks', and restore_chunks trusts journal line order —
        # a restart would silently reorder the dataset's rows.
        records = [self._write_chunk_file_locked(c)
                   for c in self._chunks[:last_victim_idx + 1]
                   if c.path is None]
        self._commit_records_locked(records)
        for c in victims:
            c.cols = None
            c.arrow = None

    def restore_chunks(self, records: List[Dict[str, Any]],
                       chunk_dir: str) -> None:
        """Rebuild the chunk list from journal records (store.load) — data
        stays on disk until first access (lazy load). Files the journal no
        longer references (a crash orphaned a half-committed generation)
        are garbage-collected."""
        chunks = []
        max_gen, max_id = 0, -1
        for rec in records:
            dtypes = {f: np.dtype(dt) for f, dt in rec["dtypes"].items()}
            c = _Chunk.on_disk(
                os.path.join(chunk_dir, rec["file"]), rec["rows"], dtypes,
                rec.get("bytes", 0), src_off=rec.get("src_off"),
                crc32=rec.get("crc32"))
            c.verify = self._verify_chunk
            chunks.append(c)
            gen, cid = _parse_chunk_name(rec["file"])
            if (gen, cid) > (max_gen, max_id):
                max_gen, max_id = gen, cid
        with self._data_lock:
            self._chunks = chunks
            self._consolidated = None
            self._gen = max_gen
            self._next_chunk_id = max_id + 1
            self._journal_records = len(records)
            prev_dir = self._chunk_dir
            self._chunk_dir = chunk_dir
            self._gc_locked()
            self._chunk_dir = prev_dir

    def scrub_chunks(self) -> Dict[str, Any]:
        """Eagerly re-verify every journaled chunk file's checksum (the
        proactive integrity pass behind ``DatasetStore.scrub`` /
        ``POST /catalog/scrub``). Ignores the lazy ``_verified`` flag —
        a scrub re-reads every file so rot that set in *after* first
        read is still caught. Repair (replica mirror) runs exactly as on
        the lazy path; unrepairable chunks are reported, not raised, so
        one corrupt dataset doesn't abort a catalog-wide scrub."""
        with self._data_lock:
            chunks = [c for c in self._chunks if c.path is not None]
            # Register as an active reader for the pass: a concurrent
            # generation rewrite (set_column save / budget eviction)
            # must not GC this snapshot's files mid-verification —
            # deleted-under-us files would read as false corruption.
            self._active_readers += 1
        report: Dict[str, Any] = {"checked": 0, "unchecksummed": 0,
                                  "missing": 0, "errors": []}
        try:
            for c in chunks:
                present = os.path.isfile(c.path)
                if c.crc32 is None and present:
                    # Pre-checksum journal record: existence is all we
                    # can attest.
                    report["unchecksummed"] += 1
                    continue
                if not present:
                    # Whole file gone (re-imaged host / deleted chunks
                    # dir): reported distinctly, and verification below
                    # still runs so the repair ladder gets its shot.
                    report["missing"] += 1
                c._verified = False
                try:
                    self._verify_chunk(c)
                    report["checked"] += 1
                except ChunkCorrupt as exc:
                    report["errors"].append(str(exc))
        finally:
            self._release_reader()
        return report

    # -- reads --------------------------------------------------------------

    @property
    def num_rows(self) -> int:
        with self._data_lock:
            return sum(c.n_rows for c in self._chunks)

    @property
    def shard_map(self) -> Optional[dict]:
        """Ownership map a range-partitioned ingest recorded (owner host →
        contiguous row range, in global row order); None for datasets
        ingested serially or written locally. A placement hint only —
        reads never require it (non-local chunks stay reachable through
        the replicate.fetch_chunk repair path)."""
        return self.metadata.extra.get("shard_map")

    @property
    def resume_offset(self) -> Optional[int]:
        """Source-stream byte offset after the last committed ingest chunk
        — where an interrupted ingest resumes. None when the dataset has
        no offset-tracked chunks (non-ingest datasets, or journals written
        before offsets existed: those must not resume, they'd duplicate
        rows)."""
        with self._data_lock:
            if not self._chunks:
                return None
            off = self._chunks[-1].src_off
            return int(off) if off is not None else None

    def _total_bytes_locked(self) -> int:
        return sum(c.data_bytes for c in self._chunks)

    def _consolidate_locked(self) -> Columns:
        """Full materialization; caller must hold ``_data_lock``.

        Cached unless the dataset exceeds its RAM budget — over-budget
        datasets materialize transiently (dense trainers need the full
        design matrix on the way to the device) but the catalog's resident
        footprint stays bounded by the chunk tier.
        """
        if self._consolidated is not None:
            return self._consolidated
        if not self._chunks:
            self._consolidated = {}
            return self._consolidated
        fields = self.metadata.fields
        loaded = [c.materialize() for c in self._chunks]
        if len(loaded) == 1:
            cols = loaded[0]
        else:
            cols = {f: _concat([lc[f] for lc in loaded]) for f in fields}
        if (self._ram_budget is None
                or self._total_bytes_locked() <= self._ram_budget):
            self._consolidated = cols
            if len(self._chunks) > 1:
                # Don't keep two resident copies (per-chunk arrays + the
                # concatenation): purely-in-memory chunk lists merge into
                # one chunk sharing the consolidated arrays; chunks with
                # disk bookkeeping to preserve re-point their resident data
                # at *views* of the consolidation — same values (no drift,
                # no re-reads), one buffer.
                if (not self._rewrite_needed
                        and all(c.path is None for c in self._chunks)):
                    merged = _Chunk(cols)
                    # The merged chunk stands for all rows up to the last
                    # chunk's source offset — resume bookkeeping survives.
                    merged.src_off = self._chunks[-1].src_off
                    self._chunks = [merged]
                else:
                    offset = 0
                    for c in self._chunks:
                        end = offset + c.n_rows
                        c.cols = {f: cols[f][offset:end] for f in fields}
                        c.arrow = None  # views are authoritative now
                        c.dtypes = {f: cols[f].dtype for f in fields}
                        c._evictable = None
                        offset = end
        return cols

    @property
    def columns(self) -> Columns:
        """Consolidated column arrays (cached; invalidated by appends).

        The returned dict is an immutable snapshot: appends build a new
        consolidation rather than mutating these arrays, so callers can
        compute over it without holding the lock."""
        with self._data_lock:
            return self._consolidate_locked()

    def column(self, name: str) -> np.ndarray:
        return self.columns[name]

    def iter_chunks(self, fields: Optional[List[str]] = None,
                    max_chunks: Optional[int] = None,
                    prefetch: Optional[int] = None) -> Iterator[Columns]:
        """Stream the dataset chunk-by-chunk without full materialization —
        the out-of-core compute path (histogram, projection). Spilled
        chunks are read from their chunk files through the prefetching
        read pipeline: while the consumer computes on chunk i, a worker
        pool reads + verifies + decodes chunks i+1..i+K (``prefetch``;
        None = the dataset/process default, 0 = strictly synchronous —
        the parity oracle). Reads go through the shared LRU chunk cache,
        so a second pass over the same snapshot hits warm host RAM.

        Yielded chunks carry *unified* dtypes matching what full
        consolidation would produce: a field that is object (string) in any
        chunk is object in every yielded chunk (`_concat`'s rule), and
        mixed numeric dtypes promote to their ``np.result_type`` (so e.g. a
        column integral in early chunks and float later yields float keys
        everywhere, agreeing with ``value_counts`` on the same data).
        Prefetch never changes yield order or values: futures are consumed
        in submission order and coercion runs on the consumer thread, so
        the pipeline is bit-identical to the synchronous oracle. A worker
        failure (``ChunkCorrupt``, an armed failpoint) re-raises here, on
        the consumer, at the failed chunk's position.

        The snapshot registers as an active reader for its lifetime: chunk
        file GC (generation rewrites) defers until the iterator is
        exhausted or closed, so lazily-read files stay valid — in-flight
        prefetch reads are drained before the registration drops. This is
        a generator function — the snapshot and reader registration happen
        at the first ``next()``, so an iterator that is never started
        never leaks a reader count.

        ``max_chunks`` truncates the snapshot *before* dtype unification:
        the SPMD histogram pins a journaled chunk count so every pod
        process streams identical chunk boundaries AND identical unified
        dtypes even if extra chunks appended on one process since.
        """
        with self._data_lock:
            chunks = list(self._chunks)
            if max_chunks is not None:
                chunks = chunks[:max_chunks]
            self._active_readers += 1
        pipeline = _pipelined_materialize(
            chunks, fields,
            readpipe.prefetch_depth(
                prefetch if prefetch is not None else self._prefetch))
        try:
            coerce = self._make_coercer(chunks, fields)
            for _c, cols in pipeline:
                yield {f: coerce(f, a) for f, a in cols.items()}
        finally:
            # Drain the worker window BEFORE releasing the reader: a
            # deferred generation-rewrite GC must never delete a file a
            # still-running prefetch worker is reading.
            pipeline.close()
            self._release_reader()

    @staticmethod
    def _make_coercer(chunks, want):
        """Per-field dtype coercer unifying a chunk snapshot's dtypes to
        what full consolidation would produce (``iter_chunks``'s contract;
        shared with ``read_rows``)."""
        target: Dict[str, np.dtype] = {}
        seen: Dict[str, set] = {}
        for c in chunks:
            for f, dt in c.dtypes.items():
                if want is None or f in want:
                    seen.setdefault(f, set()).add(dt)
        for f, dts in seen.items():
            if len(dts) > 1:
                target[f] = (np.dtype(object)
                             if any(dt == object for dt in dts)
                             else np.result_type(*dts))
        # Numeric→object coercion stringifies only when the object
        # chunks hold strings (same rule as _concat); object chunks
        # already on disk are strings by construction.
        nonstringy = set()
        if any(t == object for t in target.values()):
            for c in chunks:
                ccols = c.cols
                if ccols is None:
                    continue
                for f, a in ccols.items():
                    if (target.get(f) == object and a.dtype == object
                            and not is_stringy(a)):
                        nonstringy.add(f)

        def _coerce(f: str, a: np.ndarray) -> np.ndarray:
            t = target.get(f)
            if t is None or a.dtype == t:
                return a
            if t != object:
                return a.astype(t)
            return (a.astype(object) if f in nonstringy
                    else stringify_numeric(a))

        return _coerce

    @contextlib.contextmanager
    def snapshot(self, max_chunks: Optional[int] = None):
        """Pin ONE chunk snapshot for multiple reads: every ``read``/
        ``scan`` through the yielded :class:`SnapshotReader` sees the same
        chunk generation, so a paged response evaluated block-by-block can
        never mix pre- and post-``set_column``-rewrite values. Registers
        as an active reader for its lifetime (chunk-file GC defers)."""
        with self._data_lock:
            chunks = list(self._chunks)
            if max_chunks is not None:
                chunks = chunks[:max_chunks]
            self._active_readers += 1
        try:
            yield SnapshotReader(self, chunks)
        finally:
            self._release_reader()

    def pin_snapshot(self) -> "SnapshotReader":
        """Long-lived form of :meth:`snapshot` for readers whose lifetime
        doesn't fit a ``with`` block — a :class:`~learningorchestra_tpu.
        ops.preprocess.ChunkedDesign` reads row ranges lazily for as long
        as a build holds it, and every one of those reads must see the
        same chunk generation (a concurrent ``set_column`` rewrite must
        never mix pre-/post-rewrite rows across fitting passes or device
        shards). The active-reader registration is released when the
        returned reader is garbage-collected, or eagerly via its
        ``release()``."""
        with self._data_lock:
            chunks = list(self._chunks)
            self._active_readers += 1
        reader = SnapshotReader(self, chunks)
        reader._finalizer = weakref.finalize(reader, self._release_reader)
        return reader

    def _release_reader(self) -> None:
        with self._data_lock:
            self._active_readers -= 1
            if self._pending_gc and not self._active_readers:
                self._gc_locked()

    def read_rows(self, fields: Optional[List[str]] = None,
                  start: int = 0, stop: Optional[int] = None,
                  max_chunks: Optional[int] = None) -> Columns:
        """Materialize ONLY the chunks overlapping rows ``[start, stop)``
        and return that row range — O(overlapping chunks) host memory, not
        O(dataset). This is the shard-local read the pod data path builds
        device shards from (each process reads just its own row ranges
        instead of consolidating the full dataset; contrast the
        reference's executors, which likewise hold only their partitions,
        model_builder.py:200). Dtypes are unified exactly as
        ``iter_chunks``/consolidation would, so a range read never sees
        chunk-local dtype drift."""
        with self.snapshot(max_chunks) as snap:
            return snap.read(fields, start, stop)

    @property
    def over_budget(self) -> bool:
        """True when column data exceeds the configured RAM budget — the
        signal for switching from full consolidation to the shard-local
        streamed design-matrix path (ops/preprocess.ChunkedDesign)."""
        with self._data_lock:
            return (self._ram_budget is not None
                    and self._total_bytes_locked() > self._ram_budget)

    #: Most derived artifacts kept per dataset (each can pin a full design
    #: matrix, so the cap bounds resident memory in long-lived servers).
    _MEMO_CAP = 4

    def memo(self, key, builder, token=None):
        """Cache a derived artifact (e.g. a design matrix) against the
        current consolidation snapshot; invalidated by appends/coercion.
        ``token`` adds an extra validity object compared by *identity*
        (e.g. the preprocessing state a test matrix was built with).

        Keeping the artifact's *identity* stable across repeated reads is
        what lets downstream identity-keyed caches hit — in particular the
        mesh runtime's host→device transfer cache, so a server fitting
        repeatedly on the same dataset re-uses the on-device copy instead
        of re-transferring gigabytes per build. Snapshots and tokens are
        stored and compared as objects (``is``), never as raw ``id()``
        integers — a recycled address must not resurrect a stale entry.
        Entries from superseded snapshots are purged, and the cache is
        size-capped, so invalidated design matrices don't pin memory for
        the dataset's lifetime. Over-budget (out-of-core) datasets never
        cache their consolidation, so nothing giant gets pinned for them
        either.
        """
        cols = self.columns  # consolidates; snapshot identity = validity
        with self._data_lock:
            current = self._consolidated is cols
            for k in [k for k, (snap, _, _) in self._memo.items()
                      if snap is not cols]:
                del self._memo[k]
            if current:
                hit = self._memo.get(key)
                if hit is not None and hit[1] is token:
                    return hit[2]
        val = builder()
        if current:
            with self._data_lock:
                if self._consolidated is cols:
                    self._memo[key] = (cols, token, val)
                    while len(self._memo) > self._MEMO_CAP:
                        del self._memo[next(iter(self._memo))]
        return val

    def rows(self, indices: np.ndarray) -> List[Dict[str, Any]]:
        """Materialize row documents (``_id`` = index+1) for the given
        0-based row indices — the read-back path (reference database.py:36-48)."""
        return rows_from(self.columns, self.metadata.fields, indices)

    def numeric_matrix(self, fields: Optional[List[str]] = None) -> np.ndarray:
        """Dense float32 design matrix over the given (default: all numeric)
        fields — the hand-off point from catalog to the TPU mesh."""
        cols = self.columns
        if fields is None:
            fields = [f for f in self.metadata.fields
                      if cols[f].dtype.kind in "ifub"]
        mats = []
        for f in fields:
            c = cols[f]
            if c.dtype.kind not in "ifub":
                raise TypeError(f"field {f!r} is not numeric (dtype {c.dtype})")
            mats.append(np.asarray(c, dtype=np.float32))
        if not mats:
            return np.zeros((self.num_rows, 0), dtype=np.float32)
        return np.stack(mats, axis=1)


# -- chunk parquet IO --------------------------------------------------------

def _fsync_file(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        # Durability helper, not a commit point: the two-phase commits
        # that CALL it carry the failpoint sites (write_chunk.pre_rename,
        # journal.pre_swap), so the crash sweep already brackets this.
        os.fsync(fd)  # lolint: disable=failpoint-coverage
    finally:
        os.close(fd)


def _fsync_dir(path: str) -> None:
    """Durably commit a rename: fsync the containing directory (POSIX —
    best-effort on filesystems that reject directory fds)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        # Same as _fsync_file: durability plumbing for commit points
        # that carry their own failpoint sites at the rename itself.
        os.fsync(fd)  # lolint: disable=failpoint-coverage
    except OSError:
        pass
    finally:
        os.close(fd)


def _parse_chunk_name(fname: str) -> tuple:
    """``GGG-NNNNN.arrow`` → (gen, id); legacy ``NNNNN.parquet`` → (0, id)."""
    stem = fname
    for ext in (".arrow", ".parquet"):
        if stem.endswith(ext):
            stem = stem[:-len(ext)]
            break
    parts = stem.split("-")
    try:
        if len(parts) == 2:
            return int(parts[0]), int(parts[1])
        return 0, int(parts[0])
    except ValueError:
        return 0, -1


def _cols_to_arrow_table(cols: Columns, fields: List[str]):
    """Columns → arrow table. Object columns serialize as nullable strings
    (non-string objects stringify — the store's value domain is
    numbers/strings/null, matching the reference's Mongo documents)."""
    import pyarrow as pa

    arrays, names = [], []
    for fname in fields:
        arr = cols[fname]
        if arr.dtype == object:
            arrays.append(pa.array([None if v is None else str(v)
                                    for v in arr], type=pa.string()))
        else:
            arrays.append(pa.array(arr))
        names.append(fname)
    return pa.table(arrays, names=names)


def write_chunk_arrow(path: str, cols: Columns, fields: List[str]) -> None:
    """Columns → Arrow IPC chunk file (uncompressed; see the chunk-format
    note in ``_write_chunk_file_locked``)."""
    _write_arrow_table(path, _cols_to_arrow_table(cols, fields))


def write_chunk_arrow_batch(path: str, batch) -> None:
    """RecordBatch → Arrow IPC chunk file, straight from its buffers."""
    import pyarrow as pa

    _write_arrow_table(path, pa.Table.from_batches([batch]))


def _write_arrow_table(path: str, table) -> None:
    import pyarrow.ipc as ipc

    with ipc.new_file(path, table.schema) as writer:
        writer.write_table(table)


def write_chunk_parquet(path: str, cols: Columns,
                        fields: List[str]) -> None:
    """Columns → parquet (legacy chunk format; kept for tooling/tests that
    exercise the .parquet read fallback)."""
    import pyarrow.parquet as pq

    pq.write_table(_cols_to_arrow_table(cols, fields), path)


def read_chunk_file(path: str,
                    fields: Optional[List[str]] = None) -> Columns:
    """Chunk file → Columns (string columns come back as object arrays
    with ``None`` for nulls, numerics as their numpy dtypes). Dispatches
    on extension: Arrow IPC for current files, parquet for chunks
    journaled by older builds."""
    if path.endswith(".parquet"):
        return read_chunk_parquet(path, fields)
    import pyarrow.ipc as ipc

    with ipc.open_file(path) as reader:
        table = reader.read_all()
    if fields is not None:
        table = table.select([f for f in fields
                              if f in table.column_names])
    return {fname: table.column(fname).to_numpy(zero_copy_only=False)
            for fname in table.column_names}


def read_chunk_parquet(path: str,
                       fields: Optional[List[str]] = None) -> Columns:
    """Legacy parquet chunk file → Columns.

    Read single-threaded without pre-buffering: chunk files are a few MB
    (decode parallelism would not pay for itself), and avoiding pyarrow's
    internal IO pool is defense-in-depth against the jax+pyarrow
    init-order hazard documented in catalog/__init__.py."""
    import pyarrow.parquet as pq

    table = pq.read_table(path, columns=fields, use_threads=False,
                          pre_buffer=False)
    cols: Columns = {}
    for fname in table.column_names:
        cols[fname] = table.column(fname).to_numpy(zero_copy_only=False)
    return cols


def is_stringy(a: np.ndarray) -> bool:
    """Whether an object column holds only str/None — the CSV value domain
    (as opposed to e.g. float scores with None gaps from ``append_rows``)."""
    return all(v is None or isinstance(v, str) for v in a)


def _concat(arrays: List[np.ndarray]) -> np.ndarray:
    """Concatenate column chunks, reconciling dtypes.

    Chunked parsing infers dtypes per chunk, so a column can arrive numeric
    in early chunks and object (string) later (e.g. 'N/A' first appears at
    row 70k). A whole-file parse would have made every value a string, so on
    conflict numeric values are stringified (ints exactly; NaN → None) to
    keep one consistent value domain for queries and value_counts. That
    rule only applies when the object chunks actually hold strings: object
    chunks carrying numbers (floats with None gaps) keep their numeric
    values and the numeric chunks join them as objects."""
    has_obj = any(a.dtype == object for a in arrays)
    if has_obj and any(a.dtype != object for a in arrays):
        if all(is_stringy(a) for a in arrays if a.dtype == object):
            arrays = [stringify_numeric(a) if a.dtype != object else a
                      for a in arrays]
        else:
            arrays = [a.astype(object) if a.dtype != object else a
                      for a in arrays]
    return np.concatenate(arrays)


def stringify_numeric(a: np.ndarray) -> np.ndarray:
    """Numeric column → object strings: NaN → None, integral floats print
    as ints. The single number→string value-domain rule, shared with the
    fieldtypes coercion op (ops/dtypes.py; reference
    data_type_handler.py:63-70)."""
    out = np.empty(len(a), dtype=object)
    is_float = a.dtype.kind == "f"
    for i, v in enumerate(a):
        if is_float and np.isnan(v):
            out[i] = None
        elif is_float and v == int(v):
            out[i] = str(int(v))
        else:
            out[i] = str(v)
    return out


def _pipelined_materialize(chunks: List["_Chunk"],
                           fields: Optional[List[str]],
                           depth: int):
    """Yield ``(chunk, columns)`` in chunk order, materializing up to
    ``depth`` chunks ahead on the shared readpipe worker pool — the
    asynchronous read pipeline under ``iter_chunks`` / ``scan``.

    ``depth <= 0`` (or a trivial snapshot) degenerates to the exact
    synchronous loop — the parity oracle. Otherwise a bounded sliding
    window of futures keeps at most ``depth`` reads in flight; results
    are consumed strictly in submission order, so chunk order (and
    therefore SPMD device-op alignment) is deterministic, and a worker
    exception re-raises on the consumer thread at the failed chunk's
    position instead of hanging the stream. On close/abandonment the
    window is cancelled and in-flight reads are waited out, so callers
    can safely drop reader registrations (chunk-file GC) afterwards."""
    t0 = time.monotonic()
    hits0, misses0 = readpipe.cache_probe()
    produced = 0
    window: deque = deque()          # (chunk, future), submission order
    try:
        if depth <= 0 or len(chunks) <= 1:
            for c in chunks:
                yield c, c.materialize(fields)
                produced += 1
            return
        pool = readpipe.pool()
        nxt = 0
        while nxt < len(chunks) and len(window) < depth:
            c = chunks[nxt]
            nxt += 1
            window.append((c, pool.submit(c.materialize, fields)))
        while window:
            c, fut = window.popleft()
            if not fut.done():
                readpipe.bump("prefetch_stalls")
            try:
                cols = fut.result()
            except BaseException:
                readpipe.bump("worker_errors")
                raise
            readpipe.bump("prefetched_chunks")
            if nxt < len(chunks):
                c2 = chunks[nxt]
                nxt += 1
                window.append((c2, pool.submit(c2.materialize, fields)))
            yield c, cols
            produced += 1
    finally:
        for _c, fut in window:
            fut.cancel()
        for _c, fut in window:
            if not fut.cancelled():
                try:
                    fut.result()
                except BaseException:  # noqa: BLE001 — result discarded
                    pass
        # One span per scan (not per chunk), covering first-next →
        # exhaustion/close on the consumer thread — the read-pipeline
        # leg of a traced job's time. No-op without an ambient trace.
        # Cache traffic is a global-counter delta: exact for a lone
        # scan, approximate while scans overlap.
        hits1, misses1 = readpipe.cache_probe()
        tracing.record_span(
            "readpipe.materialize", time.monotonic() - t0,
            attrs={"chunks": produced, "snapshot_chunks": len(chunks),
                   "depth": depth, "cache_hits": hits1 - hits0,
                   "cache_misses": misses1 - misses0})


class SnapshotReader:
    """Row reads over one pinned chunk snapshot (``Dataset.snapshot``).

    All reads through one instance see the same chunk generation —
    ``set_column`` rewrites replace the dataset's chunk list, but never
    this captured one (the enclosing context's active-reader registration
    keeps the chunk files alive). Coercers are cached per field-selection
    so repeated scans/reads don't re-derive dtype unification."""

    def __init__(self, ds: "Dataset", chunks: List["_Chunk"]):
        self._ds = ds
        self._chunks = chunks
        self.n_rows = sum(c.n_rows for c in chunks)
        self._coercers: Dict[Any, Any] = {}
        #: Set by Dataset.pin_snapshot; context-managed snapshots release
        #: through their ``with`` block instead.
        self._finalizer = None

    def release(self) -> None:
        """Eagerly release a pinned snapshot (``Dataset.pin_snapshot``);
        idempotent, and a no-op for context-managed snapshots."""
        if self._finalizer is not None:
            self._finalizer()

    def _coercer(self, fields: Optional[List[str]]):
        key = None if fields is None else tuple(fields)
        got = self._coercers.get(key)
        if got is None:
            got = Dataset._make_coercer(self._chunks, fields)
            self._coercers[key] = got
        return got

    def read(self, fields: Optional[List[str]], start: int,
             stop: Optional[int]) -> Columns:
        """Rows ``[start, stop)`` — materializes only overlapping chunks,
        slicing before coercion (O(range), not O(chunk))."""
        coerce = self._coercer(fields)
        stop = self.n_rows if stop is None else min(stop, self.n_rows)
        start = max(0, min(start, stop))
        parts: List[Columns] = []
        off = 0
        for c in self._chunks:
            end = off + c.n_rows
            if end > start and off < stop:
                cols = c.materialize(fields)
                lo, hi = max(start - off, 0), min(stop - off, c.n_rows)
                parts.append({f: coerce(f, a[lo:hi])
                              for f, a in cols.items()})
            off = end
            if off >= stop:
                break
        if not parts:
            flds = (fields if fields is not None
                    else list(self._ds.metadata.fields))
            dts = {f: dt for c in self._chunks
                   for f, dt in c.dtypes.items()}
            # Coerce the empties too, so an empty page carries the same
            # unified dtypes as any non-empty read.
            return {f: coerce(f, np.empty(0, dtype=dts.get(f, object)))
                    for f in flds}
        if len(parts) == 1:
            return parts[0]
        return {f: _concat([p[f] for p in parts]) for f in parts[0]}

    def scan(self, fields: Optional[List[str]] = None,
             block_rows: int = 1 << 16, prefetch: Optional[int] = None):
        """Yield ``(offset, n_block, cols)`` row blocks over the snapshot
        — each chunk materialized once, split into ≤``block_rows`` pieces.
        ``fields`` projects columns (a filtered read scans only the
        query's fields); ``cols`` may be empty when ``fields`` is, which
        is why the block length is yielded explicitly. Chunks stream
        through the prefetching read pipeline (next chunks read/decoded
        by workers while the consumer computes on this one; ``prefetch``
        None = the dataset/process default, 0 = synchronous oracle) and
        the shared chunk cache, so a second scan of the same snapshot —
        the fused streamed-fit's second pass — hits warm host RAM."""
        coerce = self._coercer(fields)
        off = 0
        pipeline = _pipelined_materialize(
            self._chunks, fields,
            readpipe.prefetch_depth(
                prefetch if prefetch is not None else self._ds._prefetch))
        try:
            for c, cols in pipeline:
                for s in range(0, c.n_rows, block_rows):
                    e = min(s + block_rows, c.n_rows)
                    yield (off + s, e - s,
                           {f: coerce(f, a[s:e]) for f, a in cols.items()})
                off += c.n_rows
        finally:
            # Abandoned scans (a filtered read that early-outs) must
            # drain in-flight prefetch reads before the enclosing
            # snapshot's reader registration can release.
            pipeline.close()


def rows_from(cols: Columns, fields: List[str], indices: np.ndarray,
              id_offset: int = 0) -> List[Dict[str, Any]]:
    """Materialize row docs from a column snapshot (lock-free).
    ``id_offset`` shifts ``_id`` for block-streamed reads, where ``cols``
    holds a row range starting at that global offset."""
    out = []
    for i in indices:
        doc = {"_id": int(i) + 1 + id_offset}
        for f in fields:
            doc[f] = _pyval(cols[f][i])
        out.append(doc)
    return out


def _pyval(v):
    """numpy scalar → plain Python (JSON-serializable) value."""
    if isinstance(v, np.generic):
        v = v.item()
    if isinstance(v, float) and v != v:  # NaN → null in JSON
        return None
    return v
