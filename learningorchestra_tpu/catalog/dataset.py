"""Named-dataset model: columnar data + the metadata/lineage contract.

The reference's universal data plane is "one Mongo collection per file" where
document ``_id: 0`` is a metadata doc ``{filename, url|parent_filename,
time_created, finished, fields}`` and rows are ``_id: 1..N`` in CSV order
(reference database.py:157-168,205-213; docs/database_api.md:3-77). The
``finished`` flag flipping false→true is the system-wide async-completion
signal the client polls (database.py:177-181), and ``parent_filename``
records lineage for derived datasets.

This module keeps that *contract* — names, metadata-doc shape, finished-flag
semantics, row ``_id`` numbering — over a TPU-friendly *mechanism*: columns
are contiguous numpy arrays (zero-copy into ``jax.numpy``/device shards)
instead of per-row BSON documents.

Upgrade over the reference: a mid-flight crash in the reference leaves
``finished: false`` forever and clients poll infinitely (SURVEY.md §5); here
metadata carries an ``error`` field that job runners set on failure so
clients can fail fast.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

#: Columns are numpy arrays: numeric dtypes or ``object`` for strings/mixed.
Columns = Dict[str, np.ndarray]


@dataclass
class Metadata:
    """The ``_id: 0`` metadata document of a dataset."""

    name: str
    url: Optional[str] = None           # source URL for ingested datasets
    parent: Optional[str] = None        # lineage: parent dataset name
    time_created: str = ""
    finished: bool = False
    fields: List[str] = field(default_factory=list)
    error: Optional[str] = None         # set when an async job failed
    extra: Dict[str, Any] = field(default_factory=dict)  # e.g. model metrics

    def __post_init__(self):
        if not self.time_created:
            # Same human-readable stamp style as the reference
            # (database.py:206: time.strftime("%Y-%m-%d %H:%M:%S")).
            self.time_created = time.strftime("%Y-%m-%d %H:%M:%S")

    def to_doc(self) -> Dict[str, Any]:
        """Render as the reference-shaped metadata document (``_id: 0``)."""
        doc: Dict[str, Any] = {"_id": 0, "filename": self.name}
        if self.url is not None:
            doc["url"] = self.url
        if self.parent is not None:
            doc["parent_filename"] = self.parent
        doc["time_created"] = self.time_created
        doc["finished"] = self.finished
        doc["fields"] = list(self.fields)
        if self.error is not None:
            doc["error"] = self.error
        doc.update(self.extra)
        return doc

    @classmethod
    def from_doc(cls, doc: Dict[str, Any]) -> "Metadata":
        known = {"_id", "filename", "url", "parent_filename", "time_created",
                 "finished", "fields", "error"}
        return cls(
            name=doc["filename"],
            url=doc.get("url"),
            parent=doc.get("parent_filename"),
            time_created=doc.get("time_created", ""),
            finished=bool(doc.get("finished", False)),
            fields=list(doc.get("fields", [])),
            error=doc.get("error"),
            extra={k: v for k, v in doc.items() if k not in known},
        )


class Dataset:
    """A named columnar dataset with reference-compatible row addressing.

    Rows are addressed ``_id = 1..N`` in insertion order; ``_id = 0`` is the
    metadata document. Appends are amortized O(1) via chunked column buffers
    so streaming CSV ingestion never re-copies the whole table per chunk.
    """

    def __init__(self, metadata: Metadata, columns: Optional[Columns] = None):
        self.metadata = metadata
        # Guards _chunks/_consolidated: ingestion appends from a job thread
        # while readers poll/consolidate the same dataset.
        self._data_lock = threading.Lock()
        self._chunks: List[Columns] = []
        self._consolidated: Optional[Columns] = None
        if columns:
            self.append_columns(columns)

    # -- writes -------------------------------------------------------------

    def append_columns(self, columns: Columns) -> None:
        """Append a chunk of rows given as equal-length column arrays."""
        if not columns:
            return
        lengths = {len(v) for v in columns.values()}
        if len(lengths) != 1:
            raise ValueError(f"ragged column chunk: {lengths}")
        cols = {k: np.asarray(v) for k, v in columns.items()}
        if not self.metadata.fields:
            self.metadata.fields = list(cols.keys())
        elif list(cols.keys()) != self.metadata.fields:
            missing = set(self.metadata.fields) - set(cols.keys())
            extra = set(cols.keys()) - set(self.metadata.fields)
            if missing or extra:
                raise ValueError(
                    f"chunk fields mismatch: missing={missing} extra={extra}")
            cols = {k: cols[k] for k in self.metadata.fields}  # reorder
        with self._data_lock:
            self._chunks.append(cols)
            self._consolidated = None

    def append_rows(self, rows: List[Dict[str, Any]]) -> None:
        """Append row dicts (used by result writers, e.g. predictions)."""
        if not rows:
            return
        fields = self.metadata.fields or list(rows[0].keys())
        cols: Columns = {}
        for f in fields:
            vals = [r.get(f) for r in rows]
            arr = np.asarray(vals)
            if arr.dtype.kind == "U":  # keep strings as object for None-safety
                arr = np.asarray(vals, dtype=object)
            cols[f] = arr
        self.append_columns(cols)

    def set_column(self, name: str, values: np.ndarray) -> None:
        """Replace/add a full column (used by type coercion). Atomic:
        snapshot, length-check, and replacement all happen under the data
        lock so a concurrent append can never be silently dropped."""
        values = np.asarray(values)
        with self._data_lock:
            cols = dict(self._consolidate_locked())
            n = len(next(iter(cols.values()))) if cols else 0
            if n and len(values) != n:
                raise ValueError(
                    f"column length {len(values)} != num_rows {n}")
            cols[name] = values
            if name not in self.metadata.fields:
                self.metadata.fields.append(name)
            self._chunks = [{f: cols[f] for f in self.metadata.fields}]
            self._consolidated = self._chunks[0]

    # -- reads --------------------------------------------------------------

    @property
    def num_rows(self) -> int:
        with self._data_lock:
            return sum(len(next(iter(c.values()))) for c in self._chunks)

    def _consolidate_locked(self) -> Columns:
        """Consolidate chunks; caller must hold ``_data_lock``."""
        if self._consolidated is None:
            if not self._chunks:
                self._consolidated = {}
            elif len(self._chunks) == 1:
                self._consolidated = self._chunks[0]
            else:
                fields = self.metadata.fields
                self._consolidated = {
                    f: _concat([c[f] for c in self._chunks])
                    for f in fields}
                self._chunks = [self._consolidated]
        return self._consolidated

    @property
    def columns(self) -> Columns:
        """Consolidated column arrays (cached; invalidated by appends).

        The returned dict is an immutable snapshot: appends build a new
        consolidation rather than mutating these arrays, so callers can
        compute over it without holding the lock."""
        with self._data_lock:
            return self._consolidate_locked()

    def column(self, name: str) -> np.ndarray:
        return self.columns[name]

    def rows(self, indices: np.ndarray) -> List[Dict[str, Any]]:
        """Materialize row documents (``_id`` = index+1) for the given
        0-based row indices — the read-back path (reference database.py:36-48)."""
        return rows_from(self.columns, self.metadata.fields, indices)

    def numeric_matrix(self, fields: Optional[List[str]] = None) -> np.ndarray:
        """Dense float32 design matrix over the given (default: all numeric)
        fields — the hand-off point from catalog to the TPU mesh."""
        cols = self.columns
        if fields is None:
            fields = [f for f in self.metadata.fields
                      if cols[f].dtype.kind in "ifub"]
        mats = []
        for f in fields:
            c = cols[f]
            if c.dtype.kind not in "ifub":
                raise TypeError(f"field {f!r} is not numeric (dtype {c.dtype})")
            mats.append(np.asarray(c, dtype=np.float32))
        if not mats:
            return np.zeros((self.num_rows, 0), dtype=np.float32)
        return np.stack(mats, axis=1)


def _concat(arrays: List[np.ndarray]) -> np.ndarray:
    """Concatenate column chunks, reconciling dtypes.

    Chunked parsing infers dtypes per chunk, so a column can arrive numeric
    in early chunks and object (string) later (e.g. 'N/A' first appears at
    row 70k). A whole-file parse would have made every value a string, so on
    conflict numeric values are stringified (ints exactly; NaN → None) to
    keep one consistent value domain for queries and value_counts."""
    has_obj = any(a.dtype == object for a in arrays)
    if has_obj and any(a.dtype != object for a in arrays):
        arrays = [stringify_numeric(a) if a.dtype != object else a
                  for a in arrays]
    elif has_obj:
        arrays = [a.astype(object) for a in arrays]
    return np.concatenate(arrays)


def stringify_numeric(a: np.ndarray) -> np.ndarray:
    """Numeric column → object strings: NaN → None, integral floats print
    as ints. The single number→string value-domain rule, shared with the
    fieldtypes coercion op (ops/dtypes.py; reference
    data_type_handler.py:63-70)."""
    out = np.empty(len(a), dtype=object)
    is_float = a.dtype.kind == "f"
    for i, v in enumerate(a):
        if is_float and np.isnan(v):
            out[i] = None
        elif is_float and v == int(v):
            out[i] = str(int(v))
        else:
            out[i] = str(v)
    return out


def rows_from(cols: Columns, fields: List[str],
              indices: np.ndarray) -> List[Dict[str, Any]]:
    """Materialize row docs from a column snapshot (lock-free)."""
    out = []
    for i in indices:
        doc = {"_id": int(i) + 1}
        for f in fields:
            doc[f] = _pyval(cols[f][i])
        out.append(doc)
    return out


def _pyval(v):
    """numpy scalar → plain Python (JSON-serializable) value."""
    if isinstance(v, np.generic):
        v = v.item()
    if isinstance(v, float) and v != v:  # NaN → null in JSON
        return None
    return v
