from learningorchestra_tpu.catalog.dataset import Dataset, Metadata  # noqa: F401
from learningorchestra_tpu.catalog.store import DatasetStore  # noqa: F401
