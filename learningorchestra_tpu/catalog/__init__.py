# Import pyarrow eagerly, on the thread that first imports the catalog
# (the process main thread in every real entrypoint). Deferring it can be
# fatal: if pyarrow's first import happens on a worker thread of a
# jax-loaded process (e.g. an ingest parse-pool thread hitting a lazy
# `import pyarrow` in catalog.native), its static initialization corrupts
# the process and a later `pq.read_table` segfaults — reproduced
# deterministically (4/4 with worker-thread import, 0/4 with main-thread
# import) on this image's jax+pyarrow pairing.
import pyarrow  # noqa: F401
import pyarrow.parquet  # noqa: F401

from learningorchestra_tpu.catalog.dataset import (  # noqa: F401,E402
    ChunkCorrupt, Dataset, Metadata)
from learningorchestra_tpu.catalog.store import DatasetStore  # noqa: F401,E402
