"""Peer-host replication plane for the chunk store.

``ReplicaClient``/``ReplicaServer`` speak length-prefixed frames over the
same transport-agnostic framing as serving/rowchannel.py (u32 header-len,
u32 payload-len, JSON header, raw payload). Four frame kinds:

==============  ========================================================
frame           meaning
==============  ========================================================
push_chunk      primary -> peer: one chunk file's bytes; header carries
                the journal CRC32 and the peer refuses bytes that don't
                match it (a replica never *accepts* unjournaled bytes)
journal_sync    primary -> peer: a committed journal prefix (delta append
                or full rewrite) + metadata doc; the peer verifies every
                referenced chunk file against its journal CRC before
                committing, so the replica is always a consistent prefix
fetch_chunk     any host -> peer: chunk bytes back out for remote repair;
                the peer re-CRCs the file before replying (a replica
                never *serves* bytes that don't match the journal) and
                the fetching side verifies again on receipt
scrub_probe     primary -> peer: which of these (file, crc) pairs do you
                hold intact? Used to resume a full sync without
                re-pushing bytes the peer already has
==============  ========================================================

Layering: this module sits beside dataset.py (it imports only the chunk
CRC helpers and the shared framing) — store.py owns the policy of *when*
to push and *where* repairs come from.

Sharded (range-partition-ingested) datasets need nothing extra from this
plane: the shard map lives in ``metadata.extra``, so it rides the
``journal_sync`` metadata doc to every peer, and a host reading rows it
doesn't own locally fetches them through the same ``fetch_chunk`` frames
remote repair uses — placement (parallel/mesh.py) is a hint layered on
top, never a correctness dependency.
"""

from __future__ import annotations

import json
import os
import re
import socket
import threading
import zlib
from typing import Any, Dict, List, Optional, Sequence, Tuple

from learningorchestra_tpu.catalog.dataset import _fsync_dir, crc32_file
from learningorchestra_tpu.serving.rowchannel import (
    ChannelProtocolError,
    pack_frame,
    recv_frame,
)
from learningorchestra_tpu.utils import failpoints
from learningorchestra_tpu.utils.structlog import get_logger

log = get_logger("catalog.replicate")

#: Chaos sites for the crash-sweep harness (tests/test_failpoints.py).
#: push.* fire on the primary's send side, fetch.* on the repair side,
#: serve.* on the peer — pre_commit before a received file/journal is
#: renamed into place, pre_reply before any reply frame leaves.
FP_PUSH_PRE_SEND = failpoints.declare("replicate.push.pre_send")
FP_PUSH_MID_STREAM = failpoints.declare("replicate.push.mid_stream")
FP_FETCH_PRE_READ = failpoints.declare("replicate.fetch.pre_read")
FP_SERVE_PRE_COMMIT = failpoints.declare("replicate.serve.pre_commit")
FP_SERVE_PRE_REPLY = failpoints.declare("replicate.serve.pre_reply")

_NAME_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9_.\-]*$")


class ReplicaError(RuntimeError):
    """A peer rejected a frame or the exchange failed mid-flight."""


def parse_peers(spec: str) -> List[str]:
    """``"hostA:9401, hostB:9401"`` -> ``["hostA:9401", "hostB:9401"]``."""
    peers = []
    for tok in (spec or "").split(","):
        tok = tok.strip()
        if not tok:
            continue
        if ":" not in tok:
            raise ValueError(f"replica peer {tok!r} is not host:port")
        peers.append(tok)
    return peers


def _split_addr(addr: str) -> Tuple[str, int]:
    host, _, port = addr.rpartition(":")
    return host, int(port)


def _safe_name(name: str) -> str:
    if not isinstance(name, str) or not _NAME_RE.match(name) or ".." in name:
        raise ReplicaError(f"invalid dataset name {name!r}")
    return name


def _safe_file(fname: str) -> str:
    if (
        not isinstance(fname, str)
        or not fname
        or fname != os.path.basename(fname)
        or fname.startswith(".")
    ):
        raise ReplicaError(f"invalid chunk file name {fname!r}")
    return fname


def _parse_journal(data: bytes) -> List[Dict[str, Any]]:
    """Journal bytes -> records, tolerating a torn final line (same
    discipline as the store's recovery parser: everything before the
    first undecodable line is the valid prefix)."""
    records: List[Dict[str, Any]] = []
    for line in data.decode("utf-8", errors="replace").splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            records.append(json.loads(line))
        except json.JSONDecodeError:
            break
    return records


# -- client ------------------------------------------------------------------


class ReplicaClient:
    """One connection to a peer ReplicaServer. Not thread-safe; the push
    committer and each repair attempt open their own short-lived client."""

    def __init__(self, addr: str, timeout_s: float = 10.0):
        self.addr = addr
        host, port = _split_addr(addr)
        self._sock = socket.create_connection((host, port), timeout=timeout_s)
        self._sock.settimeout(timeout_s)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self) -> "ReplicaClient":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()

    def _reply(self) -> Tuple[Dict[str, Any], bytes]:
        got = recv_frame(self._sock)
        if got is None:
            raise ReplicaError(f"peer {self.addr} closed mid-exchange")
        header, payload = got
        if header.get("kind") == "error":
            raise ReplicaError(
                f"peer {self.addr}: {header.get('message', 'unknown error')}"
            )
        return header, payload

    def push_chunk(
        self, dataset: str, fname: str, crc32: Optional[int], data: bytes
    ) -> None:
        """Send one chunk file; the peer refuses it on CRC mismatch."""
        failpoints.fire(FP_PUSH_PRE_SEND, path=fname)
        self._sock.sendall(
            pack_frame(
                {
                    "kind": "push_chunk",
                    "dataset": dataset,
                    "file": fname,
                    "crc32": crc32,
                },
                data,
            )
        )
        self._reply()

    def journal_sync(
        self,
        dataset: str,
        generation: int,
        offset: int,
        data: bytes,
        is_delta: bool,
        meta: Optional[Dict[str, Any]] = None,
    ) -> int:
        """Commit a journal prefix on the peer. Returns the peer's new
        journal size (the acked watermark). ``offset`` is the size the
        peer must currently hold for a delta append to be legal."""
        failpoints.fire(FP_PUSH_MID_STREAM, path=dataset)
        self._sock.sendall(
            pack_frame(
                {
                    "kind": "journal_sync",
                    "dataset": dataset,
                    "generation": generation,
                    "offset": offset,
                    "is_delta": bool(is_delta),
                    "meta": meta,
                },
                data,
            )
        )
        header, _ = self._reply()
        return int(header.get("size", 0))

    def fetch_chunk(
        self, dataset: str, fname: str, crc32: Optional[int]
    ) -> bytes:
        """Fetch chunk bytes for remote repair; both ends CRC-verify."""
        failpoints.fire(FP_FETCH_PRE_READ, path=fname)
        self._sock.sendall(
            pack_frame(
                {
                    "kind": "fetch_chunk",
                    "dataset": dataset,
                    "file": fname,
                    "crc32": crc32,
                }
            )
        )
        header, payload = self._reply()
        actual = zlib.crc32(payload) & 0xFFFFFFFF
        expected = crc32 if crc32 is not None else header.get("crc32")
        if expected is not None and actual != expected:
            raise ReplicaError(
                f"peer {self.addr} served {dataset}/{fname} with crc "
                f"{actual}, expected {expected}"
            )
        return payload

    def scrub_probe(
        self, dataset: str, files: Sequence[Tuple[str, Optional[int]]]
    ) -> List[str]:
        """Which of these (file, crc32) pairs does the peer hold intact?
        Part of the push path (full-sync resume), hence the push site."""
        failpoints.fire(FP_PUSH_PRE_SEND, path=dataset)
        self._sock.sendall(
            pack_frame(
                {
                    "kind": "scrub_probe",
                    "dataset": dataset,
                    "files": [
                        {"file": f, "crc32": c} for f, c in files
                    ],
                }
            )
        )
        header, _ = self._reply()
        have = header.get("have", [])
        return [str(f) for f in have] if isinstance(have, list) else []


# -- server ------------------------------------------------------------------


class ReplicaServer:
    """Receive side of the replication plane. Stores peers' datasets
    under ``root/<dataset>/{chunks,journal.jsonl,metadata.json}`` — the
    same layout as a replica_root mirror, so load_all()'s replica-restore
    path and _repair_chunk's local rung work against it unchanged.
    ``extra_roots`` (typically the host's primary store_root) are
    consulted read-only by fetch_chunk, so a peer can also heal from
    datasets this host natively owns."""

    def __init__(
        self,
        root: str,
        host: str = "127.0.0.1",
        port: int = 0,
        extra_roots: Sequence[str] = (),
        timeout_s: float = 30.0,
    ):
        self.root = root
        self.extra_roots = [r for r in extra_roots if r]
        os.makedirs(root, exist_ok=True)
        self._timeout_s = timeout_s
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {
            "pushes": 0,
            "push_bytes": 0,
            "journal_syncs": 0,
            "fetches": 0,
            "probes": 0,
            "errors": 0,
        }
        self._conns: List[socket.socket] = []
        self._stopped = False
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(32)
        self.host, self.port = self._listener.getsockname()[:2]
        # thread-lifecycle: owner=ReplicaServer exit=stop() closes the
        # listener, which breaks accept() with OSError.
        self._accept_thread = threading.Thread(
            target=self._accept_loop,
            name="lo-replica-accept",
            daemon=True,
        )
        self._accept_thread.start()
        log.info("replica server listening on %s:%d (root %s)",
                 self.host, self.port, root)

    @property
    def addr(self) -> str:
        return f"{self.host}:{self.port}"

    def _bump(self, key: str, n: int = 1) -> None:
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + n

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "addr": self.addr,
                "root": self.root,
                "connections": len(self._conns),
                "counters": dict(self._counters),
            }

    def _accept_loop(self) -> None:
        while not self._stopped:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return  # listener closed by stop()
            conn.settimeout(self._timeout_s)
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                if self._stopped:
                    conn.close()
                    return
                self._conns.append(conn)
            # thread-lifecycle: owner=ReplicaServer exit=peer disconnect
            # (recv_frame -> None) or stop() closing the socket.
            t = threading.Thread(
                target=self._serve_conn,
                args=(conn,),
                name="lo-replica-conn",
                daemon=True,
            )
            t.start()

    def _serve_conn(self, conn: socket.socket) -> None:
        """One frame at a time per connection; replication is a
        sequential protocol, so no handler pool is needed."""
        try:
            while True:
                try:
                    got = recv_frame(conn)
                except (ChannelProtocolError, OSError):
                    return
                if got is None:
                    return  # clean EOF
                header, payload = got
                try:
                    reply_header, reply_payload = self._handle(
                        header, payload
                    )
                except ReplicaError as exc:
                    self._bump("errors")
                    reply_header, reply_payload = (
                        {"kind": "error", "message": str(exc)},
                        b"",
                    )
                except Exception as exc:  # noqa: BLE001 - reply then drop
                    self._bump("errors")
                    log.warning("replica %s handler failed: %r",
                                header.get("kind"), exc)
                    reply_header, reply_payload = (
                        {"kind": "error", "message": repr(exc)},
                        b"",
                    )
                failpoints.fire(FP_SERVE_PRE_REPLY,
                                path=str(header.get("file", "")))
                try:
                    conn.sendall(pack_frame(reply_header, reply_payload))
                except OSError:
                    return
        finally:
            with self._lock:
                if conn in self._conns:
                    self._conns.remove(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _handle(
        self, header: Dict[str, Any], payload: bytes
    ) -> Tuple[Dict[str, Any], bytes]:
        kind = header.get("kind")
        if kind == "push_chunk":
            return self._handle_push(header, payload)
        if kind == "journal_sync":
            return self._handle_journal(header, payload)
        if kind == "fetch_chunk":
            return self._handle_fetch(header)
        if kind == "scrub_probe":
            return self._handle_probe(header)
        raise ReplicaError(f"unknown frame kind {kind!r}")

    def _dataset_dir(self, name: str) -> str:
        return os.path.join(self.root, _safe_name(name))

    def _handle_push(
        self, header: Dict[str, Any], payload: bytes
    ) -> Tuple[Dict[str, Any], bytes]:
        name = _safe_name(str(header.get("dataset")))
        fname = _safe_file(str(header.get("file")))
        crc = header.get("crc32")
        actual = zlib.crc32(payload) & 0xFFFFFFFF
        if crc is not None and actual != crc:
            # Never accept bytes that don't match the journal CRC.
            raise ReplicaError(
                f"push_chunk {name}/{fname}: payload crc {actual} does "
                f"not match journal crc {crc}"
            )
        chunk_dir = os.path.join(self._dataset_dir(name), "chunks")
        os.makedirs(chunk_dir, exist_ok=True)
        dst = os.path.join(chunk_dir, fname)
        tmp = dst + ".push"
        with open(tmp, "wb") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        failpoints.fire(FP_SERVE_PRE_COMMIT, path=tmp)
        os.replace(tmp, dst)
        _fsync_dir(chunk_dir)
        self._bump("pushes")
        self._bump("push_bytes", len(payload))
        return {"kind": "ok", "crc32": actual}, b""

    def _handle_journal(
        self, header: Dict[str, Any], payload: bytes
    ) -> Tuple[Dict[str, Any], bytes]:
        name = _safe_name(str(header.get("dataset")))
        offset = int(header.get("offset", 0))
        is_delta = bool(header.get("is_delta"))
        ddir = self._dataset_dir(name)
        chunk_dir = os.path.join(ddir, "chunks")
        os.makedirs(chunk_dir, exist_ok=True)
        jpath = os.path.join(ddir, "journal.jsonl")
        try:
            cur_size = os.path.getsize(jpath)
        except OSError:
            cur_size = 0
        if is_delta and cur_size != offset:
            raise ReplicaError(
                f"journal_sync {name}: delta offset {offset} does not "
                f"match replica journal size {cur_size}"
            )
        # A replica never accepts a journal whose records it cannot back
        # with matching bytes: verify every newly referenced chunk file.
        for rec in _parse_journal(payload):
            fname = rec.get("file")
            if not fname:
                continue
            path = os.path.join(chunk_dir, _safe_file(str(fname)))
            crc = rec.get("crc32")
            if not os.path.isfile(path):
                raise ReplicaError(
                    f"journal_sync {name}: referenced chunk {fname} was "
                    f"never pushed"
                )
            if crc is not None and crc32_file(path) != crc:
                raise ReplicaError(
                    f"journal_sync {name}: chunk {fname} does not match "
                    f"journal crc {crc}"
                )
        if is_delta:
            with open(jpath, "ab") as f:
                f.write(payload)
                f.flush()
                failpoints.fire(FP_SERVE_PRE_COMMIT, path=jpath)
                os.fsync(f.fileno())
            new_size = cur_size + len(payload)
        else:
            tmp = jpath + ".sync"
            with open(tmp, "wb") as f:
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())
            failpoints.fire(FP_SERVE_PRE_COMMIT, path=tmp)
            os.replace(tmp, jpath)
            _fsync_dir(ddir)
            new_size = len(payload)
            # GC replica chunk files the new journal no longer references
            # (a generation rewrite on the primary shrank the set).
            referenced = {
                rec["file"]
                for rec in _parse_journal(payload)
                if rec.get("file")
            }
            for fname in os.listdir(chunk_dir):
                if fname.endswith(".push"):
                    continue
                if fname not in referenced:
                    try:
                        os.remove(os.path.join(chunk_dir, fname))
                    except OSError:
                        pass
        meta = header.get("meta")
        if isinstance(meta, dict):
            mpath = os.path.join(ddir, "metadata.json")
            tmp = mpath + ".sync"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(meta, f)
            os.replace(tmp, mpath)
        self._bump("journal_syncs")
        return {"kind": "ok", "size": new_size}, b""

    def _handle_fetch(
        self, header: Dict[str, Any]
    ) -> Tuple[Dict[str, Any], bytes]:
        name = _safe_name(str(header.get("dataset")))
        fname = _safe_file(str(header.get("file")))
        expected = header.get("crc32")
        roots = [self.root] + self.extra_roots
        last_err = f"fetch_chunk {name}/{fname}: not held by this peer"
        for root in roots:
            path = os.path.join(root, name, "chunks", fname)
            if not os.path.isfile(path):
                continue
            with open(path, "rb") as f:
                data = f.read()
            actual = zlib.crc32(data) & 0xFFFFFFFF
            if expected is not None and actual != expected:
                # Never serve bytes that don't match the journal CRC —
                # keep looking in the other roots for an intact copy.
                last_err = (
                    f"fetch_chunk {name}/{fname}: held copy crc {actual} "
                    f"does not match journal crc {expected}"
                )
                continue
            self._bump("fetches")
            return {"kind": "chunk", "crc32": actual}, data
        raise ReplicaError(last_err)

    def _handle_probe(
        self, header: Dict[str, Any]
    ) -> Tuple[Dict[str, Any], bytes]:
        name = _safe_name(str(header.get("dataset")))
        chunk_dir = os.path.join(self._dataset_dir(name), "chunks")
        have: List[str] = []
        for entry in header.get("files", []) or []:
            fname = entry.get("file")
            if not fname:
                continue
            path = os.path.join(chunk_dir, _safe_file(str(fname)))
            if not os.path.isfile(path):
                continue
            crc = entry.get("crc32")
            if crc is None or crc32_file(path) == crc:
                have.append(str(fname))
        self._bump("probes")
        return {"kind": "probe", "have": have}, b""

    def stop(self) -> None:
        with self._lock:
            if self._stopped:
                return
            self._stopped = True
            conns = list(self._conns)
        try:
            # Closing alone does not wake a blocked accept() on every
            # platform; shutdown first, mirroring RowChannelServer.
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._listener.close()
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
        self._accept_thread.join(timeout=5)
        log.info("replica server stopped (%s)", self.addr)
