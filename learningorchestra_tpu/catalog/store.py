"""DatasetStore — the catalog: thread-safe named-dataset registry + queries
+ disk persistence.

Replaces the reference's MongoDB replica set as the universal data plane
(reference docker-compose.yml:27-91). The API surface mirrors what the 7
microservices actually used Mongo for (SURVEY.md §1/L4):

- collection-per-file naming, create/get/delete/list
  (reference database.py:94-130),
- paginated, filtered, ``_id``-sorted reads (database.py:36-48,107-111),
- metadata read/update incl. the ``finished`` flip (database.py:177-181),
- value-count aggregation for histograms (histogram.py:49-74) — here a
  vectorized method instead of a Mongo ``$group`` pipeline.

Queries support the Mongo operator set a reference client could reach by
passing JSON straight to ``find()`` (reference database.py:44-48): equality,
``$gt/$gte/$lt/$lte/$ne/$eq/$in/$nin/$exists/$regex/$not``, the logical
combinators ``$and/$or/$nor``, and dotted paths into nested documents —
evaluated vectorized over columns. Persistence is parquet + metadata.json
per dataset under ``settings.store_root`` — the durability tier replacing
Mongo volumes.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from learningorchestra_tpu.catalog import readpipe, replicate
from learningorchestra_tpu.catalog.dataset import (
    ChunkCorrupt, Columns, Dataset, Metadata, _fsync_dir, crc32_file,
    rows_from as _rows_from)
from learningorchestra_tpu.config import Settings, settings as global_settings
from learningorchestra_tpu.utils import failpoints

#: Deterministic fault-injection sites (utils/failpoints.py).
FP_MIRROR_PRE_COPY = failpoints.declare("store.mirror.pre_copy")
FP_FINISH_PRE_SAVE = failpoints.declare("store.finish.pre_save")
FP_SAVE_PRE_META_SWAP = failpoints.declare("store.save.pre_meta_swap")
FP_REPAIR_PRE_INSTALL = failpoints.declare("store.repair.pre_install")
FP_SHARDMAP_PRE_SWAP = failpoints.declare("store.shardmap.pre_swap")


class DatasetNotFound(KeyError):
    pass


class DatasetExists(ValueError):
    pass


class DatasetFailed(RuntimeError):
    """``finish`` refused: the dataset already carries a failure record."""


#: Dataset names become directory names under store_root and arrive from the
#: REST API, so they must never traverse paths.
_NAME_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9_.\-]*$")

#: Row-block size for streamed filtered reads — bounds per-request host
#: memory while amortizing per-block query-evaluation overhead.
_READ_BLOCK_ROWS = 1 << 16


def validate_name(name: str) -> str:
    if not isinstance(name, str) or not _NAME_RE.match(name) or ".." in name:
        raise ValueError(
            f"invalid dataset name {name!r}: use letters, digits, '_', '-', "
            "'.' (must start with a letter or digit)")
    return name


def column_value_counts(col: np.ndarray) -> Dict[Any, int]:
    """Value→count mapping for one column; missing values (None/NaN) bucket
    under the None key (Mongo $group keeps null as a distinct group key).
    Shared by ``DatasetStore.value_counts`` and the histogram op's host
    fallback (ops/histogram.py)."""
    if col.dtype == object:
        # pandas' hash-based value_counts is ~3x np.unique on object
        # arrays (no sort of Python strings) — the streaming histogram
        # calls this per chunk. Keys stringify, matching the historical
        # astype(str) domain for the rare non-string object cell.
        import pandas as pd

        try:
            vc = pd.Series(col, dtype=object).value_counts(dropna=True)
        except TypeError:
            # Unhashable cells (e.g. the dict-valued 'counts' column that
            # create_histogram writes): per-cell walk with the SAME key
            # domain as the hashable path below — scalars keep native
            # type, everything else stringifies, NaN/None bucket under
            # None — so which branch a chunk takes never changes its keys.
            out = {}
            n_null = 0
            for v in col:
                if v is None or (isinstance(v, (float, np.floating))
                                 and v != v):
                    n_null += 1
                    continue
                if isinstance(v, np.generic):
                    v = v.item()
                if not isinstance(v, (str, int, float)):
                    v = str(v)
                out[v] = out.get(v, 0) + 1
        else:
            # Key domain must match the histogram device path, which
            # returns NATIVE int keys (ops/histogram.py field_counts): a
            # column whose chunks flip between int64 and object dtype
            # (per-block type inference on mixed data) must not split one
            # value's count across an int bucket and a str bucket. So
            # numeric keys stay native; only non-scalar cells stringify —
            # accumulated, not overwritten, since distinct unhashables can
            # stringify alike.
            out = {}
            for k, c in vc.items():
                if isinstance(k, np.generic):
                    k = k.item()
                if not isinstance(k, (str, int, float)):
                    k = str(k)
                out[k] = out.get(k, 0) + int(c)
            n_null = len(col) - int(vc.sum())
        if n_null:
            out[None] = n_null
        return out
    null_mask = (np.isnan(col) if col.dtype.kind == "f"
                 else np.zeros(len(col), dtype=bool))
    vals = col[~null_mask]
    uniq, counts = np.unique(vals, return_counts=True)
    out = {}
    for u, c in zip(uniq, counts):
        u = u.item() if isinstance(u, np.generic) else u
        out[u] = int(c)
    n_null = int(null_mask.sum())
    if n_null:
        out[None] = n_null
    return out


class DatasetStore:
    """In-memory catalog of named datasets with optional disk persistence."""

    def __init__(self, cfg: Optional[Settings] = None):
        self.cfg = cfg or global_settings
        self._lock = threading.RLock()
        self._datasets: Dict[str, Dataset] = {}
        #: (generation, journal bytes) already mirrored to the replica,
        #: per dataset — keeps per-save mirroring O(delta) and detects
        #: journal replacement across rewrites/restarts.
        self._mirror_state: Dict[str, tuple] = {}
        #: Interrupted source-URL ingests found by the last load_all
        #: (resume_ingests=True) — the serving layer resubmits these.
        self.resumable_ingests: List[str] = []
        #: Data-plane integrity counters, served on GET /metrics:
        #: corrupt chunk detections, successful replica repairs, and
        #: scrub activity.
        self._integrity_lock = threading.Lock()
        self._integrity = {"chunks_corrupt": 0, "chunks_repaired": 0,
                           "chunks_scrubbed": 0, "scrub_runs": 0}
        #: Peer replication plane (catalog/replicate.py). _peer_state
        #: generalizes _mirror_state's (generation, journal-bytes)
        #: watermark per (peer addr, dataset): acked means the peer has
        #: committed that exact journal prefix, so journal_bytes - acked
        #: is the dataset's replication lag — under-replication is
        #: *known*, not hoped. Pushes run on a single async committer
        #: thread (same single-slot discipline as ingest's chunk
        #: committer); failures land in _push_failing and surface via
        #: replication_snapshot / the data_under_replicated alert.
        self._peers: List[str] = replicate.parse_peers(
            self.cfg.replica_peers)
        self._push_cv = threading.Condition(threading.Lock())
        self._push_dirty: set = set()
        self._push_inflight: Optional[str] = None
        self._push_thread: Optional[threading.Thread] = None
        self._push_stop = False
        self._peer_state: Dict[Tuple[str, str], tuple] = {}
        self._push_failing: Dict[Tuple[str, str], str] = {}
        self._push_attempt: Dict[str, float] = {}
        self._repl = {"pushes": 0, "push_bytes": 0, "fetches": 0,
                      "repairs": 0, "errors": 0}

    def _bump(self, key: str, by: int = 1) -> None:
        with self._integrity_lock:
            self._integrity[key] += by

    def integrity_snapshot(self) -> Dict[str, int]:
        """Corruption/repair counters (GET /metrics ``integrity`` block)."""
        with self._integrity_lock:
            return dict(self._integrity)

    def _bump_repl(self, key: str, by: int = 1) -> None:
        with self._integrity_lock:
            self._repl[key] = self._repl.get(key, 0) + by

    def _forget_peer_state(self, name: str) -> None:
        """Drop all replication bookkeeping for a dataset (delete /
        reopen): the next save starts a fresh full sync."""
        with self._push_cv:
            self._push_dirty.discard(name)
            self._push_attempt.pop(name, None)
            for key in [k for k in self._peer_state if k[1] == name]:
                del self._peer_state[key]
            for key in [k for k in self._push_failing if k[1] == name]:
                del self._push_failing[key]

    # -- lifecycle ----------------------------------------------------------

    def create(self, name: str, *, url: Optional[str] = None,
               parent: Optional[str] = None, finished: bool = False,
               columns: Optional[Columns] = None,
               extra: Optional[Dict[str, Any]] = None) -> Dataset:
        validate_name(name)
        with self._lock:
            if name in self._datasets:
                # Reference returns 409 on duplicate filename
                # (database_api_image/server.py:44-48).
                raise DatasetExists(name)
            meta = Metadata(name=name, url=url, parent=parent,
                            finished=finished, extra=dict(extra or {}))
            ds = Dataset(meta, columns)
            self._attach_storage(ds)
            self._datasets[name] = ds
        if self.cfg.persist:
            # Persist the metadata-first state immediately: a crash between
            # create and commit must leave a recoverable record, so restart
            # can mark the job interrupted instead of losing the dataset
            # (pollers would 404 forever otherwise).
            self.save(name)
        return ds

    def get(self, name: str) -> Dataset:
        with self._lock:
            try:
                return self._datasets[name]
            except KeyError:
                raise DatasetNotFound(name) from None

    def exists(self, name: str) -> bool:
        with self._lock:
            return name in self._datasets

    def delete(self, name: str) -> None:
        with self._lock:
            if name not in self._datasets:
                raise DatasetNotFound(name)
            del self._datasets[name]
            self._mirror_state.pop(name, None)
        self._forget_peer_state(name)
        path = self._path(name)
        # Reclaim the dataset's cached chunk reads promptly (keys are
        # CRC-pinned, so this is about bytes, not correctness).
        readpipe.invalidate_under(os.path.join(path, "chunks"))
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
        if self.cfg.replica_root:
            rpath = os.path.join(self.cfg.replica_root, name)
            if os.path.isdir(rpath):
                shutil.rmtree(rpath, ignore_errors=True)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._datasets)

    # -- metadata / completion protocol -------------------------------------

    def metadata_docs(self) -> List[Dict[str, Any]]:
        """All metadata docs — the reference's ``read_files_descriptor``
        listing (database_api_image/server.py:79-87)."""
        with self._lock:
            return [d.metadata.to_doc() for d in self._datasets.values()]

    def finish(self, name: str, **extra) -> None:
        """Flip ``finished`` true and persist — the commit point
        (reference database.py:177-181, projection.py:113-123).

        A dataset already marked FAILED refuses to flip to success: the
        pod watchdog fails a job's outputs the moment a worker dies
        mid-job, and the surviving process's compute may still run to
        completion afterwards (death after the worker's last collective)
        — its late ``finish`` must not overwrite the recorded failure
        with a half-a-pod success."""
        ds = self.get(name)
        if ds.metadata.finished and ds.metadata.error:
            raise DatasetFailed(
                f"dataset {name} is already marked failed "
                f"({ds.metadata.error}); refusing to mark it finished")
        ds.metadata.extra.update(extra)
        ds.metadata.finished = True
        failpoints.fire(FP_FINISH_PRE_SAVE)
        if self.cfg.persist:
            self.save(name)

    def install_shard_map(self, name: str, shard_map: Dict[str, Any]) -> None:
        """Record a range-partitioned ingest's ownership map (owner host →
        contiguous row range; global row order = partition order) in the
        dataset's metadata, where it rides the atomic ``save`` swap and
        the ``journal_sync`` metadata doc to replica peers. The map is a
        pure placement hint: a crash in the window before the metadata
        swap (the failpoint below) leaves a dataset that is fully
        readable and resumable, merely unplanned — ``mesh.shard_chunked``
        treats a missing map as unsharded."""
        ds = self.get(name)
        ds.metadata.extra["shard_map"] = shard_map
        failpoints.fire(FP_SHARDMAP_PRE_SWAP)
        if self.cfg.persist:
            self.save(name)

    def fail(self, name: str, error: str) -> None:
        """Record job failure so pollers don't spin forever (fixes the
        reference's finished:false-forever failure mode, SURVEY.md §5).

        First failure wins: a dataset already in a terminal state keeps
        its original record — the root cause (e.g. the watchdog's ``pod
        failure:`` flag, which the retry rescan keys on) must not be
        overwritten by downstream errors cascading from it."""
        ds = self.get(name)
        if ds.metadata.finished:
            return
        ds.metadata.error = error
        ds.metadata.finished = True
        if self.cfg.persist:
            self.save(name)

    def reopen(self, name: str) -> Dataset:
        """Reset a failed dataset for an automatic re-run (the job-retry
        path, serving/app.py): clear the failure record, drop any
        partially-written rows (a re-run appending after a partial save
        would duplicate them), and count the attempt in ``retries``. The
        journaled chunk store makes this safe — the replaced incarnation's
        chunk files are simply never referenced again."""
        ds = self.get(name)
        meta = ds.metadata
        meta.error = None
        meta.finished = False
        meta.fields = []
        meta.extra["retries"] = int(meta.extra.get("retries", 0) or 0) + 1
        fresh = Dataset(meta)
        path = self._path(name)
        readpipe.invalidate_under(os.path.join(path, "chunks"))
        shutil.rmtree(os.path.join(path, "chunks"), ignore_errors=True)
        for fn in ("journal.jsonl", "data.parquet"):
            try:
                os.remove(os.path.join(path, fn))
            except FileNotFoundError:
                pass
        self._attach_storage(fresh)
        with self._lock:
            self._datasets[name] = fresh
            self._mirror_state.pop(name, None)
        self._forget_peer_state(name)
        if self.cfg.persist:
            self.save(name)
        return fresh

    # -- reads ---------------------------------------------------------------

    def read(self, name: str, skip: int = 0, limit: int = 10,
             query: Optional[Dict[str, Any]] = None) -> List[Dict[str, Any]]:
        """Paginated filtered read, ``_id``-sorted, metadata doc included when
        it matches — mirrors ``DatabaseApi.read_file``
        (reference database.py:36-48, server.py:62-76)."""
        ds = self.get(name)
        query = query or {}
        if limit <= 0:
            return []
        docs: List[Dict[str, Any]] = []
        meta_doc = ds.metadata.to_doc()
        n_meta = 1 if _doc_matches(meta_doc, query) else 0
        if n_meta and skip == 0:
            docs.append(meta_doc)
        if len(docs) >= limit:
            # Early out before touching column data: the client's 3-second
            # completion poll is read(limit=1) (reference __init__.py:26-32)
            # and must stay O(1) — consolidating an out-of-core dataset to
            # answer it would read every chunk from disk.
            return docs
        row_skip = max(0, skip - n_meta)
        remaining = limit - len(docs)
        if remaining <= 0:
            return docs
        fields = ds.metadata.fields
        # Row reads never consolidate: only the chunks overlapping each
        # requested range are touched, so paging a spilled 50M-row dataset
        # reads O(page) — the reference pushed skip/limit into the Mongo
        # cursor for the same reason (database.py:107-111). The whole
        # request runs over ONE pinned chunk snapshot: a concurrent
        # set_column generation rewrite can never mix pre- and
        # post-rewrite values within a single response.
        with ds.snapshot() as snap:
            if not query:
                stop = min(row_skip + remaining, snap.n_rows)
                block = snap.read(None, row_skip, stop)
                k = len(next(iter(block.values()))) if block else 0
                docs.extend(_rows_from(block, fields, np.arange(k),
                                       id_offset=row_skip))
                return docs
            # Filtered read: scan only the QUERY's columns block-by-block
            # (with each block's global ``_id`` offset), stop as soon as
            # skip+limit matches are found, and fetch full rows just for
            # the matches — a selective 1-column predicate over a wide
            # dataset never decompresses the other columns of
            # non-matching blocks.
            to_skip = row_skip
            for off, n_blk, block in snap.scan(_query_fields(query, fields),
                                               block_rows=_READ_BLOCK_ROWS):
                idx = self._query_indices(block, fields, query,
                                          id_offset=off, n=n_blk)
                if to_skip:
                    dropped = min(to_skip, len(idx))
                    idx = idx[dropped:]
                    to_skip -= dropped
                take = idx[:remaining]
                if len(take):
                    g = take + off
                    lo, hi = int(g.min()), int(g.max()) + 1
                    full = snap.read(None, lo, hi)
                    docs.extend(_rows_from(full, fields, g - lo,
                                           id_offset=lo))
                    remaining -= len(take)
                if remaining <= 0:
                    break
            return docs

    @staticmethod
    def _query_indices(cols, fields: List[str], query: Dict[str, Any],
                       id_offset: int = 0,
                       n: Optional[int] = None) -> np.ndarray:
        if n is None:
            n = len(next(iter(cols.values()))) if cols else 0

        def resolve(field: str):
            if field == "_id":
                return (np.arange(id_offset + 1, id_offset + n + 1),
                        np.ones(n, dtype=bool))
            if field in cols:
                vals = cols[field]
                if vals.dtype == object:
                    exists = np.array([v is not None for v in vals],
                                      dtype=bool)
                elif vals.dtype.kind == "f":
                    exists = ~np.isnan(vals)
                else:
                    exists = np.ones(n, dtype=bool)
                return vals, exists
            if "." in field:
                # Dotted path into an object column of nested documents
                # (Mongo path traversal; flat CSV columns rarely hit this,
                # but query parity requires it).
                root, rest = field.split(".", 1)
                if root in cols and cols[root].dtype == object:
                    out = np.empty(n, dtype=object)
                    exists = np.zeros(n, dtype=bool)
                    for i, v in enumerate(cols[root]):
                        got, ok = _traverse(v, rest)
                        out[i] = got
                        exists[i] = ok
                    return out, exists
            return np.full(n, None, dtype=object), np.zeros(n, dtype=bool)

        return np.nonzero(_eval_query_mask(query, resolve, n))[0]

    # -- aggregation ---------------------------------------------------------

    def value_counts(self, name: str, field: str) -> Dict[Any, int]:
        """Per-value counts of a column — the reference's histogram
        aggregation ``[{"$group": {"_id": "$field", "count": {"$sum": 1}}}]``
        (histogram.py:49-74), vectorized.

        Streams chunk-by-chunk and merges per-chunk counts, like the
        histogram op (ops/histogram.py) — never consolidates, so this
        stays O(one chunk) in host memory on a spilled dataset (VERDICT
        r5 weak #7: this was the last O(dataset) read on the catalog
        surface). ``iter_chunks`` yields consolidation's *unified*
        dtypes, so per-chunk key domains match the resident counts
        exactly (native numeric keys stay native, None buckets NaN/None,
        unhashables stringify)."""
        ds = self.get(name)
        if field not in ds.metadata.fields:
            raise KeyError(field)
        totals: Dict[Any, int] = {}
        for cols in ds.iter_chunks([field]):
            for k, v in column_value_counts(cols[field]).items():
                totals[k] = totals.get(k, 0) + v
        return totals

    # -- persistence ---------------------------------------------------------
    #
    # On-disk layout per dataset (store_root/<name>/):
    #   metadata.json        — small, rewritten atomically (tmp+rename)
    #   journal.jsonl        — append-only, fsynced chunk-commit log
    #   chunks/00000.parquet — immutable chunk files (tmp+rename)
    # Legacy single-file layout (data.parquet) remains loadable.
    #
    # A commit (``save``) costs O(new chunks) + one small metadata write —
    # never a full rewrite — replacing the reference's per-row Mongo
    # inserts (database.py:176) with journaled columnar chunk appends.

    def _path(self, name: str) -> str:
        # Defense in depth alongside validate_name at create time.
        validate_name(name)
        return os.path.join(self.cfg.store_root, name)

    def _attach_storage(self, ds: Dataset) -> None:
        """Wire a dataset to its chunk dir / journal / RAM budget. Spilling
        works even with persist=False (chunk files land under store_root
        and die with the dataset)."""
        path = os.path.join(self.cfg.store_root, ds.metadata.name)
        budget = (self.cfg.ram_budget_mb * (1 << 20)
                  if self.cfg.ram_budget_mb else None)
        ds.attach_storage(os.path.join(path, "chunks"),
                          os.path.join(path, "journal.jsonl"),
                          ram_budget_bytes=budget,
                          prefetch_chunks=self.cfg.prefetch_chunks)
        name = ds.metadata.name
        ds.set_repair_hook(
            lambda fname, crc, _n=name: self._repair_chunk(_n, fname, crc))

    def _repair_chunk(self, name: str, fname: str,
                      expected_crc: Optional[int]) -> bool:
        """A chunk file failed verification (checksum mismatch / missing)
        — the self-healing tier. Counts the detection, then walks the
        repair ladder: the local replica mirror first (cheap, same
        host), then a CRC-verified remote fetch from any configured peer
        holding the dataset — so bit-rot and whole-host loss heal
        through the same ChunkCorrupt path. Returns whether a verified
        copy was installed."""
        self._bump("chunks_corrupt")
        if self._repair_from_mirror(name, fname, expected_crc):
            return True
        return self._repair_from_peers(name, fname, expected_crc)

    def _install_repair(self, name: str, fname: str,
                        src_path: Optional[str] = None,
                        data: Optional[bytes] = None) -> None:
        """Land a verified replacement chunk via tmp+rename so a
        concurrent reader never sees a half-copied file — the shared
        tail of both repair rungs (``src_path`` from the local mirror,
        ``data`` fetched from a peer)."""
        dst_dir = os.path.join(self.cfg.store_root, name, "chunks")
        os.makedirs(dst_dir, exist_ok=True)
        dst = os.path.join(dst_dir, fname)
        tmp = dst + ".repair"
        if src_path is not None:
            shutil.copy2(src_path, tmp)
        else:
            with open(tmp, "wb") as f:
                f.write(data or b"")
                f.flush()
                os.fsync(f.fileno())
        # Crash/torn window mid-repair: the corrupt primary (or a torn
        # .repair tmp) survives and the next read re-enters repair
        # idempotently.
        failpoints.fire(FP_REPAIR_PRE_INSTALL, path=tmp)
        os.replace(tmp, dst)
        _fsync_dir(dst_dir)
        # The pre-repair file may have been read (and CACHED) after rot
        # set in — lazy verification only covers the first read, so such
        # bytes enter the cache under the journal CRC key. Repair is the
        # one event that proves the old reads can't be trusted: drop
        # them so the next read re-decodes the verified replacement.
        # Both rungs — local mirror AND remote fetch — must pass through
        # here: a remotely healed file with stale cache entries would
        # serve the old decoded bytes under the new file's CRC key.
        readpipe.invalidate_files([dst])
        self._bump("chunks_repaired")

    def _repair_from_mirror(self, name: str, fname: str,
                            expected_crc: Optional[int]) -> bool:
        """Rung 1: restore from the local replica mirror when one is
        configured AND its copy itself verifies (a replica that mirrored
        the same rot must not 'repair' corrupt bytes over corrupt
        bytes)."""
        if not self.cfg.replica_root:
            return False
        src = os.path.join(self.cfg.replica_root, name, "chunks", fname)
        if not os.path.isfile(src):
            return False
        if expected_crc is not None and crc32_file(src) != expected_crc:
            return False
        self._install_repair(name, fname, src_path=src)
        return True

    def _repair_from_peers(self, name: str, fname: str,
                           expected_crc: Optional[int]) -> bool:
        """Rung 2: CRC-verified remote fetch from any peer holding the
        dataset. The client side verifies the received bytes against the
        journal CRC before anything is installed, and the serving peer
        re-verifies before replying — corrupt bytes cannot cross the
        wire in either direction undetected."""
        if not self._peers:
            return False
        for peer in self._peers:
            try:
                with replicate.ReplicaClient(
                        peer, self.cfg.replica_timeout_s) as cli:
                    data = cli.fetch_chunk(name, fname, expected_crc)
            except (replicate.ReplicaError, OSError, RuntimeError):
                # Dead peer / peer without the dataset / mismatched
                # bytes: count it and try the next rung candidate.
                self._bump_repl("errors")
                continue
            self._bump_repl("fetches")
            self._install_repair(name, fname, data=data)
            self._bump_repl("repairs")
            return True
        return False

    def scrub(self, name: Optional[str] = None) -> Dict[str, Any]:
        """Proactive integrity pass: re-verify every journaled chunk's
        checksum for one dataset (or the whole catalog), repairing from
        the replica where possible. Returns a report; corruption that
        could not be repaired is listed per dataset under ``errors``
        rather than raised, so one rotten dataset doesn't hide the state
        of the rest. Served at ``POST /catalog/scrub``."""
        names = [name] if name else self.names()
        report: Dict[str, Any] = {"datasets": len(names), "checked": 0,
                                  "unchecksummed": 0, "missing": 0,
                                  "errors": {}}
        for n in names:
            ds = self.get(n)
            r = ds.scrub_chunks()
            report["checked"] += r["checked"]
            report["unchecksummed"] += r["unchecksummed"]
            report["missing"] += r.get("missing", 0)
            if r["errors"]:
                report["errors"][n] = r["errors"]
        self._bump("chunks_scrubbed", report["checked"])
        self._bump("scrub_runs")
        report["ok"] = not report["errors"]
        return report

    def save(self, name: str) -> None:
        """Incremental commit: flush new chunks + rewrite metadata.json.

        Cost is O(data appended since the last save), so streaming ingest
        can checkpoint per chunk (the reference's durability granularity
        was per row via Mongo; database.py:171-181). After a set_column
        rebuild, a new chunk generation is written and the journal swapped
        atomically (old files stay valid until the swap — no crash window
        loses committed data), then stale files are garbage-collected.
        """
        ds = self.get(name)
        path = self._path(name)
        os.makedirs(path, exist_ok=True)
        if not ds.rewrite_generation():    # GCs its own stale files
            ds.flush_new_chunks()
        # A journaled layout supersedes any legacy single-file copy.
        if os.path.isfile(os.path.join(path, "journal.jsonl")):
            try:
                os.remove(os.path.join(path, "data.parquet"))
            except FileNotFoundError:
                pass
        tmp = os.path.join(path, "metadata.json.tmp")
        with open(tmp, "w") as f:
            json.dump(ds.metadata.to_doc(), f, default=str)
        # Crash window between journal commit (above) and the metadata
        # swap: load() rebuilds metadata.fields from journal dtypes, so
        # the sweep proves a stale/missing metadata.json is recoverable.
        failpoints.fire(FP_SAVE_PRE_META_SWAP)
        os.replace(tmp, os.path.join(path, "metadata.json"))
        ds.maybe_evict()
        if self.cfg.replica_root:
            self._mirror(name)
        if self._peers:
            self._queue_push(name)

    def _mirror(self, name: str) -> None:
        """Copy the dataset's committed delta to the replica root — the
        availability tier standing in for the reference's Mongo
        primary/secondary replication (docker-compose.yml:27-91).

        Per-save cost is O(what was committed since the last mirror): the
        journal bytes appended since the tracked per-dataset offset name
        exactly the chunk files to copy (immutable, uniquely named across
        generations — including files flushed by budget evictions between
        saves). Files are copied *before* the journal bytes referencing
        them land, so the replica is itself always a consistent prefix.

        The delta path only applies while the journal is known to be
        append-only since the last mirror: a generation change (rewrites,
        including ones committed inline by budget eviction) or an unknown
        offset (fresh process) falls back to a wholesale journal replace +
        GC of unreferenced replica files.
        """
        ds = self.get(name)
        src = self._path(name)
        dst = os.path.join(self.cfg.replica_root, name)
        os.makedirs(os.path.join(dst, "chunks"), exist_ok=True)
        src_chunks = os.path.join(src, "chunks")
        src_journal = os.path.join(src, "journal.jsonl")
        dst_journal = os.path.join(dst, "journal.jsonl")

        def copy_files(records):
            for rec in records:
                fn = rec.get("file")
                if not fn:
                    continue
                s = os.path.join(src_chunks, fn)
                d = os.path.join(dst, "chunks", fn)
                if os.path.isfile(d):
                    continue
                failpoints.fire(FP_MIRROR_PRE_COPY, path=s)
                if not os.path.isfile(s):
                    continue
                crc = rec.get("crc32")
                actual = None if crc is None else crc32_file(s)
                if crc is not None and actual != crc:
                    # The primary file is already damaged at mirror time
                    # (torn write that slipped past rename, or rot
                    # between commit and mirror). NEVER propagate corrupt
                    # bytes into the replica: repair the primary from an
                    # existing good replica copy if one survives,
                    # otherwise fail the save with the precise error.
                    if not self._repair_chunk(name, fn, crc):
                        raise ChunkCorrupt(s, crc, actual)
                shutil.copy2(s, d)

        # One atomic snapshot under the dataset's data lock: a concurrent
        # eviction flush (journal append) or inline generation rewrite
        # (journal *replacement*) cannot interleave, so the tracked offset
        # always refers to this exact byte sequence — reading gen and size
        # separately would let a rewrite land between them and the delta
        # path would splice new-generation bytes after old-generation
        # records in the replica. The snapshot reads only the delta when
        # the generation matches (O(what was committed since last mirror)).
        state = self._mirror_state.get(name)
        known_gen, known_off = (state if state is not None
                                and os.path.isfile(dst_journal)
                                else (None, 0))
        gen, size, data, is_delta = ds.journal_snapshot(known_gen, known_off)
        if data or is_delta or os.path.isfile(src_journal):
            records = _parse_journal_bytes(data)
            copy_files(records)
            if is_delta:
                if data:
                    with open(dst_journal, "ab") as d_f:
                        d_f.write(data)
            else:
                tmp = dst_journal + ".tmp"
                with open(tmp, "wb") as t_f:
                    t_f.write(data)
                os.replace(tmp, dst_journal)
                referenced = {rec["file"] for rec in records
                              if rec.get("file")}
                dst_chunks = os.path.join(dst, "chunks")
                for fn in os.listdir(dst_chunks):
                    if fn not in referenced:
                        try:
                            os.remove(os.path.join(dst_chunks, fn))
                        except FileNotFoundError:
                            pass
            self._mirror_state[name] = (gen, size)
        meta = os.path.join(src, "metadata.json")
        if os.path.isfile(meta):
            tmp = os.path.join(dst, "metadata.json.tmp")
            shutil.copy2(meta, tmp)
            os.replace(tmp, os.path.join(dst, "metadata.json"))

    # -- peer replication ----------------------------------------------------
    #
    # The cross-host generalization of _mirror: each save marks the
    # dataset dirty and a single committer thread pushes the committed
    # journal delta to every peer in LO_TPU_REPLICA_PEERS — chunk bytes
    # first (each hop CRC-verified against the journal record), then the
    # journal bytes referencing them, so a peer's replica is always a
    # consistent prefix exactly like the local mirror. A host death
    # mid-push costs only the unacked suffix.

    def _queue_push(self, name: str) -> None:
        """Mark a dataset dirty for the push committer (idempotent;
        concurrent saves of the same dataset coalesce — the push always
        reads the newest committed journal snapshot)."""
        with self._push_cv:
            if self._push_stop:
                return
            self._push_dirty.add(name)
            if self._push_thread is None:
                # thread-lifecycle: owner=DatasetStore
                # exit=stop_replication() sets _push_stop and notifies;
                # the loop returns on the next wake.
                self._push_thread = threading.Thread(
                    target=self._push_loop, name="lo-replica-push",
                    daemon=True)
                self._push_thread.start()
            self._push_cv.notify_all()

    def _push_loop(self) -> None:
        while True:
            with self._push_cv:
                while not self._push_dirty and not self._push_stop:
                    self._push_cv.wait()
                if self._push_stop:
                    return
                name = sorted(self._push_dirty)[0]
                self._push_dirty.discard(name)
                self._push_inflight = name
            try:
                self._push_dataset(name)
            finally:
                with self._push_cv:
                    self._push_inflight = None
                    self._push_cv.notify_all()

    def _push_dataset(self, name: str) -> None:
        """One push cycle: every peer, errors recorded per (peer,
        dataset) — never raised (replication is asynchronous; the
        primary's durability does not depend on it)."""
        with self._push_cv:
            self._push_attempt[name] = time.monotonic()
        try:
            ds = self.get(name)
        except DatasetNotFound:
            return  # deleted between save and push
        for peer in self._peers:
            key = (peer, name)
            try:
                self._push_peer(peer, name, ds)
            except (replicate.ReplicaError, ChunkCorrupt, OSError,
                    RuntimeError) as exc:
                self._bump_repl("errors")
                with self._push_cv:
                    self._push_failing[key] = str(exc)

    def _push_peer(self, peer: str, name: str, ds: Dataset) -> None:
        """Push the committed journal delta for one dataset to one peer.
        Same snapshot discipline as _mirror: one atomic journal_snapshot
        names exactly the chunk files to send; files cross the wire
        before the journal bytes referencing them, each hop CRC-checked
        on both ends. An offset-mismatch rejection (peer re-imaged or
        watermark lost) clears the watermark and retries once as a full
        sync, using scrub_probe to skip bytes the peer already holds."""
        key = (peer, name)
        src_chunks = os.path.join(self.cfg.store_root, name, "chunks")
        for attempt in (0, 1):
            with self._push_cv:
                state = self._peer_state.get(key)
            known_gen, known_off = (state if state is not None
                                    else (None, 0))
            gen, size, data, is_delta = ds.journal_snapshot(
                known_gen, known_off)
            records = _parse_journal_bytes(data)
            try:
                with replicate.ReplicaClient(
                        peer, self.cfg.replica_timeout_s) as cli:
                    if is_delta:
                        need = [r for r in records if r.get("file")]
                    else:
                        refs = [(r["file"], r.get("crc32"))
                                for r in records if r.get("file")]
                        have = (set(cli.scrub_probe(name, refs))
                                if refs else set())
                        need = [r for r in records
                                if r.get("file") and r["file"] not in have]
                    for rec in need:
                        fn = rec["file"]
                        crc = rec.get("crc32")
                        path = os.path.join(src_chunks, fn)
                        actual = (crc32_file(path)
                                  if os.path.isfile(path) else None)
                        if crc is not None and actual != crc:
                            # NEVER push bytes that don't match the
                            # journal — heal the primary first (mirror
                            # or another peer) or record the failure.
                            if not self._repair_chunk(name, fn, crc):
                                raise ChunkCorrupt(path, crc, actual)
                        with open(path, "rb") as f:
                            payload = f.read()
                        cli.push_chunk(name, fn, crc, payload)
                        self._bump_repl("pushes")
                        self._bump_repl("push_bytes", len(payload))
                    # Metadata rides every sync (a bare `finish` changes
                    # metadata without appending journal bytes). Routed
                    # through json default=str like save()'s write.
                    meta_doc = json.loads(
                        json.dumps(ds.metadata.to_doc(), default=str))
                    cli.journal_sync(
                        name, gen, known_off if is_delta else 0, data,
                        is_delta, meta_doc)
            except replicate.ReplicaError as exc:
                if attempt == 0 and "offset" in str(exc):
                    with self._push_cv:
                        self._peer_state.pop(key, None)
                    continue
                raise
            with self._push_cv:
                self._peer_state[key] = (gen, size)
                self._push_failing.pop(key, None)
            return

    def replication_drain(self, timeout_s: float = 30.0) -> bool:
        """Block until the push committer's queue is empty (every dirty
        dataset attempted against every peer). Returns False on timeout.
        Failed pushes still count as drained — their outcome is in
        replication_snapshot, not an exception."""
        deadline = time.monotonic() + timeout_s
        with self._push_cv:
            while self._push_dirty or self._push_inflight:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._push_cv.wait(left)
        return True

    def stop_replication(self) -> None:
        """Stop the push committer thread (serving shutdown)."""
        with self._push_cv:
            self._push_stop = True
            self._push_cv.notify_all()
            t = self._push_thread
        if t is not None:
            t.join(timeout=5)

    def replication_snapshot(self) -> Dict[str, Any]:
        """Per-dataset replication state for GET /metrics: per-peer
        acked watermarks, lag bytes, and which datasets are
        under-replicated (lag with a failed last push — transient lag
        from an in-flight push is not flagged). Also the read-driven
        retry tick: datasets whose last attempt failed longer than
        replica_push_retry_s ago are re-queued, so each scrape advances
        re-replication until lag clears."""
        with self._integrity_lock:
            counters = dict(self._repl)
        snap: Dict[str, Any] = {"enabled": bool(self._peers),
                                "peers": list(self._peers),
                                "counters": counters,
                                "datasets": {},
                                "under_replicated": [],
                                "max_lag_bytes": 0}
        if not self._peers:
            return snap
        now = time.monotonic()
        with self._push_cv:
            state = dict(self._peer_state)
            failing = dict(self._push_failing)
            dirty = set(self._push_dirty)
            inflight = self._push_inflight
            attempts = dict(self._push_attempt)
        retry: List[str] = []
        for name in self.names():
            try:
                ds = self.get(name)
            except DatasetNotFound:
                continue
            gen, size = ds.journal_size()
            peers_doc: Dict[str, Any] = {}
            worst = 0
            flagged = False
            for peer in self._peers:
                st = state.get((peer, name))
                acked = st[1] if st is not None and st[0] == gen else 0
                lag = max(0, size - acked)
                err = failing.get((peer, name))
                doc: Dict[str, Any] = {"acked_bytes": acked,
                                       "lag_bytes": lag}
                if err:
                    doc["error"] = err
                peers_doc[peer] = doc
                pending = name in dirty or inflight == name
                if lag > 0 and (err or not pending):
                    worst = max(worst, lag)
                    flagged = True
                    snap["under_replicated"].append(
                        {"dataset": name, "peer": peer,
                         "lag_bytes": lag})
            snap["datasets"][name] = {"journal_bytes": size,
                                      "lag_bytes": worst,
                                      "peers": peers_doc}
            snap["max_lag_bytes"] = max(snap["max_lag_bytes"], worst)
            if flagged and name not in dirty and inflight != name:
                last = attempts.get(name)
                if last is None or (now - last
                                    >= self.cfg.replica_push_retry_s):
                    retry.append(name)
        for name in retry:
            self._queue_push(name)
        return snap

    @staticmethod
    def _read_journal(path: str) -> List[Dict[str, Any]]:
        """Parse journal records from a file (load path)."""
        try:
            with open(path, "rb") as f:
                return _parse_journal_bytes(f.read())
        except FileNotFoundError:
            return []

    def load(self, name: str) -> Dataset:
        """Load one persisted dataset into the catalog.

        Journaled chunk layout loads *lazily* — only metadata and the
        journal are read; column data stays in its chunk files until first
        access. Legacy single-file (data.parquet) layout reads eagerly.
        """
        import pyarrow.parquet as pq

        path = self._path(name)
        meta_path = os.path.join(path, "metadata.json")
        if not os.path.isfile(meta_path):
            raise DatasetNotFound(name)
        with open(meta_path) as f:
            meta = Metadata.from_doc(json.load(f))
        records = self._read_journal(os.path.join(path, "journal.jsonl"))
        ds = Dataset(meta)
        if records:
            ds.restore_chunks(records, os.path.join(path, "chunks"))
            if not meta.fields:
                # Crash window: chunks journal-committed before the first
                # metadata rewrite landed (save orders journal first).
                # The journal's dtype maps carry the field names in
                # append order — recover them so the prefix is readable
                # (and a resumed ingest knows its columns).
                meta.fields = list(records[0].get("dtypes", {}).keys())
        else:
            data_path = os.path.join(path, "data.parquet")
            if os.path.isfile(data_path):
                # Single-threaded read: see read_chunk_parquet's note on
                # pyarrow's IO pool segfaulting in jax-loaded processes.
                table = pq.read_table(data_path, use_threads=False,
                                      pre_buffer=False)
                columns: Columns = {
                    fname: table.column(fname).to_numpy(zero_copy_only=False)
                    for fname in table.column_names}
                if columns:
                    ds.append_columns(
                        {f: columns[f] for f in meta.fields if f in columns}
                        if meta.fields else columns)
        self._attach_storage(ds)
        with self._lock:
            self._datasets[name] = ds
        return ds

    def load_all(self, resume_ingests: bool = False) -> List[str]:
        """Recover the catalog from disk at startup (crash resume).

        If a replica root is configured, datasets present there but missing
        from the primary (disk loss) are restored first — the failover
        analogue of the reference's replica-set recovery
        (docker-compose.yml:27-91).

        Datasets recovered with ``finished: false`` were mid-job when the
        process died; their jobs are gone, so they are marked failed —
        every dataset reaches a terminal state across restarts (the
        reference left finished:false forever, SURVEY.md §5). Exception:
        with ``resume_ingests``, interrupted *source-URL ingests* are left
        unfinished and listed in ``resumable_ingests`` — their journaled
        chunks carry source byte offsets, so the serving layer restarts
        them from the last committed byte (catalog/ingest.py
        ``resume_ingest``) instead of failing a 99%-done load.
        """
        root = self.cfg.store_root
        if self.cfg.replica_root and os.path.isdir(self.cfg.replica_root):
            for name in sorted(os.listdir(self.cfg.replica_root)):
                rmeta = os.path.join(self.cfg.replica_root, name,
                                     "metadata.json")
                pmeta = os.path.join(root, name, "metadata.json")
                if os.path.isfile(rmeta) and not os.path.isfile(pmeta):
                    shutil.copytree(os.path.join(self.cfg.replica_root, name),
                                    os.path.join(root, name),
                                    dirs_exist_ok=True)
        loaded = []
        if os.path.isdir(root):
            for name in sorted(os.listdir(root)):
                if os.path.isfile(os.path.join(root, name, "metadata.json")):
                    self.load(name)
                    loaded.append(name)
        self.resumable_ingests: List[str] = []
        for name in loaded:
            ds = self.get(name)
            if not ds.metadata.finished and not ds.metadata.error:
                if (resume_ingests and ds.metadata.url
                        and not ds.metadata.parent
                        and (ds.num_rows == 0
                             or ds.resume_offset is not None)):
                    self.resumable_ingests.append(name)
                    continue
                self.fail(name, "interrupted: server restarted mid-job")
        if self.cfg.scrub_on_load and loaded:
            # Recovery-scan verification: checksum every journaled chunk
            # the crash-surviving journals reference, repairing from the
            # replica where possible. Off by default — it reads every
            # chunk file, trading startup time for eager detection;
            # lazy first-read verification covers the default path.
            report = self.scrub()
            for n, errs in report["errors"].items():
                # Direct mark (not ``fail``): corruption must surface on
                # the metadata even for datasets that finished
                # successfully before the rot set in, and must not
                # overwrite an earlier recorded root cause.
                ds = self.get(n)
                ds.metadata.error = (ds.metadata.error
                                     or f"chunk corruption: {errs[0]}")
                ds.metadata.finished = True
                # A corrupt interrupted ingest must NOT be resubmitted
                # for resume — it would append fresh rows to a dataset
                # just declared damaged.
                if n in self.resumable_ingests:
                    self.resumable_ingests.remove(n)
                if self.cfg.persist:
                    try:
                        self.save(n)
                    except ChunkCorrupt:
                        # The mirror re-verifies chunks and re-raises on
                        # the same unrepairable file; metadata.json was
                        # already rewritten before the mirror step, and
                        # one rotten dataset must not abort the whole
                        # recovery scan.
                        pass
        if self._peers:
            # Establish fresh acked watermarks: a restarted process has
            # no push state, so every recovered dataset is re-synced
            # (scrub_probe keeps the cost at journal bytes + any chunk
            # bytes the peers actually lack). This is the
            # "re-replicate" leg of the host-loss runbook.
            for name in loaded:
                self._queue_push(name)
        return loaded


def _parse_journal_bytes(data: bytes) -> List[Dict[str, Any]]:
    """Journal bytes → records, tolerating a torn final line (a crash
    mid-append commits nothing; the preceding prefix stays valid)."""
    records: List[Dict[str, Any]] = []
    for line in data.decode("utf-8", errors="replace").splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            records.append(json.loads(line))
        except json.JSONDecodeError:
            break  # torn tail write — everything before is valid
    return records


# -- query evaluation --------------------------------------------------------
#
# The reference's read API passed the client's JSON query verbatim into
# pymongo's ``find()`` (database_api_image/database.py:44-48), so the whole
# Mongo operator set was reachable. This section reproduces that contract
# as vectorized mask evaluation: one shared evaluator serves both column
# queries (arrays of length n) and single-document matches (length-1).

_OPS = {
    "$gt": lambda v, x: v > x,
    "$gte": lambda v, x: v >= x,
    "$lt": lambda v, x: v < x,
    "$lte": lambda v, x: v <= x,
    "$ne": lambda v, x: v != x,
    "$eq": lambda v, x: v == x,
    "$in": lambda v, x: np.isin(v, x),
    "$nin": lambda v, x: ~np.isin(v, x),
}

#: Operators whose Mongo semantics MATCH documents missing the field
#: ($ne/$nin match absent values; comparisons and $in/$regex don't).
_MATCH_MISSING = {"$ne", "$nin"}

_REGEX_FLAGS = {"i": re.IGNORECASE, "m": re.MULTILINE, "s": re.DOTALL,
                "x": re.VERBOSE}


def _query_fields(query: Dict[str, Any],
                  fields: List[str]) -> List[str]:
    """Root column names a Mongo-style query touches (dotted paths keep
    their root; ``_id`` is positional and needs no column) — the
    projection a filtered scan reads instead of every column."""
    out: set = set()

    def walk(q) -> None:
        if not isinstance(q, dict):
            return
        for k, v in q.items():
            if k in ("$and", "$or", "$nor"):
                for sub in (v if isinstance(v, (list, tuple)) else ()):
                    walk(sub)
            elif not k.startswith("$") and k != "_id":
                out.add(k.split(".", 1)[0])

    walk(query)
    return [f for f in fields if f in out]


def _traverse(value: Any, path: str):
    """Walk a dotted path inside a nested document; returns (value, found)."""
    for part in path.split("."):
        if isinstance(value, dict) and part in value:
            value = value[part]
        else:
            return None, False
    return value, True


def _apply_op(op: str, vals: np.ndarray, operand: Any) -> np.ndarray:
    """One operator over a column; object columns evaluate elementwise so
    mixed/None values never raise (a None cell simply doesn't match —
    Mongo's null-comparison behavior, which the vectorized path can't give
    for object dtypes)."""
    fn = _OPS[op]
    if vals.dtype == object:
        out = np.zeros(len(vals), dtype=bool)
        for i, v in enumerate(vals):
            try:
                out[i] = bool(fn(v, operand))
            except TypeError:
                out[i] = False
        return out
    with np.errstate(invalid="ignore"):
        return np.asarray(fn(vals, operand), dtype=bool)


def _apply_regex(vals: np.ndarray, pattern: str, options: str) -> np.ndarray:
    flags = 0
    for ch in options or "":
        flags |= _REGEX_FLAGS.get(ch, 0)
    rx = re.compile(pattern, flags)
    out = np.zeros(len(vals), dtype=bool)
    for i, v in enumerate(vals):
        if isinstance(v, str):          # np.str_ subclasses str
            out[i] = rx.search(v) is not None
    return out


def _eval_cond(vals: np.ndarray, exists: np.ndarray, cond: Any) -> np.ndarray:
    """Evaluate one field condition (scalar equality or operator document)
    against resolved values + an existence mask."""
    if isinstance(cond, dict) and any(k.startswith("$") for k in cond):
        mask = np.ones(len(vals), dtype=bool)
        for op, operand in cond.items():
            if op == "$exists":
                mask &= exists if operand else ~exists
            elif op == "$not":
                # $not negates the operator expression and matches docs
                # missing the field (Mongo semantics).
                mask &= ~_eval_cond(vals, exists, operand)
            elif op == "$regex":
                mask &= _apply_regex(vals.astype(object), operand,
                                     cond.get("$options", ""))
            elif op == "$options":
                continue  # consumed by $regex
            elif op == "$eq" and operand is None:
                mask &= ~exists          # null equality matches null/missing
            elif op == "$ne" and operand is None:
                mask &= exists
            elif op in _OPS:
                m = _apply_op(op, vals, operand)
                has_null = (op in ("$in", "$nin")
                            and isinstance(operand, (list, tuple))
                            and None in operand)
                if op in _MATCH_MISSING:
                    # $nin [..., null]: null IS in the list, so null/missing
                    # values are excluded rather than matched.
                    m = (m & exists) if has_null else (m | ~exists)
                else:
                    # $in [..., null] matches null/missing (Mongo null-in-
                    # array semantics); plain comparisons require presence.
                    m = (m | ~exists) if has_null else (m & exists)
                mask &= m
            else:
                raise ValueError(f"unsupported query operator: {op}")
        return mask
    if cond is None:
        # {field: null} matches documents where the field is null OR
        # missing (Mongo semantics; NaN/None cells count as missing here).
        return ~exists
    # Scalar (or literal-document) equality: field must exist and equal.
    return _apply_op("$eq", vals, cond) & exists


def _eval_query_mask(query: Dict[str, Any], resolve, n: int) -> np.ndarray:
    """Evaluate a full query document: implicit AND of field conditions and
    the $and/$or/$nor combinators. ``resolve(field) -> (vals, exists)``."""
    mask = np.ones(n, dtype=bool)
    for key, cond in query.items():
        if key in ("$and", "$or", "$nor"):
            if not isinstance(cond, (list, tuple)) or not cond:
                raise ValueError(f"{key} requires a non-empty array")
            subs = [_eval_query_mask(q, resolve, n) for q in cond]
            if key == "$and":
                sub = np.logical_and.reduce(subs)
            else:
                sub = np.logical_or.reduce(subs)
                if key == "$nor":
                    sub = ~sub
            mask &= sub
        elif key.startswith("$"):
            raise ValueError(f"unsupported top-level operator: {key}")
        else:
            vals, exists = resolve(key)
            mask &= _eval_cond(vals, exists, cond)
    return mask


def _doc_matches(doc: Dict[str, Any], query: Dict[str, Any]) -> bool:
    def resolve(field: str):
        val, found = _traverse(doc, field)
        return (np.asarray([val], dtype=object),
                np.asarray([found], dtype=bool))

    try:
        return bool(_eval_query_mask(query, resolve, 1)[0])
    except TypeError:
        return False
