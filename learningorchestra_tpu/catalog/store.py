"""DatasetStore — the catalog: thread-safe named-dataset registry + queries
+ disk persistence.

Replaces the reference's MongoDB replica set as the universal data plane
(reference docker-compose.yml:27-91). The API surface mirrors what the 7
microservices actually used Mongo for (SURVEY.md §1/L4):

- collection-per-file naming, create/get/delete/list
  (reference database.py:94-130),
- paginated, filtered, ``_id``-sorted reads (database.py:36-48,107-111),
- metadata read/update incl. the ``finished`` flip (database.py:177-181),
- value-count aggregation for histograms (histogram.py:49-74) — here a
  vectorized method instead of a Mongo ``$group`` pipeline.

Queries support the Mongo-query subset the reference's docs exercise
(equality and ``$gt/$gte/$lt/$lte/$ne/$in``) evaluated vectorized over
columns. Persistence is parquet + metadata.json per dataset under
``settings.store_root`` — the durability tier replacing Mongo volumes.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import threading
from typing import Any, Dict, List, Optional

import numpy as np

from learningorchestra_tpu.catalog.dataset import (
    Columns, Dataset, Metadata, rows_from as _rows_from)
from learningorchestra_tpu.config import Settings, settings as global_settings


class DatasetNotFound(KeyError):
    pass


class DatasetExists(ValueError):
    pass


#: Dataset names become directory names under store_root and arrive from the
#: REST API, so they must never traverse paths.
_NAME_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9_.\-]*$")


def validate_name(name: str) -> str:
    if not isinstance(name, str) or not _NAME_RE.match(name) or ".." in name:
        raise ValueError(
            f"invalid dataset name {name!r}: use letters, digits, '_', '-', "
            "'.' (must start with a letter or digit)")
    return name


def column_value_counts(col: np.ndarray) -> Dict[Any, int]:
    """Value→count mapping for one column; missing values (None/NaN) bucket
    under the None key (Mongo $group keeps null as a distinct group key).
    Shared by ``DatasetStore.value_counts`` and the histogram op's host
    fallback (ops/histogram.py)."""
    if col.dtype == object:
        null_mask = np.array([v is None for v in col], dtype=bool)
        vals = col[~null_mask].astype(str)
    else:
        null_mask = (np.isnan(col) if col.dtype.kind == "f"
                     else np.zeros(len(col), dtype=bool))
        vals = col[~null_mask]
    uniq, counts = np.unique(vals, return_counts=True)
    out: Dict[Any, int] = {}
    for u, c in zip(uniq, counts):
        u = u.item() if isinstance(u, np.generic) else u
        out[u] = int(c)
    n_null = int(null_mask.sum())
    if n_null:
        out[None] = n_null
    return out


class DatasetStore:
    """In-memory catalog of named datasets with optional disk persistence."""

    def __init__(self, cfg: Optional[Settings] = None):
        self.cfg = cfg or global_settings
        self._lock = threading.RLock()
        self._datasets: Dict[str, Dataset] = {}

    # -- lifecycle ----------------------------------------------------------

    def create(self, name: str, *, url: Optional[str] = None,
               parent: Optional[str] = None, finished: bool = False,
               columns: Optional[Columns] = None,
               extra: Optional[Dict[str, Any]] = None) -> Dataset:
        validate_name(name)
        with self._lock:
            if name in self._datasets:
                # Reference returns 409 on duplicate filename
                # (database_api_image/server.py:44-48).
                raise DatasetExists(name)
            meta = Metadata(name=name, url=url, parent=parent,
                            finished=finished, extra=dict(extra or {}))
            ds = Dataset(meta, columns)
            self._datasets[name] = ds
        if self.cfg.persist:
            # Persist the metadata-first state immediately: a crash between
            # create and commit must leave a recoverable record, so restart
            # can mark the job interrupted instead of losing the dataset
            # (pollers would 404 forever otherwise).
            self.save(name)
        return ds

    def get(self, name: str) -> Dataset:
        with self._lock:
            try:
                return self._datasets[name]
            except KeyError:
                raise DatasetNotFound(name) from None

    def exists(self, name: str) -> bool:
        with self._lock:
            return name in self._datasets

    def delete(self, name: str) -> None:
        with self._lock:
            if name not in self._datasets:
                raise DatasetNotFound(name)
            del self._datasets[name]
        path = self._path(name)
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._datasets)

    # -- metadata / completion protocol -------------------------------------

    def metadata_docs(self) -> List[Dict[str, Any]]:
        """All metadata docs — the reference's ``read_files_descriptor``
        listing (database_api_image/server.py:79-87)."""
        with self._lock:
            return [d.metadata.to_doc() for d in self._datasets.values()]

    def finish(self, name: str, **extra) -> None:
        """Flip ``finished`` true and persist — the commit point
        (reference database.py:177-181, projection.py:113-123)."""
        ds = self.get(name)
        ds.metadata.extra.update(extra)
        ds.metadata.finished = True
        if self.cfg.persist:
            self.save(name)

    def fail(self, name: str, error: str) -> None:
        """Record job failure so pollers don't spin forever (fixes the
        reference's finished:false-forever failure mode, SURVEY.md §5)."""
        ds = self.get(name)
        ds.metadata.error = error
        ds.metadata.finished = True
        if self.cfg.persist:
            self.save(name)

    # -- reads ---------------------------------------------------------------

    def read(self, name: str, skip: int = 0, limit: int = 10,
             query: Optional[Dict[str, Any]] = None) -> List[Dict[str, Any]]:
        """Paginated filtered read, ``_id``-sorted, metadata doc included when
        it matches — mirrors ``DatabaseApi.read_file``
        (reference database.py:36-48, server.py:62-76)."""
        ds = self.get(name)
        query = query or {}
        if limit <= 0:
            return []
        docs: List[Dict[str, Any]] = []
        meta_doc = ds.metadata.to_doc()
        n_meta = 1 if _doc_matches(meta_doc, query) else 0
        if n_meta and skip == 0:
            docs.append(meta_doc)
        # One consistent snapshot for the whole read: ds.columns is an
        # immutable consolidation, so mask lengths and row materialization
        # can't diverge even while an ingest job is appending.
        cols = ds.columns
        idx = self._query_indices(cols, ds.metadata.fields, query)
        # Apply skip/limit on indices BEFORE materializing row dicts (the
        # reference pushed skip/limit into the Mongo cursor,
        # database.py:107-111).
        row_skip = max(0, skip - n_meta)
        remaining = limit - len(docs)
        idx = idx[row_skip:row_skip + remaining] if remaining > 0 else idx[:0]
        docs.extend(_rows_from(cols, ds.metadata.fields, idx))
        return docs

    @staticmethod
    def _query_indices(cols, fields: List[str],
                       query: Dict[str, Any]) -> np.ndarray:
        n = len(next(iter(cols.values()))) if cols else 0
        mask = np.ones(n, dtype=bool)
        for field, cond in query.items():
            if field == "_id":
                vals = np.arange(1, n + 1)
            elif field in cols:
                vals = cols[field]
            else:
                mask[:] = False
                break
            mask &= _eval_cond(vals, cond)
        return np.nonzero(mask)[0]

    # -- aggregation ---------------------------------------------------------

    def value_counts(self, name: str, field: str) -> Dict[Any, int]:
        """Per-value counts of a column — the reference's histogram
        aggregation ``[{"$group": {"_id": "$field", "count": {"$sum": 1}}}]``
        (histogram.py:49-74), vectorized."""
        return column_value_counts(self.get(name).columns[field])

    # -- persistence ---------------------------------------------------------

    def _path(self, name: str) -> str:
        # Defense in depth alongside validate_name at create time.
        validate_name(name)
        return os.path.join(self.cfg.store_root, name)

    def save(self, name: str) -> None:
        """Write dataset as parquet + metadata.json under store_root."""
        import pyarrow as pa
        import pyarrow.parquet as pq

        ds = self.get(name)
        path = self._path(name)
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "metadata.json"), "w") as f:
            json.dump(ds.metadata.to_doc(), f, default=str)
        if ds.num_rows:
            cols = ds.columns
            arrays, names = [], []
            for fname in ds.metadata.fields:
                arr = cols[fname]
                if arr.dtype == object:
                    arrays.append(pa.array([None if v is None else str(v)
                                            for v in arr]))
                else:
                    arrays.append(pa.array(arr))
                names.append(fname)
            pq.write_table(pa.table(arrays, names=names),
                           os.path.join(path, "data.parquet"))

    def load(self, name: str) -> Dataset:
        """Load one persisted dataset into the catalog."""
        import pyarrow.parquet as pq

        path = self._path(name)
        meta_path = os.path.join(path, "metadata.json")
        if not os.path.isfile(meta_path):
            raise DatasetNotFound(name)
        with open(meta_path) as f:
            meta = Metadata.from_doc(json.load(f))
        columns: Columns = {}
        data_path = os.path.join(path, "data.parquet")
        if os.path.isfile(data_path):
            table = pq.read_table(data_path)
            for fname in table.column_names:
                arr = table.column(fname).to_numpy(zero_copy_only=False)
                columns[fname] = arr
        ds = Dataset(meta, columns or None)
        with self._lock:
            self._datasets[name] = ds
        return ds

    def load_all(self) -> List[str]:
        """Recover the catalog from disk at startup (crash resume).

        Datasets recovered with ``finished: false`` were mid-job when the
        process died; their jobs are gone, so they are marked failed —
        every dataset reaches a terminal state across restarts (the
        reference left finished:false forever, SURVEY.md §5).
        """
        root = self.cfg.store_root
        loaded = []
        if os.path.isdir(root):
            for name in sorted(os.listdir(root)):
                if os.path.isfile(os.path.join(root, name, "metadata.json")):
                    self.load(name)
                    loaded.append(name)
        for name in loaded:
            ds = self.get(name)
            if not ds.metadata.finished and not ds.metadata.error:
                self.fail(name, "interrupted: server restarted mid-job")
        return loaded


# -- query evaluation --------------------------------------------------------

_OPS = {
    "$gt": lambda v, x: v > x,
    "$gte": lambda v, x: v >= x,
    "$lt": lambda v, x: v < x,
    "$lte": lambda v, x: v <= x,
    "$ne": lambda v, x: v != x,
    "$eq": lambda v, x: v == x,
    "$in": lambda v, x: np.isin(v, x),
}


def _eval_cond(vals: np.ndarray, cond: Any) -> np.ndarray:
    if isinstance(cond, dict):
        mask = np.ones(len(vals), dtype=bool)
        for op, operand in cond.items():
            if op not in _OPS:
                raise ValueError(f"unsupported query operator: {op}")
            with np.errstate(invalid="ignore"):
                mask &= np.asarray(_OPS[op](vals, operand), dtype=bool)
        return mask
    with np.errstate(invalid="ignore"):
        return np.asarray(vals == cond, dtype=bool)


def _doc_matches(doc: Dict[str, Any], query: Dict[str, Any]) -> bool:
    for field, cond in query.items():
        if field not in doc:
            return False
        val = np.asarray([doc[field]], dtype=object)
        try:
            if not _eval_cond(val, cond)[0]:
                return False
        except TypeError:
            return False
    return True
