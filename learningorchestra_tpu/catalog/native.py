"""ctypes binding to the native C++ CSV tokenizer (``native/csv_parser.cpp``).

The reference's native horsepower lived in the external Spark JVM
(SURVEY.md §2); this framework's native tier is first-party C++. The parser
tokenizes CSV bytes into whole-column buffers — numeric columns as
contiguous float64/int64, string columns in Arrow layout (int32 offsets +
UTF-8 data + validity bitmap) — which Python adopts in bulk: numerics as
numpy arrays, strings as ``pyarrow`` arrays built from the raw buffers.
No per-cell Python work happens anywhere on the ingest path, and ctypes
releases the GIL for the duration of each parse call, so block parsing
scales across threads (catalog/ingest.py's parse pool).

Falls back to pandas when the shared library has not been built
(``make -C native`` builds it; tests cover both paths).
"""

from __future__ import annotations

import ctypes
import os
from typing import Iterator, List, Optional

import numpy as np

_LIB_NAMES = ("libcsv_parser.so",)
_lib: Optional[ctypes.CDLL] = None
_load_attempted = False


def _lib_path() -> Optional[str]:
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    for name in _LIB_NAMES:
        for sub in ("native", "native/build"):
            p = os.path.join(root, sub, name)
            if os.path.isfile(p):
                return p
    return None


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_attempted
    if _load_attempted:
        return _lib
    _load_attempted = True
    path = _lib_path()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(path)
        lib.lo_csv_parse.restype = ctypes.c_void_p
        lib.lo_csv_parse.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_int, ctypes.c_int]
        lib.lo_csv_ncols.restype = ctypes.c_int
        lib.lo_csv_ncols.argtypes = [ctypes.c_void_p]
        lib.lo_csv_nrows.restype = ctypes.c_long
        lib.lo_csv_nrows.argtypes = [ctypes.c_void_p]
        lib.lo_csv_col_name.restype = ctypes.c_char_p
        lib.lo_csv_col_name.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.lo_csv_col_kind.restype = ctypes.c_int
        lib.lo_csv_col_kind.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.lo_csv_col_f64.restype = ctypes.POINTER(ctypes.c_double)
        lib.lo_csv_col_f64.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.lo_csv_col_i64.restype = ctypes.POINTER(ctypes.c_int64)
        lib.lo_csv_col_i64.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.lo_csv_col_offsets.restype = ctypes.POINTER(ctypes.c_int32)
        lib.lo_csv_col_offsets.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.lo_csv_col_strdata.restype = ctypes.c_void_p
        lib.lo_csv_col_strdata.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.lo_csv_col_validity.restype = ctypes.c_void_p
        lib.lo_csv_col_validity.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.lo_csv_free.restype = None
        lib.lo_csv_free.argtypes = [ctypes.c_void_p]
        # No argtypes: called with bytes (char*) or with a from_buffer
        # view over a bytearray (zero-copy splitter path).
        lib.lo_csv_record_split.restype = ctypes.c_long
        _lib = lib
    except (OSError, AttributeError):
        # AttributeError: a stale pre-rebuild .so missing the new symbols.
        _lib = None
    return _lib


def available() -> bool:
    return _load() is not None


def record_split(data: bytes) -> int:
    """Index of the last newline terminating a complete CSV record (even
    quote parity), -1 if none — native-speed core of the block splitter."""
    lib = _load()
    assert lib is not None, "native parser not built"
    return lib.lo_csv_record_split(data, ctypes.c_size_t(len(data)))


def record_split_buffer(buf: bytearray, n: int) -> int:
    """record_split over the first ``n`` bytes of a bytearray WITHOUT
    copying — the splitter scans its accumulation buffer in place (the
    windows are tens of MB; two memcpys per block were measurable)."""
    lib = _load()
    assert lib is not None, "native parser not built"
    view = (ctypes.c_char * n).from_buffer(buf)
    try:
        return lib.lo_csv_record_split(view, ctypes.c_size_t(n))
    finally:
        del view  # release the exported buffer so `del buf[:k]` can resize


class _ParseHandle:
    """Owner of a native parse result. The RecordBatch built over the
    handle's buffers holds this object as every buffer's base, so the C++
    Table is freed exactly when the last reference (batch, or a numpy view
    of one of its columns) dies."""

    __slots__ = ("_free", "_h")

    def __init__(self, lib, h):
        self._free = lib.lo_csv_free
        self._h = h

    def __del__(self):
        try:
            self._free(self._h)
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass


def _addr(ptr) -> int:
    return ctypes.cast(ptr, ctypes.c_void_p).value or 0


def parse_csv_block_arrow(data: bytes,
                          names: Optional[List[str]] = None):
    """Parse a CSV byte block into a ``pyarrow.RecordBatch`` ZERO-COPY:
    every column buffer (numeric values, string offsets/data/validity) is
    adopted in place from the C++ parse result via ``pa.foreign_buffer``,
    with the parse handle as owner. No per-cell work, no memcpy.

    With ``names``, the block is headerless (a resumed or split block) and
    columns take the given names positionally; otherwise the first record
    is the header. Empty cells are nulls in string columns and NaN in
    float columns; all-integral no-missing numeric columns come back
    int64 (pandas/reference inference, database.py:163-168).
    """
    import pyarrow as pa

    lib = _load()
    assert lib is not None, "native parser not built"
    # `names is not None`: an empty list still means "headerless" (the
    # caller is naming columns positionally, it just has none to name).
    # The names' count is passed as the expected width so a ragged FIRST
    # record can't shrink the block's schema — every record pads or
    # truncates to it, exactly as the header (or pandas names=) would.
    handle = lib.lo_csv_parse(data, len(data),
                              0 if names is not None else 1,
                              len(names) if names else 0)
    if not handle:
        raise ValueError("native CSV parse failed")
    owner = _ParseHandle(lib, handle)
    ncols = lib.lo_csv_ncols(handle)
    nrows = lib.lo_csv_nrows(handle)
    empty = pa.py_buffer(b"")
    arrays, out_names = [], []
    for c in range(ncols):
        if names is not None and c < len(names):
            name = names[c]
        else:
            name = lib.lo_csv_col_name(handle, c).decode("utf-8")
        kind = lib.lo_csv_col_kind(handle, c)
        if kind == 2:
            offs_ptr = lib.lo_csv_col_offsets(handle, c)
            total = int(np.ctypeslib.as_array(offs_ptr,
                                              shape=(nrows + 1,))[-1]) \
                if nrows else 0
            offs_buf = (pa.foreign_buffer(_addr(offs_ptr), 4 * (nrows + 1),
                                          base=owner) if nrows else empty)
            data_addr = lib.lo_csv_col_strdata(handle, c)
            data_buf = (pa.foreign_buffer(data_addr, total, base=owner)
                        if total else empty)
            valid_buf = (pa.foreign_buffer(
                _addr(lib.lo_csv_col_validity(handle, c)),
                (nrows + 7) // 8, base=owner) if nrows else empty)
            arr = pa.Array.from_buffers(
                pa.utf8(), nrows, [valid_buf, offs_buf, data_buf])
        else:
            ptr = (lib.lo_csv_col_i64(handle, c) if kind == 1
                   else lib.lo_csv_col_f64(handle, c))
            buf = (pa.foreign_buffer(_addr(ptr), 8 * nrows, base=owner)
                   if nrows else empty)
            arr = pa.Array.from_buffers(
                pa.int64() if kind == 1 else pa.float64(), nrows,
                [None, buf])
        arrays.append(arr)
        out_names.append(name)
    return pa.RecordBatch.from_arrays(arrays, names=out_names)


def parse_csv_bytes(data: bytes, has_header: bool = True) -> dict:
    """Parse a complete CSV byte buffer into {name: np.ndarray} (numeric
    dtypes or object-with-None strings — the catalog's column domain)."""
    batch = parse_csv_block_arrow(data, names=None if has_header else [])
    out = {}
    for name, col in zip(batch.schema.names, batch.columns):
        out[name] = col.to_numpy(zero_copy_only=False)
    return out


def _record_split_py(buf, n: Optional[int] = None) -> int:
    """Python fallback for record_split over ``buf[:n]`` using C-speed
    primitives with explicit bounds (no copies — the window is tens of
    MB): try the rightmost newlines and verify even quote parity via
    count()."""
    if n is None:
        n = len(buf)
    if buf.find(b'"', 0, n) < 0:
        return buf.rfind(b"\n", 0, n)
    end = n
    while True:
        cut = buf.rfind(b"\n", 0, end)
        if cut < 0:
            return -1
        if buf.count(b'"', 0, cut) % 2 == 0:
            return cut
        end = cut


def parse_csv_chunks(fileobj, chunk_rows: int) -> Iterator[dict]:
    """Chunked parse over a stream: reads record-aligned byte blocks and
    feeds them to the native parser, re-attaching the header to every block."""
    header = fileobj.readline()
    if not header:
        return
    approx_row = max(len(header), 32)
    # Honor small configured chunk sizes (out-of-core tests/budgeted ingest
    # rely on chunk granularity); the default 65536-row config still reads
    # >=2 MiB blocks per native call.
    target = max(chunk_rows * approx_row, 1 << 12)
    carry = b""
    while True:
        block = fileobj.read(target)
        if not block:
            if carry.strip():
                yield parse_csv_bytes(header + carry)
            return
        block = carry + block
        cut = record_split(block)
        if cut < 0:
            carry = block
            continue
        carry = block[cut + 1:]
        chunk = block[:cut + 1]
        if chunk.strip():
            yield parse_csv_bytes(header + chunk)
