"""ctypes binding to the native C++ CSV tokenizer (``native/csv_parser.cpp``).

The reference's native horsepower lived in the external Spark JVM
(SURVEY.md §2); this framework's native tier is first-party C++. The parser
tokenizes CSV bytes into per-column buffers with SIMD-friendly scanning and
returns numeric columns as contiguous float64 buffers consumed zero-copy by
numpy. Falls back to pandas when the shared library has not been built
(``make -C native`` builds it; tests cover both paths).
"""

from __future__ import annotations

import ctypes
import os
from typing import Iterator, Optional

import numpy as np

_LIB_NAMES = ("libcsv_parser.so",)
_lib: Optional[ctypes.CDLL] = None
_load_attempted = False


def _lib_path() -> Optional[str]:
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    for name in _LIB_NAMES:
        for sub in ("native", "native/build"):
            p = os.path.join(root, sub, name)
            if os.path.isfile(p):
                return p
    return None


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_attempted
    if _load_attempted:
        return _lib
    _load_attempted = True
    path = _lib_path()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(path)
        lib.lo_csv_parse.restype = ctypes.c_void_p
        lib.lo_csv_parse.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_int]
        lib.lo_csv_ncols.restype = ctypes.c_int
        lib.lo_csv_ncols.argtypes = [ctypes.c_void_p]
        lib.lo_csv_nrows.restype = ctypes.c_long
        lib.lo_csv_nrows.argtypes = [ctypes.c_void_p]
        lib.lo_csv_col_name.restype = ctypes.c_char_p
        lib.lo_csv_col_name.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.lo_csv_col_is_numeric.restype = ctypes.c_int
        lib.lo_csv_col_is_numeric.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.lo_csv_col_numeric.restype = ctypes.POINTER(ctypes.c_double)
        lib.lo_csv_col_numeric.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.lo_csv_cell_str.restype = ctypes.c_char_p
        lib.lo_csv_cell_str.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_long]
        lib.lo_csv_free.restype = None
        lib.lo_csv_free.argtypes = [ctypes.c_void_p]
        _lib = lib
    except OSError:
        _lib = None
    return _lib


def available() -> bool:
    return _load() is not None


def parse_csv_bytes(data: bytes, has_header: bool = True) -> dict:
    """Parse a complete CSV byte buffer into {name: np.ndarray}."""
    lib = _load()
    assert lib is not None, "native parser not built"
    handle = lib.lo_csv_parse(data, len(data), 1 if has_header else 0)
    if not handle:
        raise ValueError("native CSV parse failed")
    try:
        ncols = lib.lo_csv_ncols(handle)
        nrows = lib.lo_csv_nrows(handle)
        out = {}
        for c in range(ncols):
            name = lib.lo_csv_col_name(handle, c).decode("utf-8")
            if lib.lo_csv_col_is_numeric(handle, c):
                ptr = lib.lo_csv_col_numeric(handle, c)
                arr = np.ctypeslib.as_array(ptr, shape=(nrows,)).copy()
                # Integral float columns → int64, matching pandas/reference
                # inference (database.py:163-168 float→int when integral).
                if arr.size and not np.isnan(arr).any() \
                        and np.all(arr == np.floor(arr)):
                    arr = arr.astype(np.int64)
                out[name] = arr
            else:
                vals = []
                for r in range(nrows):
                    cell = lib.lo_csv_cell_str(handle, c, r)
                    s = cell.decode("utf-8") if cell is not None else None
                    vals.append(None if s == "" or s is None else s)
                out[name] = np.array(vals, dtype=object)
        return out
    finally:
        lib.lo_csv_free(handle)


def _record_split(block: bytes) -> int:
    """Last newline index that terminates a complete CSV *record* — i.e. a
    newline at even quote parity, so RFC-4180 quoted fields containing
    embedded newlines are never cut mid-record. Returns -1 if none."""
    cut = -1
    in_quotes = False
    for i, b in enumerate(block):
        if b == 0x22:  # '"' — doubled quotes inside fields flip twice: no-op
            in_quotes = not in_quotes
        elif b == 0x0A and not in_quotes:
            cut = i
    return cut


def parse_csv_chunks(fileobj, chunk_rows: int) -> Iterator[dict]:
    """Chunked parse over a stream: reads record-aligned byte blocks and
    feeds them to the native parser, re-attaching the header to every block."""
    header = fileobj.readline()
    if not header:
        return
    approx_row = max(len(header), 32)
    # Honor small configured chunk sizes (out-of-core tests/budgeted ingest
    # rely on chunk granularity); the default 65536-row config still reads
    # >=2 MiB blocks per native call.
    target = max(chunk_rows * approx_row, 1 << 12)
    carry = b""
    while True:
        block = fileobj.read(target)
        if not block:
            if carry.strip():
                yield parse_csv_bytes(header + carry)
            return
        block = carry + block
        cut = _record_split(block)
        if cut < 0:
            carry = block
            continue
        carry = block[cut + 1:]
        chunk = block[:cut + 1]
        if chunk.strip():
            yield parse_csv_bytes(header + chunk)
