"""Async job manager — the framework's completion/failure protocol.

The reference's async model: an HTTP request returns 201 immediately, work
continues on daemon threads, and completion is signaled *only* by the
dataset's metadata ``finished`` flag flipping true, which clients poll every
3 s (reference database.py:199-216, client __init__.py:14-32). There is no
failure signal — a crashed job leaves ``finished: false`` forever
(SURVEY.md §5).

This manager keeps the same observable contract (request returns, poll the
metadata) and adds: a job registry with status/timing, guaranteed terminal
state (``finished`` always flips, with ``error`` set on failure), and a
bounded worker pool replacing unbounded daemon-thread spawning.
"""

from __future__ import annotations

import contextvars
import json
import threading
import time
import traceback
from concurrent.futures import Future, ThreadPoolExecutor
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from learningorchestra_tpu.utils import failpoints, tracing
from learningorchestra_tpu.utils.profiling import op_timer
from learningorchestra_tpu.utils.structlog import get_logger

log = get_logger("jobs")

#: Deterministic fault-injection site: the head of every progress mark
#: (``heartbeat``) — ``hang``/``slow`` here simulates a wedge at a
#: round/pass boundary, which is exactly what the watchdog must catch.
FP_JOB_PRE_HEARTBEAT = failpoints.declare("job.pre_heartbeat")

#: The currently-running job's record: its body (and anything it calls
#: on the same thread) records profiling counters — streamed-fit pass
#: counts, per-family device seconds — that surface on the job's /jobs
#: doc. A ContextVar, not a thread-local: the JobManager pool thread
#: owns the context for the job's whole body.
_job_record: contextvars.ContextVar = contextvars.ContextVar(
    "lo_job_record", default=None)

#: Serializes profile merges: watermark updates arrive from concurrent
#: family threads (builder's pipelined sweep) and from the SPMD span
#: drain, and a lost read-modify-write would silently drop a family's
#: entry. Every merge still publishes a FRESH dict (never mutates the
#: published one), so /jobs listings stay safe to copy lock-free.
_profile_lock = threading.Lock()


def current_job_record():
    """The ambient managed-job record, or None outside one — capture it
    before fanning work out to a thread pool (pool threads carry no
    ContextVar context) and re-attach with :func:`attach_job_record`,
    the same discipline as ``tracing.attach``."""
    return _job_record.get()


@contextmanager
def attach_job_record(rec):
    """Make an explicitly captured job record ambient on this thread, so
    profile/watermark recording from fan-out threads (the builder's
    per-family fit threads) lands on the right job. None = no-op."""
    if rec is None:
        yield
        return
    token = _job_record.set(rec)
    try:
        yield
    finally:
        _job_record.reset(token)


def record_job_profile(**entries: Any) -> None:
    """Merge profiling metadata into the current job's record (no-op when
    called outside a managed job, e.g. from the synchronous test path).
    Publishes by swapping in a fresh merged dict — never mutating the
    published one in place — so a concurrent /jobs listing copying
    ``profile`` can never see it change size mid-iteration."""
    rec = _job_record.get()
    if rec is not None:
        with _profile_lock:
            rec.profile = {**rec.profile, **entries}


def record_job_watermarks(*, peak_hbm_bytes: Optional[int] = None,
                          compile_s: Optional[float] = None,
                          host_rss_delta: Optional[int] = None,
                          family: Optional[str] = None,
                          family_stats: Optional[Dict[str, Any]] = None
                          ) -> None:
    """Merge resource watermarks into the current job's profile with
    watermark semantics (utils/resources.py is the sampler): peaks
    max-merge, ``compile_s`` max-merges too (phase deltas are subsets of
    the whole-job window, so the largest observed window wins — never a
    double-counting sum), ``host_rss_delta`` takes the latest whole-job
    figure, and per-family ``fit_resources`` entries accumulate
    (compile sums across a family's phases, peak maxes). No-op outside
    a managed job."""
    rec = _job_record.get()
    if rec is None:
        return
    with _profile_lock:
        prof = dict(rec.profile)
        if peak_hbm_bytes is not None:
            prof["peak_hbm_bytes"] = max(
                int(peak_hbm_bytes), int(prof.get("peak_hbm_bytes", 0)))
        if compile_s is not None:
            prof["compile_s"] = round(
                max(float(compile_s), float(prof.get("compile_s", 0.0))), 6)
        if host_rss_delta is not None:
            prof["host_rss_delta"] = int(host_rss_delta)
        if family is not None and family_stats:
            fr = dict(prof.get("fit_resources", {}))
            ent = dict(fr.get(family, {"compile_s": 0.0,
                                       "peak_hbm_bytes": 0}))
            ent["compile_s"] = round(
                float(ent.get("compile_s", 0.0))
                + float(family_stats.get("compile_s", 0.0)), 6)
            ent["peak_hbm_bytes"] = max(
                int(ent.get("peak_hbm_bytes", 0)),
                int(family_stats.get("peak_hbm_bytes", 0)))
            fr[family] = ent
            prof["fit_resources"] = fr
        rec.profile = prof

#: Job-tier fault counters (process-wide, monotone — the alert engine
#: reads deltas): watchdog kills and checkpoint resumes. Module-level so
#: trainers/preprocess can count a resume without holding a JobManager.
_fault_lock = threading.Lock()
_fault = {"watchdog_fired_total": 0, "jobs_resumed_total": 0}


def fault_snapshot() -> Dict[str, int]:
    """The ``job_fault`` section of ``/metrics``."""
    with _fault_lock:
        return dict(_fault)


def heartbeat() -> None:
    """Progress mark: the running job is ALIVE and advancing. Called at
    natural boundaries — gb boost-round/checkpoint batches, rf tree
    batches, mlp iteration segments, streamed-fit pass boundaries, SPMD
    dispatch round completion — it resets the watchdog's liveness clock
    (``LO_TPU_JOB_DEADLINE_S`` bounds the gap BETWEEN marks, so a slow
    but progressing fit survives while a wedged program dies). No-op
    outside a managed job."""
    failpoints.fire(FP_JOB_PRE_HEARTBEAT)
    rec = _job_record.get()
    if rec is not None:
        rec.progress_mono = time.monotonic()


def record_job_resume(label: str, doc: Dict[str, Any]) -> None:
    """A fit (or the streamed design fit) resumed from a checkpoint:
    count it and surface the provenance on the job profile as
    ``resumed_from[label]`` (round/pass reached, writing epoch) so
    ``/jobs`` shows what a retry actually skipped."""
    with _fault_lock:
        _fault["jobs_resumed_total"] += 1
    rec = _job_record.get()
    if rec is None:
        return
    with _profile_lock:
        prof = dict(rec.profile)
        resumed = dict(prof.get("resumed_from", {}))
        resumed[label] = dict(doc)
        prof["resumed_from"] = resumed
        rec.profile = prof


#: Error prefixes marking a job killed by INFRASTRUCTURE — a pod worker
#: death (watchdog flag, parallel/spmd.py) or a process restart mid-job
#: (catalog load_all) — rather than by its own inputs. Only these are
#: safe and useful to retry automatically: a deterministic input error
#: would just fail identically again.
RETRYABLE_ERROR_PREFIXES = ("pod failure:", "interrupted:")


def select_retry_groups(docs: List[Dict[str, Any]],
                        max_retries: int) -> List[Dict[str, Any]]:
    """Pick the failed jobs worth re-running after a restart.

    ``docs`` are catalog metadata docs (``DatasetStore.metadata_docs``).
    A dataset is retryable when it reached a terminal FAILED state from an
    infrastructure cause (:data:`RETRYABLE_ERROR_PREFIXES`), carries the
    ``job`` spec the serving layer recorded at submission (enough to
    re-run it), and has been retried fewer than ``max_retries`` times.
    Datasets sharing one job spec (a model build owns one prediction
    dataset per classifier) group into a single re-run. Returns
    ``[{"spec": job_spec, "datasets": [names...]}, ...]``.
    """
    groups: Dict[str, Dict[str, Any]] = {}
    for doc in docs:
        err = doc.get("error")
        if not doc.get("finished") or not err:
            continue
        if not any(err.startswith(p) for p in RETRYABLE_ERROR_PREFIXES):
            continue
        spec = doc.get("job")
        if not isinstance(spec, dict) or "kind" not in spec:
            continue
        if int(doc.get("retries", 0) or 0) >= max_retries:
            continue
        key = json.dumps(spec, sort_keys=True, default=str)
        group = groups.setdefault(key, {"spec": spec, "datasets": []})
        group["datasets"].append(doc["filename"])
    return list(groups.values())


@dataclass
class JobRecord:
    job_id: str
    dataset: str
    kind: str
    status: str = "running"          # running | done | failed
    error: Optional[str] = None
    started_at: float = field(default_factory=time.time)
    finished_at: Optional[float] = None
    #: The job's trace id: the submitting HTTP request's trace when one
    #: was ambient at submit (one trace spans accept → job completion),
    #: else freshly minted — either way, ``GET /trace/{id}`` resolves it.
    trace_id: Optional[str] = None
    #: Profiling metadata the job body recorded (record_job_profile):
    #: streamed-fit pass counts, per-family device_s, ...
    profile: Dict[str, Any] = field(default_factory=dict)
    #: Liveness deadline (seconds of no progress before the watchdog
    #: fails the job); None/0 = unbounded (today's behavior).
    deadline_s: Optional[float] = None
    #: Monotonic clock of the last progress mark (``heartbeat``).
    progress_mono: float = field(default_factory=time.monotonic)
    #: The body actually began executing: the watchdog only judges
    #: STARTED jobs — pool queue-wait is a capacity condition, not a
    #: hung device program, and must never poison the pod.
    body_started: bool = False

    def to_doc(self) -> Dict[str, Any]:
        doc = {
            "job_id": self.job_id, "dataset": self.dataset, "kind": self.kind,
            "status": self.status, "error": self.error,
            "started_at": self.started_at, "finished_at": self.finished_at,
            "duration": (self.finished_at or time.time()) - self.started_at,
            "trace_id": self.trace_id,
        }
        if self.deadline_s:
            doc["deadline_s"] = self.deadline_s
        if self.profile:
            doc["profile"] = dict(self.profile)
        return doc


class JobManager:
    """Bounded-pool async job runner with per-dataset failure recording."""

    #: Terminal job records kept for /jobs observability; oldest evicted
    #: beyond this so a long-lived server doesn't leak a record per job.
    MAX_RECORDS = 1000

    #: Watchdog scan cadence, seconds — cheap (a lock + a few clock
    #: reads per running job) and fine-grained enough for sub-second
    #: test deadlines.
    WATCHDOG_POLL_S = 0.1

    def __init__(self, store, max_workers: int = 8, cfg=None):
        from learningorchestra_tpu.config import settings as global_settings

        self.store = store
        self.cfg = cfg or global_settings
        self._pool = ThreadPoolExecutor(max_workers=max_workers,
                                        thread_name_prefix="lo-job")
        self._lock = threading.Lock()
        self._jobs: Dict[str, JobRecord] = {}
        self._seq = 0
        self._watchdog_started = False

    # -- the device-program watchdog ----------------------------------------

    def _ensure_watchdog(self) -> None:
        """Start the liveness watchdog lazily on the first deadline'd
        job — a server with LO_TPU_JOB_DEADLINE_S unset never spawns the
        thread at all."""
        with self._lock:
            if self._watchdog_started:
                return
            self._watchdog_started = True
        # thread-lifecycle: owner=JobManager; daemon scan loop that
        # lives for the process (the manager has no shutdown seam and
        # the loop only reads/flips job records); exceptions are caught
        # per scan so the sanitizer never sees it die.
        threading.Thread(target=self._watchdog_loop, daemon=True,
                         name="lo-job-watchdog").start()

    def _watchdog_loop(self) -> None:
        while True:
            time.sleep(self.WATCHDOG_POLL_S)
            try:
                self._watchdog_scan()
            except Exception:  # noqa: BLE001 — the watchdog must outlive bugs
                log.exception("job watchdog scan failed")

    def _watchdog_scan(self) -> None:
        now = time.monotonic()
        expired: List[JobRecord] = []
        with self._lock:
            for rec in self._jobs.values():
                if (rec.status != "running" or not rec.deadline_s
                        or not rec.body_started):
                    continue
                if now - rec.progress_mono > rec.deadline_s:
                    rec.status = "failed"
                    rec.error = (
                        f"interrupted: watchdog: job {rec.job_id} "
                        f"({rec.kind}) made no progress for "
                        f"{rec.deadline_s:.1f}s — device program "
                        "presumed hung")
                    rec.finished_at = time.time()
                    expired.append(rec)
        for rec in expired:
            self._expire(rec)

    def _expire(self, rec: JobRecord) -> None:
        """Post-transition actions for one watchdog-killed job: pollable
        failure records (the retryable ``interrupted:`` prefix — the
        restarted pod's rescan re-runs the job, which then resumes from
        its fit checkpoint), pod poison (the PR 2 machinery: the
        supervisor's health poll sees the degradation and restarts the
        pod under a fresh mesh epoch, which is what actually tears down
        the hung program), and a flight-recorder evidence bundle. The
        hung thread itself cannot be killed from Python — bounding its
        damage is the supervisor restart's job."""
        from learningorchestra_tpu.parallel import spmd
        from learningorchestra_tpu.utils import flightrec

        with _fault_lock:
            _fault["watchdog_fired_total"] += 1
        log.error("%s", rec.error)
        for name in [n for n in rec.dataset.split(",") if n]:
            try:
                if not self.store.get(name).metadata.finished:
                    self.store.fail(name, rec.error)
            except Exception:  # noqa: BLE001 — best-effort flagging
                pass
        spmd.poison_pod(f"watchdog: job {rec.job_id} ({rec.kind}) hung "
                        f"past its {rec.deadline_s:.1f}s deadline")
        flightrec.incident(
            "job:watchdog",
            detail={"job_id": rec.job_id, "kind": rec.kind,
                    "dataset": rec.dataset,
                    "deadline_s": rec.deadline_s,
                    "trace_id": rec.trace_id})
        op_timer.record(f"job.{rec.kind}",
                        rec.finished_at - rec.started_at)

    def _settle(self, rec: JobRecord, status: str,
                error: Optional[str] = None) -> bool:
        """Atomically move a RUNNING record to a terminal state; False
        when something else (the watchdog) already terminated it — the
        woken-up job body must never overwrite the watchdog's verdict
        (or resurrect a job whose datasets were already failed)."""
        with self._lock:
            if rec.status != "running":
                return False
            rec.status = status
            rec.error = error
            return True

    def submit(self, kind: str, dataset,
               fn: Callable[[], Any]) -> JobRecord:
        """Run ``fn`` async. On exception, mark the job's dataset(s) failed
        in the catalog (finished=True + error) so pollers terminate.

        ``dataset`` may be one name or a sequence of names — a model build
        owns one prediction dataset per classifier and all of them must
        reach a terminal state if the job dies before (or after) creating
        them.
        """
        datasets: List[str] = ([dataset] if isinstance(dataset, str)
                               else list(dataset))
        deadline_s = float(self.cfg.job_deadline_s or 0.0) or None
        # Capture the submitting thread's trace position NOW: the pool
        # thread running the job has no ambient context of its own, and
        # the HTTP request whose handler submitted us will be long gone.
        parent_ctx = tracing.current()
        with self._lock:
            self._seq += 1
            rec = JobRecord(job_id=f"{kind}-{self._seq}",
                            dataset=",".join(datasets), kind=kind,
                            trace_id=(parent_ctx.trace_id if parent_ctx
                                      else tracing.new_id()),
                            deadline_s=deadline_s)
            self._jobs[rec.job_id] = rec
            if len(self._jobs) > self.MAX_RECORDS:
                for jid, r in list(self._jobs.items()):
                    if len(self._jobs) <= self.MAX_RECORDS:
                        break
                    if r.status != "running":
                        del self._jobs[jid]

        def _fail_datasets():
            for name in datasets:
                # Only unfinished datasets get the failure flag — ones
                # that completed before the crash keep their results.
                try:
                    if not self.store.get(name).metadata.finished:
                        self.store.fail(name, rec.error)
                except Exception:
                    pass

        def run():
            from learningorchestra_tpu.parallel.spmd import PodDegraded

            token = _job_record.set(rec)
            # The liveness clock starts HERE, not at submit: time spent
            # queued behind the bounded pool never reads as a hang.
            rec.progress_mono = time.monotonic()
            rec.body_started = True
            settled = False
            try:
                # The job's root span: joins the submitting request's
                # trace when one was ambient, else roots a new trace
                # under rec.trace_id. Everything the job body records
                # (design.build, fit.*, journal.commit, worker-process
                # spans over the SPMD channel) nests under it; a raise
                # marks the span status=error before the handling below.
                # resources.job_phase is the resource-sampling seam:
                # every managed job's profile carries peak_hbm_bytes /
                # compile_s / host_rss_delta, refined mid-job by the
                # builder's per-phase samples (utils/resources.py).
                from learningorchestra_tpu import config
                from learningorchestra_tpu.utils import resources

                with tracing.job_trace(
                        f"job.{kind}", trace_id=rec.trace_id,
                        parent=parent_ctx,
                        attrs={"kind": kind, "dataset": rec.dataset,
                               "job_id": rec.job_id,
                               "mesh_epoch": config.mesh_epoch()}), \
                        resources.job_phase():
                    fn()
                settled = self._settle(rec, "done")
            except PodDegraded as exc:
                # A job refused (or interrupted) because the pod is
                # degraded failed from INFRASTRUCTURE, exactly like one
                # the watchdog flagged — record it under the retryable
                # prefix so the restarted pod's rescan re-runs it, e.g.
                # a build queued behind the one whose worker died.
                settled = self._settle(rec, "failed",
                                       f"pod failure: {exc}")
                traceback.print_exc()
                if settled:
                    _fail_datasets()
            except Exception as exc:  # noqa: BLE001 — job boundary
                settled = self._settle(rec, "failed",
                                       f"{type(exc).__name__}: {exc}")
                traceback.print_exc()
                if settled:
                    _fail_datasets()
            finally:
                _job_record.reset(token)
                # A record the watchdog already terminated keeps its
                # verdict (and its finished_at — the moment the OPERATOR
                # learned the job died, not the moment the hung thread
                # finally woke up).
                if settled:
                    rec.finished_at = time.time()
                    op_timer.record(f"job.{kind}",
                                    rec.finished_at - rec.started_at)

        if deadline_s:
            self._ensure_watchdog()
        future: Future = self._pool.submit(run)
        rec._future = future  # type: ignore[attr-defined]
        return rec

    def wait_all(self, timeout: Optional[float] = None) -> None:
        """Block until all submitted jobs reach a terminal state (tests)."""
        deadline = None if timeout is None else time.time() + timeout
        for rec in list(self._jobs.values()):
            fut = getattr(rec, "_future", None)
            if fut is not None:
                remaining = None if deadline is None else max(
                    0.0, deadline - time.time())
                fut.result(timeout=remaining)

    def records(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [r.to_doc() for r in self._jobs.values()]

    def running_count(self) -> int:
        """Jobs not yet terminal (includes pool-queued ones — their
        record is minted "running" at submit): the drain loop's quiesce
        probe for the job plane."""
        with self._lock:
            return sum(1 for r in self._jobs.values()
                       if r.status == "running")
