"""Typed configuration for the framework.

The reference configures everything through env vars scattered across
Dockerfiles and docker-compose service blocks with no validation layer
(reference docker-compose.yml:23-25,188-192; model_builder_image/Dockerfile:8-13).
Here a single dataclass holds every knob, reads the environment once, and is
importable everywhere — the "typed pydantic-style settings" upgrade called for
in SURVEY.md §7 without taking a pydantic dependency.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field, fields
from typing import Optional


def _env(name: str, default, cast=None):
    raw = os.environ.get(name)
    if raw is None:
        return default
    if cast is None:
        cast = type(default) if default is not None else str
    if cast is bool:
        return raw.lower() in ("1", "true", "yes", "on")
    return cast(raw)


@dataclass
class Settings:
    """All framework knobs, env-overridable with the ``LO_TPU_`` prefix."""

    # --- storage -----------------------------------------------------------
    #: On-disk root for persisted datasets (parquet + metadata.json). The
    #: catalog always keeps hot data in host RAM; this is the durability tier
    #: replacing the reference's MongoDB volumes (docker-compose.yml:335-340).
    store_root: str = field(
        default_factory=lambda: _env("LO_TPU_STORE_ROOT", "/tmp/lo_tpu_store")
    )
    #: Persist datasets to disk on every commit (finished-flip).
    persist: bool = field(default_factory=lambda: _env("LO_TPU_PERSIST", True, bool))
    #: Soft cap (MiB) on column data resident in host RAM *per dataset*;
    #: 0 = unlimited. Over budget, chunks flush to immutable parquet chunk
    #: files and are evicted — the out-of-core tier replacing the
    #: reference's disk-backed Mongo collections (database.py:133-216).
    ram_budget_mb: int = field(
        default_factory=lambda: _env("LO_TPU_RAM_BUDGET_MB", 0)
    )
    #: Force the shard-local streamed design-matrix path for every build
    #: (ops/preprocess.ChunkedDesign). Default off: builds stream
    #: automatically when a dataset is over its RAM budget; this knob
    #: forces it for testing / for pods whose datasets fit in RAM but
    #: whose operators still want per-process residency divided by
    #: process count.
    stream_design: bool = field(
        default_factory=lambda: _env("LO_TPU_STREAM_DESIGN", False, bool)
    )
    #: Optional second directory mirroring every committed dataset (chunk
    #: files + journal + metadata). Standing in for the reference's Mongo
    #: primary/secondary replica set (docker-compose.yml:27-91): if the
    #: primary store_root is lost, load_all() restores from the replica.
    replica_root: str = field(
        default_factory=lambda: _env("LO_TPU_REPLICA_ROOT", "")
    )
    #: Comma-separated ``host:port`` list of peer replica servers
    #: (catalog/replicate.py). Each committed journal prefix is pushed to
    #: every peer by an async single-slot committer; `_repair_chunk` adds
    #: a CRC-verified remote fetch rung so reads heal whole-host loss
    #: through the same ChunkCorrupt path as local bit-rot. Empty (the
    #: default) keeps replica_root-only behavior byte-for-byte unchanged.
    replica_peers: str = field(
        default_factory=lambda: _env("LO_TPU_REPLICA_PEERS", "")
    )
    #: Port for this host's ReplicaServer (the receive side of the
    #: replication plane). 0 (default) does not start one — set it on
    #: every host that should hold peers' replicas. Bound on
    #: LO_TPU_HOST.
    replica_port: int = field(
        default_factory=lambda: _env("LO_TPU_REPLICA_PORT", 0)
    )
    #: Socket timeout, seconds, for every replication frame exchange
    #: (push, fetch, probe). A dead peer costs at most this long per
    #: attempt before the push is recorded as failed and the dataset
    #: counted under-replicated.
    replica_timeout_s: float = field(
        default_factory=lambda: _env("LO_TPU_REPLICA_TIMEOUT_S", 10.0)
    )
    #: Minimum seconds between re-push attempts for an under-replicated
    #: dataset. Failed pushes leave the dataset on the push queue's
    #: retry list; each /metrics scrape (or replication_snapshot call)
    #: re-queues datasets whose last attempt is older than this.
    replica_push_retry_s: float = field(
        default_factory=lambda: _env("LO_TPU_REPLICA_PUSH_RETRY_S", 2.0)
    )
    #: Chunks read ahead of the consumer by the prefetching read pipeline
    #: (catalog/readpipe.py): while a streaming consumer (iter_chunks /
    #: snapshot scans) computes on chunk i, a background worker pool
    #: reads + CRC-verifies + decodes chunks i+1..i+K. 0 disables
    #: prefetch entirely — the strictly synchronous read path is kept as
    #: the parity oracle (docs/performance.md).
    prefetch_chunks: int = field(
        default_factory=lambda: _env("LO_TPU_PREFETCH_CHUNKS", 2)
    )
    #: Byte budget for the host-RAM LRU chunk cache shared across
    #: passes/datasets: decoded chunk reads are kept keyed by
    #: (chunk file, journal CRC, field selection) so the second scan of a
    #: streamed-fit pipeline and repeated histogram/projection calls hit
    #: warm memory instead of re-reading disk. 0 disables caching.
    chunk_cache_bytes: int = field(
        default_factory=lambda: _env("LO_TPU_CHUNK_CACHE_BYTES", 256 << 20)
    )
    #: Run a full checksum scrub (DatasetStore.scrub) as part of
    #: load_all's recovery scan: every journaled chunk file is re-read
    #: and verified against its journal CRC32, repairing from the
    #: replica on mismatch. Off by default — it reads every chunk at
    #: startup; the lazy first-read verification covers the default
    #: path, and POST /catalog/scrub runs the same pass on demand.
    scrub_on_load: bool = field(
        default_factory=lambda: _env("LO_TPU_SCRUB_ON_LOAD", False, bool)
    )

    # --- ingestion ---------------------------------------------------------
    #: CSV ingest chunk size (rows) for the streaming loader. Replaces the
    #: reference's 3-thread/queue(1000) row-at-a-time pipeline
    #: (database_api_image/database.py:133-216) with columnar chunks.
    #: 256k rows ≈ 10-20 MB blocks — big enough that per-chunk overheads
    #: (journal record, file open, arrow framing) vanish in the noise.
    ingest_chunk_rows: int = field(
        default_factory=lambda: _env("LO_TPU_INGEST_CHUNK_ROWS", 262144)
    )
    #: HTTP timeout for CSV downloads, seconds.
    download_timeout: float = field(
        default_factory=lambda: _env("LO_TPU_DOWNLOAD_TIMEOUT", 60.0)
    )
    #: Use the native C++ CSV parser when its shared library is built.
    use_native_csv: bool = field(
        default_factory=lambda: _env("LO_TPU_USE_NATIVE_CSV", True, bool)
    )
    #: Parser threads for streaming ingest. Row-aligned byte blocks parse
    #: concurrently (the native parser releases the GIL for the whole
    #: call); chunks still commit in source order. 0 = automatic:
    #: os.cpu_count() clamped to [4, 8] (a few threads pay even on one
    #: core by overlapping the committer's IO waits; beyond 8 the
    #: in-order committer is the bottleneck).
    ingest_parse_threads: int = field(
        default_factory=lambda: _env("LO_TPU_INGEST_PARSE_THREADS", 0)
    )
    #: Commit (journal-fsync + metadata write) cadence for streaming
    #: ingest, in bytes of parsed chunk data; chunks batch up to this many
    #: bytes per store.save. 0 = commit every chunk (max durability).
    ingest_commit_bytes: int = field(
        default_factory=lambda: _env("LO_TPU_INGEST_COMMIT_BYTES", 64 << 20)
    )
    #: Range-partitioned ingest: split the source byte range into this
    #: many per-host partitions fetched/parsed/journaled concurrently
    #: (catalog/ingest.py). 0 or 1 = today's single-stream path,
    #: byte-for-byte. Only applies when the source advertises its length
    #: (HEAD Content-Length, or file size); unsized sources fall back to
    #: the serial path.
    ingest_partitions: int = field(
        default_factory=lambda: _env("LO_TPU_INGEST_PARTITIONS", 0)
    )
    #: Minimum partition size in bytes: sources smaller than
    #: 2 * this never split (a second ranged connection costs more than
    #: it overlaps on small files).
    ingest_partition_min_bytes: int = field(
        default_factory=lambda: _env("LO_TPU_INGEST_PARTITION_MIN_BYTES",
                                     4 << 20)
    )

    # --- kernels -----------------------------------------------------------
    #: Use hand-written Pallas kernels for hot inner loops (t-SNE repulsion;
    #: ops/pallas_kernels.py). Off-TPU they run in interpreter mode, so the
    #: flag is safe everywhere; disable to force the pure-XLA fallbacks.
    use_pallas: bool = field(
        default_factory=lambda: _env("LO_TPU_USE_PALLAS", True, bool)
    )
    #: Route the tree families' (dt/rf/gb) histogram, routing and descent
    #: hot loops through the fused Pallas binned-histogram kernels
    #: (ops/pallas_kernels.py tree_*). ``0`` selects the pure-XLA blocked
    #: contraction path, kept as the bit-parity oracle
    #: (docs/performance.md §tree kernels). Subordinate to ``use_pallas``;
    #: off-TPU the kernels run in interpreter mode so the same code path
    #: is exercised by the CPU-mesh tests.
    tree_kernel: bool = field(
        default_factory=lambda: _env("LO_TPU_TREE_KERNEL", True, bool)
    )

    # --- mesh / parallelism ------------------------------------------------
    #: Mesh axis names. "data" shards rows (the reference's Spark partitioning
    #: axis, SURVEY.md §2 parallelism #1); "model" shards features/params.
    data_axis: str = "data"
    model_axis: str = "model"
    #: Optional forced mesh shape "D,M" or "D,M,S" (data × model × seq);
    #: empty = all local devices on the data axis.
    mesh_shape: str = field(default_factory=lambda: _env("LO_TPU_MESH_SHAPE", ""))

    # --- serving -----------------------------------------------------------
    #: Single service port. The reference runs 7 Flask apps on ports
    #: 5000-5006 (client __init__.py:56-333); here one server hosts all
    #: routers; per-service ports are emulated by path prefixes.
    port: int = field(default_factory=lambda: _env("LO_TPU_PORT", 5000))
    host: str = field(default_factory=lambda: _env("LO_TPU_HOST", "127.0.0.1"))
    #: Page-size cap for dataset reads; reference hard-caps at 20
    #: (database_api_image/server.py:28,69-70).
    read_limit_cap: int = field(default_factory=lambda: _env("LO_TPU_READ_CAP", 20))
    #: Per-connection socket timeout (seconds) on the HTTP server. A
    #: handler thread reading a request body blocks on the client's
    #: socket; without a timeout a hung/dead client that sent a
    #: Content-Length it never delivers wedges that thread forever.
    #: 0 disables (not recommended outside tests).
    http_timeout_s: float = field(
        default_factory=lambda: _env("LO_TPU_HTTP_TIMEOUT_S", 30.0)
    )
    #: Directory where viz services write PNGs (reference volumes
    #: tsne:/images, pca:/images, docker-compose.yml:289-290).
    image_root: str = field(
        default_factory=lambda: _env("LO_TPU_IMAGE_ROOT", "/tmp/lo_tpu_images")
    )
    #: HTTP accept processes. ``1`` (the default) keeps today's
    #: single-process topology byte-for-byte: the device-owning process
    #: serves HTTP itself through the threaded stdlib server. ``N > 1``
    #: binds N lightweight front-end worker processes to the SAME
    #: host:port via ``SO_REUSEPORT`` (the kernel spreads accepted
    #: connections across them, sidestepping the GIL), each running an
    #: async ``selectors`` request loop and forwarding predict rows /
    #: proxied requests to the device-owning process over the
    #: length-prefixed row channel (serving/rowchannel.py,
    #: serving/frontend.py — docs/serving.md §front end).
    http_workers: int = field(
        default_factory=lambda: _env("LO_TPU_HTTP_WORKERS", 1)
    )
    #: Handler threads the device-owning process runs for row-channel
    #: frames from front-end workers — bounds how many forwarded
    #: requests execute concurrently inside the primary (the analogue
    #: of the threaded server's one-thread-per-connection, made
    #: explicit). Only meaningful when ``http_workers > 1``.
    frontend_channel_threads: int = field(
        default_factory=lambda: _env("LO_TPU_FRONTEND_CHANNEL_THREADS", 16)
    )

    # --- online inference (serving/batcher.py, models/aot.py) --------------
    #: Largest coalesced micro-batch (rows) per device dispatch of the
    #: online predict tier — also the top of the AOT padding-bucket
    #: ladder (1/8/64/…/max), so raising it adds compiled programs per
    #: model. Requests carrying more rows than this are rejected 406;
    #: the client SDK splits client-side (Model.predict_online).
    serve_max_batch: int = field(
        default_factory=lambda: _env("LO_TPU_SERVE_MAX_BATCH", 256)
    )
    #: Bound (rows) on each model's predict queue. A request that would
    #: push the queue past this answers 503 + Retry-After — backpressure
    #: the stock client's jittered backoff already honors. 0 disables
    #: the online tier entirely (every /predict answers 503).
    serve_queue_depth: int = field(
        default_factory=lambda: _env("LO_TPU_SERVE_QUEUE_DEPTH", 1024)
    )
    #: Optional coalescing linger (milliseconds): after picking up the
    #: first waiting request, the dispatcher may wait this long for more
    #: rows before dispatching a non-full batch. Default 0 — dispatch
    #: immediately: continuous batching coalesces on its own because the
    #: queue refills while the device runs the previous batch, and a
    #: linger just adds its full length to every batch's latency
    #: whenever traffic can't fill ``serve_max_batch`` within it
    #: (measured: a 2 ms linger cost a 24-worker closed loop ~10x
    #: throughput). Raise it only for sparse open-loop traffic where
    #: trading p50 for occupancy is explicitly wanted.
    serve_max_wait_ms: float = field(
        default_factory=lambda: _env("LO_TPU_SERVE_MAX_WAIT_MS", 0.0)
    )
    #: How long a queued request may wait for its batch result before
    #: answering 503 (dispatcher wedged / overloaded) — bounds handler
    #: threads the same way http_timeout_s bounds the socket.
    serve_timeout_s: float = field(
        default_factory=lambda: _env("LO_TPU_SERVE_TIMEOUT_S", 30.0)
    )
    #: Default end-to-end deadline budget (milliseconds) applied to a
    #: predict request that carries no ``X-Deadline-Ms`` header. 0 = no
    #: implicit deadline (requests wait out ``serve_timeout_s``). A
    #: request whose budget expires — at admission (predicted queue wait
    #: exceeds the remaining budget) or in queue — answers a terminal
    #: 504, and its rows are never dispatched to the device.
    serve_deadline_default_ms: float = field(
        default_factory=lambda: _env("LO_TPU_SERVE_DEADLINE_DEFAULT_MS", 0.0)
    )
    #: Upper clamp (milliseconds) on client-supplied deadline budgets —
    #: a confused client must not park a handler thread for an hour.
    #: 0 disables deadline handling entirely (headers are ignored).
    serve_deadline_cap_ms: float = field(
        default_factory=lambda: _env("LO_TPU_SERVE_DEADLINE_CAP_MS",
                                     600000.0)
    )
    #: Device replicas of the online predict plane: each replica is a
    #: full AOT bucket ladder compiled for (and params resident on) its
    #: own local device, with its own dispatcher thread + bounded queue;
    #: a router sends each request to the replica with the lowest
    #: predicted queue wait. ``1`` (the default) preserves the
    #: single-device topology byte-for-byte (``jax.local_devices()[0]``,
    #: one dispatcher per model — exactly the pre-replication tier);
    #: ``0`` means ALL local devices; ``N`` clamps to the locally
    #: available device count. Quarantine, self-healing, drain and chaos
    #: failpoints are all per-replica — a crashed replica degrades
    #: capacity, not availability.
    serve_replicas: int = field(
        default_factory=lambda: _env("LO_TPU_SERVE_REPLICAS", 1)
    )
    #: Consecutive dispatcher-thread crashes (exceptions escaping the
    #: dispatch loop, not per-request model errors) before a model is
    #: QUARANTINED: its predicts answer a terminal 503 naming the
    #: quarantine instead of endlessly crash-looping, and the
    #: ``serving_quarantined`` alert fires. A successful dispatch resets
    #: the streak; DELETE or re-save (invalidate) lifts the quarantine.
    #: With ``serve_replicas > 1`` the threshold applies PER REPLICA —
    #: one poisoned replica quarantines alone while siblings keep
    #: serving.
    serve_quarantine_crashes: int = field(
        default_factory=lambda: _env("LO_TPU_SERVE_QUARANTINE_CRASHES", 3)
    )
    #: First supervised-restart backoff (seconds) after a dispatcher
    #: crash; doubles per consecutive crash, capped at 5 s so teardown
    #: joins stay bounded.
    serve_restart_backoff_s: float = field(
        default_factory=lambda: _env("LO_TPU_SERVE_RESTART_BACKOFF_S", 0.2)
    )
    #: Graceful-drain window (seconds): on SIGTERM (or a programmatic
    #: ``App.drain``) the server stops admitting new work (503 +
    #: Retry-After + ``Connection: close``), lets in-flight predicts and
    #: queued jobs finish for up to this long, then stops. The
    #: supervisor's planned-restart path (SIGHUP) grants children this
    #: window before escalating to SIGKILL.
    drain_timeout_s: float = field(
        default_factory=lambda: _env("LO_TPU_DRAIN_TIMEOUT_S", 30.0)
    )

    # --- training ----------------------------------------------------------
    #: Max concurrently running model fits (reference: 5 classifiers through
    #: a ThreadPoolExecutor + Spark FAIR pool, model_builder.py:95,160-176).
    max_concurrent_fits: int = field(
        default_factory=lambda: _env("LO_TPU_MAX_CONCURRENT_FITS", 5)
    )
    #: Allow user-supplied preprocessing code via exec(). The reference does
    #: this unconditionally (model_builder.py:145-150); here it is opt-in and
    #: off by default — the declarative preprocessing API is the default path.
    allow_exec_preprocessing: bool = field(
        default_factory=lambda: _env("LO_TPU_ALLOW_EXEC", False, bool)
    )
    #: Resource jail for exec preprocessing (ops/exec_jail.py): wall-clock
    #: timeout, CPU seconds, and address-space cap for the child process.
    #: 0 disables the respective limit.
    exec_timeout_seconds: float = field(
        default_factory=lambda: _env("LO_TPU_EXEC_TIMEOUT_S", 300.0)
    )
    exec_cpu_seconds: int = field(
        default_factory=lambda: _env("LO_TPU_EXEC_CPU_S", 300)
    )
    exec_memory_mb: int = field(
        default_factory=lambda: _env("LO_TPU_EXEC_MEM_MB", 4096)
    )
    #: Checkpoint fitted models (orbax) into store_root/_models so they can
    #: be listed and re-used for prediction. The reference discards models
    #: after use (model_builder.py:227-248) — this is the §5 upgrade.
    persist_models: bool = field(
        default_factory=lambda: _env("LO_TPU_PERSIST_MODELS", True, bool)
    )
    #: Mid-fit checkpoint cadence (utils/fitckpt.py): persist per-family
    #: fit progress under ``<store_root>/_fitckpt`` every this many
    #: natural units — gb boost rounds, mlp training iterations, and (at
    #: every vmapped tree-batch boundary) rf trees — plus the streamed
    #: design fit's accumulator state at pass boundaries. A retried job
    #: (supervisor restart, watchdog kill, explicit re-POST) resumes
    #: from the newest valid checkpoint and produces BIT-IDENTICAL final
    #: params/metrics to an uninterrupted fit. ``0`` (the default)
    #: disables checkpointing entirely and keeps today's single-program
    #: fit path as the oracle (docs/fault_tolerance.md §8).
    fit_ckpt_rounds: int = field(
        default_factory=lambda: _env("LO_TPU_FIT_CKPT_ROUNDS", 0)
    )
    #: Successive-halving rungs for a hyperparameter sweep (models/
    #: tune.py): the sweep's total unit budget (boost rounds / adam
    #: iterations / tree batches) is cut into this many segments; after
    #: each, every candidate's k-fold scores are taken and the bottom
    #: half of the surviving configs is dropped (masks zeroed — the
    #: survivors' arithmetic is untouched). ``1`` disables halving (one
    #: rung, everyone runs to completion).
    tune_rungs: int = field(
        default_factory=lambda: _env("LO_TPU_TUNE_RUNGS", 3)
    )
    #: Cross-validation folds for tune sweeps: fold membership is an
    #: index mask over the ONE resident design matrix (row i belongs to
    #: fold ``i % folds``), never a data copy. ``1`` disables CV — each
    #: candidate trains on all rows and is scored on them too.
    tune_folds: int = field(
        default_factory=lambda: _env("LO_TPU_TUNE_FOLDS", 3)
    )
    #: HBM budget (MB) for sizing a tune population wave: the largest
    #: candidate count whose modeled per-member footprint (models/
    #: flops.py bytes model, raised to the family's recorded
    #: ``peak_hbm_bytes`` watermark when one exists) fits this budget
    #: runs as ONE vmapped device program; extra candidates spill into
    #: sequential waves (counted on ``/metrics``). ``0`` = unlimited
    #: (one wave, trusting the device).
    tune_hbm_budget_mb: int = field(
        default_factory=lambda: _env("LO_TPU_TUNE_HBM_BUDGET_MB", 0)
    )
    #: Hard cap on candidates per vmapped wave regardless of the HBM
    #: model — bounds compile-time shape growth for very large sweeps.
    tune_max_population: int = field(
        default_factory=lambda: _env("LO_TPU_TUNE_MAX_POPULATION", 64)
    )

    # --- job-tier fault domain (jobs.py watchdog) ---------------------------
    #: Per-job liveness deadline (seconds): a managed job whose BODY has
    #: started and then makes no PROGRESS for this long — progress marks
    #: (``jobs.heartbeat``) fire at boost-round / tree-batch /
    #: fitting-pass / dispatch boundaries — is failed by the watchdog
    #: thread with the retryable ``interrupted: watchdog`` prefix, the
    #: pod is poisoned so the supervisor restarts it under a new mesh
    #: epoch, and a flight-recorder bundle freezes the evidence. Bounds
    #: the one phase nothing else bounds: a hung device program after
    #: SPMD 'go'. Marks land at PROGRAM boundaries (a running device
    #: program is opaque), so size this above the longest single fit
    #: program plus cold compile — docs/fault_tolerance.md §8 has the
    #: granularity table. ``0`` (the default) disables the watchdog.
    job_deadline_s: float = field(
        default_factory=lambda: _env("LO_TPU_JOB_DEADLINE_S", 0.0)
    )

    # --- elastic recovery (supervisor.py) ----------------------------------
    #: Automatic re-runs per job whose outputs failed from INFRASTRUCTURE
    #: (``pod failure:`` watchdog flags, ``interrupted:`` restart marks) —
    #: the analogue of Spark re-running lost tasks on recovered executors.
    #: On startup, process 0 rescans the store and resubmits such jobs
    #: until each has been retried this many times. 0 disables retry.
    job_retries: int = field(
        default_factory=lambda: _env("LO_TPU_JOB_RETRIES", 1)
    )
    #: Pod restarts the supervisor will attempt before declaring the pod
    #: failed (reason then served via its fallback /cluster responder) —
    #: the bounded analogue of the reference's restart_policy:on-failure.
    restart_budget: int = field(
        default_factory=lambda: _env("LO_TPU_RESTART_BUDGET", 5)
    )
    #: First restart delay, seconds; doubles per restart (exponential
    #: backoff) up to ``restart_backoff_max_s``.
    restart_backoff_s: float = field(
        default_factory=lambda: _env("LO_TPU_RESTART_BACKOFF_S", 1.0)
    )
    restart_backoff_max_s: float = field(
        default_factory=lambda: _env("LO_TPU_RESTART_BACKOFF_MAX_S", 30.0)
    )
    #: Cadence of the supervisor's /cluster health poll, seconds — catches
    #: degradations where no supervised process died (e.g. a remote host's
    #: worker vanished and the watchdog poisoned this pod).
    health_interval_s: float = field(
        default_factory=lambda: _env("LO_TPU_HEALTH_INTERVAL_S", 2.0)
    )
    #: Restart-budget decay window (seconds): after this much CONTINUOUS
    #: healthy pod uptime the supervisor resets its consumed restart
    #: count to zero, so budget spent on an incident from hours ago no
    #: longer dooms tonight's single blip (budget exhaustion used to be
    #: permanent). A pod that keeps flapping faster than this window
    #: still exhausts its budget exactly as before. ``0`` disables decay.
    restart_healthy_s: float = field(
        default_factory=lambda: _env("LO_TPU_RESTART_HEALTHY_S", 300.0)
    )

    # --- observability -----------------------------------------------------
    #: When set, compute jobs run under jax.profiler.trace writing
    #: TensorBoard-loadable device traces here.
    profile_dir: str = field(
        default_factory=lambda: _env("LO_TPU_PROFILE_DIR", "")
    )
    #: Capacity (spans) of the in-process trace ring buffer
    #: (utils/tracing.py). Old spans evict FIFO past this, so a long-lived
    #: server holds a bounded window of recent traces. 0 disables span
    #: retention entirely (trace ids still mint and propagate).
    trace_buffer_spans: int = field(
        default_factory=lambda: _env("LO_TPU_TRACE_BUFFER_SPANS", 4096)
    )
    #: Probability (0.0-1.0) that a new trace records spans. 1.0 traces
    #: every request/job; 0.0 disables recording (ids still propagate,
    #: which is what the bench's overhead A/B toggles).
    trace_sample: float = field(
        default_factory=lambda: _env("LO_TPU_TRACE_SAMPLE", 1.0)
    )
    #: Log line format for the structured logger (utils/structlog.py):
    #: "text" (human-readable, trace ids appended) or "json" (one JSON
    #: doc per line, trace/span ids as fields).
    log_format: str = field(
        default_factory=lambda: _env("LO_TPU_LOG_FORMAT", "text")
    )
    #: Log level for the framework's ``lo_tpu`` logger tree.
    log_level: str = field(
        default_factory=lambda: _env("LO_TPU_LOG_LEVEL", "INFO")
    )

    # --- telemetry history (utils/timeseries.py) ----------------------------
    #: Cadence (seconds) of the background telemetry sampler: the server
    #: snapshots its own ``/metrics`` document this often into the
    #: history ring, whether or not anything scrapes it — retained
    #: telemetry, not scrape luck, is what post-hoc debugging reads.
    #: ``0`` disables the sampler thread and records one sample per
    #: registry read instead (tests drive history deterministically this
    #: way); negative disables history entirely.
    telemetry_sample_s: float = field(
        default_factory=lambda: _env("LO_TPU_TELEMETRY_SAMPLE_S", 5.0)
    )
    #: In-memory history ring capacity (samples). 720 × the 5 s default
    #: cadence ≈ one hour of full-resolution history served from RAM.
    telemetry_ring_samples: int = field(
        default_factory=lambda: _env("LO_TPU_TELEMETRY_RING_SAMPLES", 720)
    )
    #: Samples per on-disk segment: every this many samples the ring
    #: rotates a delta-encoded segment file to
    #: ``<store_root>/_telemetry/`` so history survives restarts.
    telemetry_segment_samples: int = field(
        default_factory=lambda: _env("LO_TPU_TELEMETRY_SEGMENT_SAMPLES",
                                     120)
    )
    #: Newest on-disk segments kept; older ones are unlinked at each
    #: rotation (bounded retention — telemetry must never eat the disk
    #: the ``disk_free_low`` alert guards).
    telemetry_retention_segments: int = field(
        default_factory=lambda: _env(
            "LO_TPU_TELEMETRY_RETENTION_SEGMENTS", 48)
    )

    # --- flight recorder (utils/flightrec.py) -------------------------------
    #: Newest flight-recorder bundles kept under
    #: ``<store_root>/_flightrec/``; older bundles are pruned at each
    #: dump. ``0`` disables the recorder entirely.
    flightrec_keep: int = field(
        default_factory=lambda: _env("LO_TPU_FLIGHTREC_KEEP", 8)
    )
    #: Minimum seconds between AUTOMATIC bundle dumps (alert firing,
    #: healthz flip, quarantine, supervisor incident): a flapping
    #: condition records its first transition, not one bundle per flap.
    #: Manual ``POST /debug/flightrec`` ignores this.
    flightrec_min_interval_s: float = field(
        default_factory=lambda: _env("LO_TPU_FLIGHTREC_MIN_INTERVAL_S",
                                     30.0)
    )
    #: Seconds of telemetry history captured into each bundle's
    #: ``history.json`` — the "surrounding window" an operator replays.
    flightrec_window_s: float = field(
        default_factory=lambda: _env("LO_TPU_FLIGHTREC_WINDOW_S", 600.0)
    )

    # --- resource & capacity plane (utils/resources.py, utils/alerts.py) ---
    #: Evaluation-window length (seconds) of the declarative alert engine:
    #: rule conditions are (re)checked at most once per window, driven by
    #: /metrics, /alerts, /healthz and status-page reads — the Prometheus
    #: scrape-window model. 0 evaluates on every read (tests).
    alert_window_s: float = field(
        default_factory=lambda: _env("LO_TPU_ALERT_WINDOW_S", 15.0)
    )
    #: Consecutive bad windows before a threshold rule (serving p99,
    #: queue rejection rate) transitions to FIRING — the fire-side
    #: hysteresis that keeps one jittery window from paging anyone.
    #: Event rules (pod degraded, disk watermark, corruption/worker-error
    #: increments) fire on a single window regardless.
    alert_for_windows: int = field(
        default_factory=lambda: _env("LO_TPU_ALERT_FOR_WINDOWS", 2)
    )
    #: Consecutive clean windows before a firing alert resolves — the
    #: resolve-side hysteresis (a flapping condition stays visibly FIRING
    #: instead of strobing).
    alert_clear_windows: int = field(
        default_factory=lambda: _env("LO_TPU_ALERT_CLEAR_WINDOWS", 2)
    )
    #: Serving-latency SLO: the online predict tier's recent-window p99
    #: (milliseconds, per model — worst model counts) above this for
    #: ``alert_for_windows`` windows fires ``serving_p99_slo``. 0 disables
    #: the rule.
    slo_p99_ms: float = field(
        default_factory=lambda: _env("LO_TPU_SLO_P99_MS", 500.0)
    )
    #: Queue-rejection-rate SLO: rejected / offered requests per window
    #: at or above this ratio fires ``serving_reject_rate`` (sustained
    #: backpressure — capacity, not a blip). 0 disables the rule.
    slo_reject_rate: float = field(
        default_factory=lambda: _env("LO_TPU_SLO_REJECT_RATE", 0.05)
    )
    #: Deadline-miss-rate SLO: deadline-expired / offered predict
    #: requests per window above this ratio fires
    #: ``serving_deadline_exceeded_rate`` — callers are giving up on a
    #: sustained fraction of answers, so the device is burning time the
    #: clients no longer want. 0 disables the rule.
    slo_deadline_rate: float = field(
        default_factory=lambda: _env("LO_TPU_SLO_DEADLINE_RATE", 0.05)
    )
    #: Fast burn-rate window (seconds) for the serving SLO rules when a
    #: telemetry history store is attached (serving_p99_slo,
    #: serving_reject_rate, serving_deadline_exceeded_rate): the rule
    #: fires only while the condition is STILL bad over this recent
    #: window. 0 keeps the legacy single-window evaluation.
    slo_burn_fast_s: float = field(
        default_factory=lambda: _env("LO_TPU_SLO_BURN_FAST_S", 300.0)
    )
    #: Slow burn-rate window (seconds): the error budget is judged over
    #: this span, so a brief spike that consumed almost none of it stops
    #: paging, and a slow burn that consumes it keeps paging. 0 keeps
    #: the legacy single-window evaluation.
    slo_burn_slow_s: float = field(
        default_factory=lambda: _env("LO_TPU_SLO_BURN_SLOW_S", 3600.0)
    )
    #: Error budget: the fraction of an evaluation window that may be
    #: out-of-SLO before its burn rate reads 1.0 (the firing line).
    slo_burn_budget: float = field(
        default_factory=lambda: _env("LO_TPU_SLO_BURN_BUDGET", 0.02)
    )
    #: Disk-headroom watermark (MiB) for the chunk store's filesystem:
    #: free bytes under it fires ``disk_free_low`` and degrades
    #: ``GET /healthz`` — ingest/journal writes are about to start
    #: failing. 0 disables the check.
    disk_free_watermark_mb: int = field(
        default_factory=lambda: _env("LO_TPU_DISK_FREE_WATERMARK_MB", 512)
    )
    #: Allow ``POST /debug/profile`` to capture an on-demand
    #: ``jax.profiler`` trace (N seconds, written under
    #: ``<store_root>/_profiles``). Off by default: profiling costs real
    #: overhead and writes operator-readable traces to disk, so it is an
    #: explicit opt-in, never ambient.
    debug_profile: bool = field(
        default_factory=lambda: _env("LO_TPU_DEBUG_PROFILE", False, bool)
    )

    def replace(self, **kw) -> "Settings":
        new = Settings()
        for f in fields(self):
            setattr(new, f.name, kw.get(f.name, getattr(self, f.name)))
        return new


#: Process-global settings instance. Tests construct their own.
settings = Settings()


# --- dynamic environment accessors ------------------------------------------
# Knobs that cannot be Settings fields because they change within a
# process's lifetime (the supervisor bumps LO_TPU_MESH_EPOCH and
# LO_TPU_RESTART_COUNT per pod restart, and the poison/health scope must
# follow the env, not an import-time snapshot) or are read before any
# Settings instance exists (failpoint arming at import). They still live
# HERE: every LO_TPU_* read in the codebase is either a Settings field
# above or an accessor below, so one file answers "what knobs exist" —
# enforced by lolint's env-discipline rule (docs/static_analysis.md),
# which also cross-checks that each knob named in this file appears in
# docs/configuration.md.


def restart_count() -> int:
    """This incarnation's supervisor restart ordinal
    (``LO_TPU_RESTART_COUNT``, set by supervisor.py for each supervised
    child; 0 = first launch). Served on ``/cluster`` as ``restarts``."""
    try:
        return int(os.environ.get("LO_TPU_RESTART_COUNT", "0") or 0)
    except ValueError:
        return 0


def mesh_epoch() -> int:
    """The pod's mesh generation (``LO_TPU_MESH_EPOCH``) — bumped by the
    supervisor on every restart so the SPMD job channel can reject
    workers from a previous incarnation (parallel/spmd.py). Read per
    call, never cached: the epoch-scoped pod poison follows the env."""
    try:
        return int(os.environ.get("LO_TPU_MESH_EPOCH", "0") or 0)
    except ValueError:
        return 0


def coordinator_address(default: Optional[str] = None) -> Optional[str]:
    """``host:port`` of process 0's jax.distributed coordination service
    (``LO_TPU_COORDINATOR``); also locates the SPMD job channel
    (coordinator host, port + 1). None/default = single-host."""
    return os.environ.get("LO_TPU_COORDINATOR") or default


def job_port(default: int) -> int:
    """Explicit SPMD job-channel port (``LO_TPU_JOB_PORT``); defaults to
    the coordinator port + 1 computed by the caller. A malformed value
    raises immediately: silently falling back would have coordinator and
    workers listening on different ports, surfacing as an opaque
    handshake timeout instead of a config error."""
    raw = os.environ.get("LO_TPU_JOB_PORT")
    if not raw:
        return default
    try:
        return int(raw)
    except ValueError:
        raise ValueError(
            f"LO_TPU_JOB_PORT must be an integer, got {raw!r}") from None


def num_processes() -> Optional[int]:
    """Pod process count for jax.distributed init
    (``LO_TPU_NUM_PROCESSES``); None = unset (single-host)."""
    raw = os.environ.get("LO_TPU_NUM_PROCESSES")
    return int(raw) if raw else None


def process_id() -> Optional[int]:
    """This process's pod rank for jax.distributed init
    (``LO_TPU_PROCESS_ID``); None = unset (single-host)."""
    raw = os.environ.get("LO_TPU_PROCESS_ID")
    return int(raw) if raw is not None and raw != "" else None


def peak_flops() -> float:
    """Override for the per-chip peak dense-matmul FLOP/s used as the
    MFU denominator (``LO_TPU_PEAK_FLOPS``; models/flops.py defaults to
    the v5e bf16 figure). 0.0 = unset."""
    try:
        return float(os.environ.get("LO_TPU_PEAK_FLOPS", "") or 0.0)
    except ValueError:
        return 0.0


def peak_bw() -> float:
    """Override for the per-chip peak HBM bandwidth used as the
    ``bw_util`` denominator (``LO_TPU_PEAK_BW``). 0.0 = unset."""
    try:
        return float(os.environ.get("LO_TPU_PEAK_BW", "") or 0.0)
    except ValueError:
        return 0.0


def failpoint_spec() -> str:
    """The deterministic fault-injection arming spec
    (``LO_TPU_FAILPOINTS=site=mode[:nth],...``), read at
    utils/failpoints.py import — before any Settings exists."""
    return os.environ.get("LO_TPU_FAILPOINTS", "")


def shard_host() -> Optional[int]:
    """Explicit placement identity of this host for shard-map planning
    (``LO_TPU_SHARD_HOST``): which ingest-partition owner's chunks count
    as host-local when ``mesh.shard_chunked`` classifies its feed. None =
    unset — multi-process pods use the jax process index, single-process
    sims model the pod topology (parallel/spmd.local_host_id)."""
    raw = os.environ.get("LO_TPU_SHARD_HOST")
    return int(raw) if raw is not None and raw != "" else None
