"""Multi-worker serving front end: SO_REUSEPORT accept processes + the
device-owning backend of the row channel.

``LO_TPU_HTTP_WORKERS > 1`` replaces the single threaded stdlib server
with N **accept processes** — separate interpreters, so N GILs — all
bound to the SAME host:port via ``SO_REUSEPORT`` (the kernel spreads
accepted connections across the listeners). Each worker runs a
non-blocking ``selectors`` event loop: it parses HTTP, decodes predict
bodies (JSON rows → a packed float32 matrix; binary columnar bodies
pass through untouched), and forwards frames over the length-prefixed
row channel (serving/rowchannel.py) to the ONE process that owns the
device and all serving state. Responses relay back asynchronously —
a worker never blocks on one request, and the expensive per-request
JSON encode of probabilities runs in the worker's interpreter, off the
device process's GIL.

Everything that is not the predict hot path proxies over the same
channel as a generic ``http`` frame and executes in the device-owning
process through the exact same ``Router``/``App._wrap`` stack the
threaded server uses — idempotency replay, drain gating, error mapping
and backpressure semantics are shared by construction, not re-derived.

Topology (``docs/serving.md`` §front end has the full diagram)::

     clients ──┬─► worker 0 (async accept loop) ─┐
               ├─► worker 1                      ├─ row channel ─► device
               └─► worker N-1                    ┘   (frames)      process

Semantics preserved across the process hop:

- **trace propagation** — the worker mints/validates the request id,
  roots the ``http.handle`` span, and ships the trace context in every
  frame (``tracing.to_wire`` form); the backend attaches it so
  ``queue.wait``/``dispatch.device`` spans land in the SAME trace with
  the worker's root as parent. Workers ship their finished spans back
  as ``spans`` frames (``tracing.ingest``), so ``GET /trace/{id}``
  shows one tree spanning both processes.
- **deadlines** — the raw ``X-Deadline-Ms`` header rides the frame and
  is parsed/clamped by the same ``App._deadline_ms``; expiry is the
  same terminal 504.
- **backpressure / drain** — QueueFull's computed Retry-After, the
  draining 503 + ``Connection: close``, quarantine and pod-degraded
  mappings all come from the shared ``App.map_exception``.
- **self-healing** — a worker process death is survived twice over: the
  kernel stops routing new connections to the dead listener (the
  client's stock connection-error retry lands on a live sibling), and
  the in-process :class:`WorkerSupervisor` respawns the slot under the
  supervisor-style restart budget with healthy-window decay
  (``LO_TPU_RESTART_BUDGET`` / ``LO_TPU_RESTART_HEALTHY_S``).
  Respawned workers start with ``LO_TPU_FAILPOINTS`` stripped — a
  one-shot chaos seam must not become a crash loop.

Worker processes import NO jax (serving/__init__ is lazy for exactly
this reason): an accept process is a few MB of Python + numpy and
starts in fractions of a second.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import re
import selectors
import socket
import subprocess
import sys
import threading
import time
import traceback
from http.client import responses as _REASONS
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from learningorchestra_tpu.config import Settings, settings as global_settings
from learningorchestra_tpu.serving import rowchannel
from learningorchestra_tpu.serving.http import (
    _REQUEST_ID_RE, FileResponse, HtmlResponse, HttpError, TextResponse,
    parse_body)
from learningorchestra_tpu.utils import failpoints, tracing
from learningorchestra_tpu.utils.structlog import get_logger

log = get_logger("serving.frontend")

#: Chaos seams on the worker↔batcher relay (docs/fault_tolerance.md §7):
#: ``pre_forward`` fires in the worker before a request frame enters the
#: channel (raise = the device never saw it → retryable 503; crash = a
#: worker death mid-request, survived by kernel re-routing + respawn);
#: ``pre_reply`` fires before the worker writes the relayed response
#: (raise-mode proves a computed-but-unsendable answer still ends in a
#: typed retryable error, never a hang).
FP_PRE_FORWARD = failpoints.declare("serving.front.pre_forward")
FP_PRE_REPLY = failpoints.declare("serving.front.pre_reply")

#: The predict hot path's route, matched in the worker without a Router.
PREDICT_ROUTE = "/trained-models/{name}/predict"
_PREDICT_RE = re.compile(r"^/trained-models/([^/]+)/predict$")

#: Worker span ``process`` stamp base: front-end workers are not pod
#: ranks, so they stamp 100+index — a trace's ``processes`` list shows
#: the hop explicitly.
WORKER_PROCESS_BASE = 100

_MAX_HEADER_BYTES = 64 << 10
_MAX_BODY_BYTES = 256 << 20


# =============================================================================
# Worker side (runs in the accept processes; imports no jax)
# =============================================================================


class _Conn:
    """One client HTTP connection inside the worker event loop."""

    __slots__ = ("sock", "inbuf", "out", "close_after", "inflight",
                 "last_active", "open", "writing")

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.inbuf = bytearray()
        self.out = bytearray()
        self.close_after = False
        self.inflight = False
        self.last_active = time.monotonic()
        self.open = True
        self.writing = False


class _Chan:
    """The worker's end of the row channel: one persistent non-blocking
    socket multiplexing every in-flight request, plus incremental frame
    parsing."""

    __slots__ = ("sock", "inbuf", "out", "alive")

    def __init__(self, host: str, port: int):
        self.sock = socket.create_connection((host, port), timeout=10.0)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.sock.setblocking(False)
        self.inbuf = bytearray()
        self.out = bytearray()
        self.alive = True

    def queue_frame(self, header: Dict[str, Any], payload: bytes = b"") \
            -> None:
        if not self.alive:
            raise ConnectionError("row channel closed")
        self.out += rowchannel.pack_frame(header, payload)

    def parse_frames(self) -> List[Tuple[Dict[str, Any], bytes]]:
        out = []
        buf = self.inbuf
        prefix = rowchannel._FRAME_PREFIX
        while len(buf) >= prefix.size:
            hlen, plen = prefix.unpack_from(buf)
            total = prefix.size + hlen + plen
            if hlen > rowchannel.MAX_HEADER_BYTES \
                    or plen > rowchannel.MAX_PAYLOAD_BYTES:
                raise rowchannel.ChannelProtocolError("oversized frame")
            if len(buf) < total:
                break
            header = json.loads(bytes(buf[prefix.size:prefix.size + hlen]))
            payload = bytes(buf[prefix.size + hlen:total])
            del buf[:total]
            out.append((header, payload))
        return out


class FrontendWorker:
    """One accept process: async HTTP in front, the row channel behind.

    Single-threaded by design — concurrency comes from the event loop
    inside one worker and from N workers across GILs, never from
    handler threads.
    """

    def __init__(self, host: str, port: int, channel_port: int,
                 index: int, http_timeout_s: float = 30.0,
                 pending_timeout_s: float = 60.0,
                 channel_host: str = "127.0.0.1",
                 trace_sample: Optional[float] = None):
        self.host = host
        self.port = port
        self.index = index
        self.http_timeout_s = float(http_timeout_s)
        self.pending_timeout_s = float(pending_timeout_s)
        self.sel = selectors.DefaultSelector()
        self.stopping = False
        self.conns: Dict[int, _Conn] = {}
        self.pending: Dict[int, Tuple[_Conn, Dict[str, Any]]] = {}
        self._next_fid = 0
        try:
            # The supervisor forwards the primary's EFFECTIVE sampling
            # rate on the command line — a programmatic
            # Settings(trace_sample=...) must shape worker sampling
            # exactly like the single-process topology's, not whatever
            # the env happens to say.
            self._sample = float(
                global_settings.trace_sample if trace_sample is None
                else trace_sample)
        except (TypeError, ValueError):
            self._sample = 1.0
        # Channel first: if the primary is gone there is nothing to
        # serve, and failing before bind keeps the port clean.
        self.chan = _Chan(channel_host, channel_port)
        self.lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        self.lsock.bind((host, port))
        self.lsock.listen(256)
        self.lsock.setblocking(False)
        self.sel.register(self.lsock, selectors.EVENT_READ, "listen")
        self.sel.register(self.chan.sock, selectors.EVENT_READ, "chan")
        # Ready handshake (raw append on purpose: the ready frame is
        # lifecycle plumbing, not a request forward — it must not trip
        # the pre_forward chaos seam). The supervisor's startup barrier
        # counts these.
        self.chan.out += rowchannel.pack_frame(
            {"kind": "ready", "index": index})
        self._chan_interest()

    # -- event loop -----------------------------------------------------------

    def run(self) -> None:
        log.info("front-end worker %d accepting on %s:%d",
                 self.index, self.host, self.port)
        try:
            while not self.stopping:
                for key, mask in self.sel.select(0.5):
                    tag = key.data
                    try:
                        if tag == "listen":
                            self._accept()
                        elif tag == "chan":
                            self._chan_io(mask)
                        else:
                            self._conn_io(tag, mask)
                    except rowchannel.ChannelProtocolError:
                        self._channel_lost()
                self._sweep()
        finally:
            self._close_all()

    def _accept(self) -> None:
        while True:
            try:
                sock, _addr = self.lsock.accept()
            except BlockingIOError:
                return
            except OSError:
                return
            sock.setblocking(False)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn = _Conn(sock)
            self.conns[sock.fileno()] = conn
            self.sel.register(sock, selectors.EVENT_READ, conn)

    def _conn_interest(self, conn: _Conn) -> None:
        if not conn.open:
            return
        events = selectors.EVENT_READ
        if conn.out:
            events |= selectors.EVENT_WRITE
        try:
            self.sel.modify(conn.sock, events, conn)
        except (KeyError, ValueError, OSError):
            pass

    def _chan_interest(self) -> None:
        events = selectors.EVENT_READ
        if self.chan.out:
            events |= selectors.EVENT_WRITE
        try:
            self.sel.modify(self.chan.sock, events, "chan")
        except (KeyError, ValueError, OSError):
            pass

    def _conn_io(self, conn: _Conn, mask: int) -> None:
        if mask & selectors.EVENT_WRITE and conn.out:
            try:
                sent = conn.sock.send(conn.out)
                del conn.out[:sent]
                if sent:
                    conn.last_active = time.monotonic()
            except BlockingIOError:
                pass
            except OSError:
                self._close_conn(conn)
                return
            if not conn.out:
                if conn.close_after:
                    self._close_conn(conn)
                    return
                self._conn_interest(conn)
        if mask & selectors.EVENT_READ:
            try:
                data = conn.sock.recv(1 << 18)
            except BlockingIOError:
                return
            except OSError:
                self._close_conn(conn)
                return
            if not data:
                self._close_conn(conn)
                return
            conn.last_active = time.monotonic()
            conn.inbuf += data
            self._try_parse(conn)

    def _chan_io(self, mask: int) -> None:
        if mask & selectors.EVENT_WRITE and self.chan.out:
            try:
                sent = self.chan.sock.send(self.chan.out)
                del self.chan.out[:sent]
            except BlockingIOError:
                pass
            except OSError:
                self._channel_lost()
                return
            self._chan_interest()
        if mask & selectors.EVENT_READ:
            try:
                data = self.chan.sock.recv(1 << 20)
            except BlockingIOError:
                return
            except OSError:
                self._channel_lost()
                return
            if not data:
                self._channel_lost()
                return
            self.chan.inbuf += data
            for header, payload in self.chan.parse_frames():
                self._on_chan_frame(header, payload)

    def _sweep(self) -> None:
        now = time.monotonic()
        if self.http_timeout_s > 0:
            # A non-empty out buffer does NOT exempt a connection: a
            # client that stops READING its response would otherwise
            # pin the socket + buffer forever (writes that make
            # progress refresh last_active, so only stalled writers
            # age out).
            idle = [c for c in list(self.conns.values())
                    if not c.inflight
                    and now - c.last_active > self.http_timeout_s]
            for c in idle:
                self._close_conn(c)
        stale = [fid for fid, (_c, meta) in self.pending.items()
                 if now - meta["t0"] > self.pending_timeout_s]
        for fid in stale:
            conn, meta = self.pending.pop(fid)
            self._emergency_503(conn, meta,
                                "front-end relay timed out; retry")

    # -- request handling -----------------------------------------------------

    def _try_parse(self, conn: _Conn) -> None:
        # One request in flight per connection (clients here don't
        # pipeline); parsing resumes from the buffer when the reply is
        # queued, so back-to-back keep-alive requests still stream.
        while conn.open and not conn.inflight:
            buf = conn.inbuf
            end = buf.find(b"\r\n\r\n")
            if end < 0:
                if len(buf) > _MAX_HEADER_BYTES:
                    self._direct_error(conn, 431,
                                       "request header too large")
                return
            try:
                head = bytes(buf[:end]).decode("latin-1")
                lines = head.split("\r\n")
                method, path_qs, _version = lines[0].split(" ", 2)
            except ValueError:
                self._direct_error(conn, 400, "malformed request line")
                return
            headers: Dict[str, str] = {}
            for line in lines[1:]:
                if ":" not in line:
                    continue
                k, v = line.split(":", 1)
                headers.setdefault(k.strip(), v.strip())
            lower = {k.lower(): v for k, v in headers.items()}
            if "transfer-encoding" in lower:
                self._direct_error(conn, 501,
                                   "chunked request bodies unsupported")
                return
            try:
                clen = int(lower.get("content-length") or 0)
            except ValueError:
                self._direct_error(conn, 400, "bad Content-Length")
                return
            if clen < 0 or clen > _MAX_BODY_BYTES:
                self._direct_error(conn, 413, "request body too large")
                return
            total = end + 4 + clen
            if len(buf) < total:
                return
            body = bytes(buf[end + 4:total])
            del buf[:total]
            conn.inflight = True
            conn.last_active = time.monotonic()
            self._handle_request(conn, method.upper(), path_qs, headers,
                                 lower, body)

    def _handle_request(self, conn: _Conn, method: str, path_qs: str,
                        headers: Dict[str, str], lower: Dict[str, str],
                        body: bytes) -> None:
        inbound = lower.get("x-request-id") or ""
        rid = (inbound if _REQUEST_ID_RE.match(inbound)
               else tracing.new_id())
        sampled = (self._sample >= 1.0
                   or (self._sample > 0.0
                       and random.random() < self._sample))
        meta: Dict[str, Any] = {
            "rid": rid, "sid": tracing.new_id(), "sampled": sampled,
            "t0": time.monotonic(), "t_wall": time.time(),
            "method": method, "path": path_qs.split("?", 1)[0],
            "close": (lower.get("connection") or "").lower() == "close",
        }
        trace_doc = {"trace_id": rid, "span_id": meta["sid"],
                     "sampled": sampled}
        self._next_fid += 1
        fid = self._next_fid
        m = _PREDICT_RE.match(meta["path"])
        if method == "POST" and m:
            meta["model"] = m.group(1)
            payload = body
            ct = (lower.get("content-type") or "").split(";", 1)[0] \
                .strip().lower()
            if ct == rowchannel.COLUMNAR_CONTENT_TYPE:
                bkind = "columnar"
            else:
                bkind = "json"
                # Numeric list rows decode HERE, in the worker's
                # interpreter, and ship as the same columnar matrix a
                # binary body carries — the device process never JSON-
                # parses a row. Anything else (dict rows, malformed
                # JSON) forwards raw; the backend reproduces the exact
                # single-process behavior for it.
                rows = None
                try:
                    parsed = json.loads(body) if body else None
                    if isinstance(parsed, dict):
                        rows = parsed.get("rows")
                except (ValueError, UnicodeDecodeError):
                    rows = None
                if isinstance(rows, list) and rows \
                        and isinstance(rows[0], (list, tuple)):
                    try:
                        X = np.asarray(rows, dtype=np.float32)
                        if X.ndim == 2:
                            payload = rowchannel.encode_columnar(X)
                            bkind = "columnar"
                    except (TypeError, ValueError):
                        pass
            frame = {"kind": "predict", "id": fid,
                     "model": meta["model"],
                     "deadline": lower.get("x-deadline-ms"),
                     "body": bkind, "trace": trace_doc}
        else:
            frame = {"kind": "http", "id": fid, "method": method,
                     "url": path_qs, "headers": headers,
                     "trace": trace_doc}
            payload = body
        try:
            self._forward(conn, meta, frame, payload)
        except Exception as e:  # noqa: BLE001 — forward seam: retryable
            # The device never saw this request (the forward itself
            # failed): a retryable 503 — the stock client's backoff
            # lands the retry on a healthy path.
            try:
                self._reply(conn, meta, 503, json.dumps(
                    {"result": f"front-end forward failed: {e}"},
                    default=str).encode(), "application/json",
                    {"Retry-After": "1"}, None)
            except Exception:  # noqa: BLE001 — last-resort raw answer
                self._emergency_503(conn, meta, "front-end forward failed")

    def _forward(self, conn: _Conn, meta: Dict[str, Any],
                 header: Dict[str, Any], payload: bytes) -> None:
        failpoints.fire(FP_PRE_FORWARD)
        fid = header["id"]
        self.pending[fid] = (conn, meta)
        try:
            self.chan.queue_frame(header, payload)
        except Exception:
            self.pending.pop(fid, None)
            raise
        self._chan_interest()

    def _on_chan_frame(self, header: Dict[str, Any],
                       payload: bytes) -> None:
        kind = header.get("kind")
        ent = self.pending.pop(header.get("id") or -1, None)
        if ent is None:
            return                          # connection died meanwhile
        conn, meta = ent
        if not conn.open:
            return
        try:
            if kind == "probs":
                n, k = header.get("shape") or (0, 0)
                probs = np.frombuffer(payload, np.float32).reshape(n, k)
                # The exact response the single-process handler builds
                # (same key order, same float32→Python widening) — the
                # bytes are bit-identical by construction, just encoded
                # on this GIL instead of the device process's.
                doc = {"model": meta.get("model"),
                       "kind": header.get("mkind"),
                       "predictions": np.argmax(probs, axis=1).tolist(),
                       "probabilities": probs.tolist()}
                self._reply(conn, meta, 200,
                            json.dumps(doc, default=str).encode(),
                            "application/json", {}, header.get("route"))
            elif kind == "error":
                data = json.dumps({"result": header.get("message")},
                                  default=str).encode()
                self._reply(conn, meta, int(header.get("status", 500)),
                            data, "application/json",
                            header.get("headers") or {},
                            header.get("route"))
            elif kind == "http_ok":
                self._reply(conn, meta, int(header.get("status", 200)),
                            payload,
                            header.get("content_type")
                            or "application/json",
                            header.get("headers") or {},
                            header.get("route"))
            else:
                self._emergency_503(conn, meta,
                                    f"unknown channel frame {kind!r}")
        except Exception:  # noqa: BLE001 — reply seam: typed answer
            self._emergency_503(conn, meta, "front-end reply failed; retry")

    def _reply(self, conn: _Conn, meta: Dict[str, Any], status: int,
               data: bytes, content_type: str,
               extra_headers: Dict[str, str],
               route: Optional[str]) -> None:
        failpoints.fire(FP_PRE_REPLY)
        close = bool(meta.get("close"))
        lines = [f"HTTP/1.1 {status} {_REASONS.get(status, '')}",
                 f"Content-Type: {content_type}",
                 f"Content-Length: {len(data)}",
                 f"X-Request-Id: {meta['rid']}"]
        for k, v in (extra_headers or {}).items():
            lines.append(f"{k}: {v}")
            if k.lower() == "connection" and str(v).lower() == "close":
                close = True
        if close and "connection" not in {k.lower() for k in
                                          (extra_headers or {})}:
            lines.append("Connection: close")
        resp = ("\r\n".join(lines) + "\r\n\r\n").encode("latin-1") + data
        if meta["sampled"]:
            attrs: Dict[str, Any] = {"method": meta["method"],
                                     "path": meta["path"],
                                     "status": status,
                                     "worker": self.index}
            if route:
                attrs["route"] = route
            tracing.record_span(
                "http.handle", time.monotonic() - meta["t0"],
                ctx=tracing.TraceContext(meta["rid"], meta["sid"], True),
                span_id=meta["sid"], parent_id="",
                t_wall=meta["t_wall"], attrs=attrs)
            docs = tracing.pop_spans(meta["rid"])
            if docs and self.chan.alive:
                self.chan.queue_frame({"kind": "spans"},
                                      json.dumps(docs).encode())
                self._chan_interest()
        self._queue_response(conn, resp, close)
        conn.inflight = False
        self._try_parse(conn)

    def _queue_response(self, conn: _Conn, resp: bytes,
                        close: bool) -> None:
        conn.out += resp
        conn.close_after = close
        conn.last_active = time.monotonic()
        try:
            sent = conn.sock.send(conn.out)
            del conn.out[:sent]
        except BlockingIOError:
            pass
        except OSError:
            self._close_conn(conn)
            return
        if not conn.out and close:
            self._close_conn(conn)
            return
        self._conn_interest(conn)

    def _direct_error(self, conn: _Conn, status: int, msg: str) -> None:
        """Protocol-level reject (bad request line, oversized header):
        answered locally and the connection closed — there is no request
        to forward."""
        data = json.dumps({"result": msg}).encode()
        resp = (f"HTTP/1.1 {status} {_REASONS.get(status, '')}\r\n"
                f"Content-Type: application/json\r\n"
                f"Content-Length: {len(data)}\r\n"
                "Connection: close\r\n\r\n").encode("latin-1") + data
        self._queue_response(conn, resp, True)

    def _emergency_503(self, conn: _Conn, meta: Dict[str, Any],
                       msg: str) -> None:
        """Raw last-resort 503 (used when the normal reply path itself
        failed — e.g. a pre_reply chaos raise): the client must get a
        retryable answer, never a hang."""
        if not conn.open:
            return
        data = json.dumps({"result": msg}).encode()
        resp = ("HTTP/1.1 503 Service Unavailable\r\n"
                "Content-Type: application/json\r\n"
                f"Content-Length: {len(data)}\r\n"
                f"X-Request-Id: {meta.get('rid', '-')}\r\n"
                "Retry-After: 1\r\n"
                "Connection: close\r\n\r\n").encode("latin-1") + data
        conn.inflight = False
        self._queue_response(conn, resp, True)

    # -- teardown -------------------------------------------------------------

    def _close_conn(self, conn: _Conn) -> None:
        if not conn.open:
            return
        conn.open = False
        try:
            self.sel.unregister(conn.sock)
        except (KeyError, ValueError):
            pass
        self.conns.pop(conn.sock.fileno(), None)
        try:
            conn.sock.close()
        except OSError:
            pass

    def _channel_lost(self) -> None:
        """The primary went away: answer every pending request 503 and
        exit — the supervisor (or the operator) owns what happens next."""
        if not self.chan.alive:
            return
        self.chan.alive = False
        log.error("front-end worker %d lost the row channel; exiting",
                  self.index)
        for fid in list(self.pending):
            conn, meta = self.pending.pop(fid)
            self._emergency_503(conn, meta,
                                "server restarting; retry")
        self.stopping = True

    def _close_all(self) -> None:
        for conn in list(self.conns.values()):
            self._close_conn(conn)
        for sock in (self.lsock, self.chan.sock):
            try:
                self.sel.unregister(sock)
            except (KeyError, ValueError):
                pass
            try:
                sock.close()
            except OSError:
                pass
        self.sel.close()


def worker_main(argv: Optional[List[str]] = None) -> int:
    from learningorchestra_tpu.utils import structlog

    structlog.configure()
    ap = argparse.ArgumentParser(
        description="learningorchestra_tpu front-end accept worker")
    ap.add_argument("--host", required=True)
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--channel-port", type=int, required=True)
    ap.add_argument("--channel-host", default="127.0.0.1")
    ap.add_argument("--index", type=int, default=0)
    ap.add_argument("--http-timeout", type=float, default=30.0)
    ap.add_argument("--pending-timeout", type=float, default=60.0)
    ap.add_argument("--trace-sample", type=float, default=None)
    args = ap.parse_args(argv)
    tracing.set_process(WORKER_PROCESS_BASE + args.index)
    worker = FrontendWorker(args.host, args.port, args.channel_port,
                            args.index, http_timeout_s=args.http_timeout,
                            pending_timeout_s=args.pending_timeout,
                            channel_host=args.channel_host,
                            trace_sample=args.trace_sample)

    import signal

    def _term(_signum, _frame):
        worker.stopping = True

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    worker.run()
    return 0


# =============================================================================
# Primary side (runs in the device-owning process)
# =============================================================================


class _FrontendBackend:
    """Frame handlers for the row channel — thin adapters onto the App's
    existing serving stack, so the process hop adds no second copy of
    any semantic."""

    def __init__(self, app):
        self.app = app
        self._lock = threading.Lock()
        self.predict_frames = 0
        self.predict_binary = 0
        self.proxied_frames = 0
        self.spans_ingested = 0

    def handle_frame(self, header: Dict[str, Any], payload: bytes
                     ) -> Optional[Tuple[Dict[str, Any], bytes]]:
        kind = header.get("kind")
        if kind == "spans":
            try:
                n = tracing.ingest(json.loads(payload))
            except (ValueError, TypeError):
                n = 0
            with self._lock:
                self.spans_ingested += n
            return None
        if kind == "predict":
            return self._predict_frame(header, payload)
        if kind == "http":
            return self._http_frame(header, payload)
        return ({"kind": "error", "id": header.get("id"), "status": 500,
                 "message": f"unknown frame kind {kind!r}"}, b"")

    def _error_reply(self, fid: Any, e: Exception,
                     route: Optional[str]) -> Tuple[Dict[str, Any], bytes]:
        he = e if isinstance(e, HttpError) else self.app.map_exception(e)
        if he is None:
            traceback.print_exc()
            he = HttpError(500, f"internal error: {e}")
        return ({"kind": "error", "id": fid, "status": he.status,
                 "message": he.message, "headers": dict(he.headers),
                 "route": route}, b"")

    def _predict_frame(self, header: Dict[str, Any], payload: bytes
                       ) -> Tuple[Dict[str, Any], bytes]:
        app = self.app
        fid = header.get("id")
        binary = header.get("body") == "columnar"
        with self._lock:
            self.predict_frames += 1
            if binary:
                self.predict_binary += 1
        ctx = tracing.from_wire(header.get("trace"))
        try:
            with tracing.attach(ctx):
                if app.draining:
                    raise app.drain_error()
                from learningorchestra_tpu.parallel import spmd

                spmd.require_pod_health()
                deadline_ms = app._deadline_ms(header.get("deadline"))
                if binary:
                    # ValueError → the same 406 a malformed JSON row
                    # gets (map_exception), never a 500.
                    rows: Any = rowchannel.decode_columnar(payload)
                else:
                    try:
                        body = json.loads(payload) if payload else None
                    except ValueError:
                        raise HttpError(400, "invalid JSON body") \
                            from None
                    if not isinstance(body, dict) or "rows" not in body:
                        raise HttpError(400,
                                        "missing required field: rows")
                    rows = body["rows"]
                mkind, probs = app.predictor.predict_probs(
                    str(header.get("model")), rows,
                    deadline_ms=deadline_ms)
        except Exception as e:  # noqa: BLE001 — mapped like the router
            return self._error_reply(fid, e, PREDICT_ROUTE)
        probs = np.ascontiguousarray(np.asarray(probs, np.float32))
        return ({"kind": "probs", "id": fid, "mkind": mkind,
                 "shape": [int(probs.shape[0]), int(probs.shape[1])],
                 "route": PREDICT_ROUTE}, probs.tobytes())

    def _http_frame(self, header: Dict[str, Any], payload: bytes
                    ) -> Tuple[Dict[str, Any], bytes]:
        app = self.app
        fid = header.get("id")
        method = str(header.get("method", "GET")).upper()
        url = str(header.get("url", "/"))
        headers = {str(k): str(v)
                   for k, v in (header.get("headers") or {}).items()}
        with self._lock:
            self.proxied_frames += 1
        ctx = tracing.from_wire(header.get("trace"))
        attrs: Dict[str, Any] = {}
        extra: Dict[str, str] = {}
        try:
            with tracing.attach(ctx):
                ct_in = next((v for k, v in headers.items()
                              if k.lower() == "content-type"), "")
                body = parse_body(payload, ct_in)
                status, result = app.router.dispatch(
                    method, url, body, headers, attrs=attrs)
                data, content_type, override = _render_payload(result)
                if override is not None:
                    status = override
        except HttpError as e:
            status = e.status
            extra = dict(e.headers)
            content_type = "application/json"
            data = json.dumps({"result": e.message}, default=str).encode()
        except Exception as e:  # noqa: BLE001 — request boundary
            traceback.print_exc()
            status = 500
            content_type = "application/json"
            data = json.dumps({"result": f"internal error: {e}"},
                              default=str).encode()
        return ({"kind": "http_ok", "id": fid, "status": status,
                 "content_type": content_type, "headers": extra,
                 "route": attrs.get("route")}, data)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"predict_frames_total": self.predict_frames,
                    "predict_binary_total": self.predict_binary,
                    "proxied_frames_total": self.proxied_frames,
                    "spans_ingested_total": self.spans_ingested}


def _render_payload(payload: Any) -> Tuple[bytes, str, Optional[int]]:
    """A dispatch result → (body bytes, content type, status override) —
    the wire-path mirror of the threaded handler's _send_* family."""
    if isinstance(payload, FileResponse):
        with open(payload.path, "rb") as f:
            return f.read(), payload.content_type, None
    if isinstance(payload, HtmlResponse):
        return (payload.html.encode(), "text/html; charset=utf-8",
                payload.status)
    if isinstance(payload, TextResponse):
        return payload.text.encode(), payload.content_type, payload.status
    return (json.dumps(payload, default=str).encode(),
            "application/json", None)


class WorkerSupervisor:
    """Spawns and respawns the accept processes — the supervisor.py
    restart discipline (budget, exponential backoff, healthy-window
    budget decay) applied to front-end workers."""

    def __init__(self, cfg: Settings, host: str, port: int,
                 channel_port: int):
        self.cfg = cfg
        self.host = host
        self.port = port
        self.channel_port = channel_port
        self.n = max(0, int(cfg.http_workers))
        self._lock = threading.Lock()
        self._slots: List[Optional[subprocess.Popen]] = [None] * self.n
        self._next_spawn = [0.0] * self.n
        self._gave_up = [False] * self.n
        #: Restart budget is PER SLOT (unlike supervisor.py, which
        #: supervises one pod): one flapping worker exhausting a shared
        #: budget must not doom its healthy siblings' future respawns.
        self._budget_used = [0] * self.n
        self._healthy_since = time.monotonic()
        self.respawns_total = 0
        self._stopping = threading.Event()
        self._monitor: Optional[threading.Thread] = None

    def _cmd(self, index: int) -> List[str]:
        return [sys.executable, "-m",
                "learningorchestra_tpu.serving.frontend",
                "--host", self.host, "--port", str(self.port),
                "--channel-port", str(self.channel_port),
                "--index", str(index),
                "--http-timeout", str(self.cfg.http_timeout_s),
                "--pending-timeout",
                str(float(self.cfg.serve_timeout_s) + 30.0),
                "--trace-sample", str(float(self.cfg.trace_sample))]

    def _spawn(self, index: int, first: bool) -> subprocess.Popen:
        env = dict(os.environ)
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH",
                                                            "")
        if not first:
            # A respawned worker starts with fault injection disarmed:
            # chaos seams are one-shot by convention (failpoints nth
            # semantics) and re-arming them in every incarnation would
            # turn a single injected crash into a crash loop.
            env.pop("LO_TPU_FAILPOINTS", None)
        return subprocess.Popen(self._cmd(index), env=env)

    def start(self) -> None:
        with self._lock:
            for i in range(self.n):
                self._slots[i] = self._spawn(i, first=True)
        # thread-lifecycle: owner=WorkerSupervisor; exits when stop()
        # sets _stopping (joined there); daemon so a leaked supervisor
        # cannot hang interpreter exit.
        self._monitor = threading.Thread(target=self._monitor_loop,
                                         daemon=True,
                                         name="lo-frontend-supervisor")
        self._monitor.start()

    def _monitor_loop(self) -> None:
        while not self._stopping.wait(0.05):
            now = time.monotonic()
            with self._lock:
                alive = 0
                for i in range(self.n):
                    if self._gave_up[i]:
                        continue
                    proc = self._slots[i]
                    if proc is not None and proc.poll() is None:
                        alive += 1
                        continue
                    if proc is not None:
                        log.error(
                            "front-end worker %d exited rc=%s",
                            i, proc.returncode)
                        proc.wait()
                        self._slots[i] = None
                        self._budget_used[i] += 1
                        if self._budget_used[i] > int(
                                self.cfg.restart_budget):
                            self._gave_up[i] = True
                            log.error(
                                "front-end worker %d: restart budget "
                                "exhausted (%d); slot abandoned — "
                                "remaining workers keep accepting",
                                i, int(self.cfg.restart_budget))
                            continue
                        backoff = min(
                            float(self.cfg.restart_backoff_max_s),
                            float(self.cfg.restart_backoff_s)
                            * (2 ** max(0, self._budget_used[i] - 1)))
                        self._next_spawn[i] = now + backoff
                        log.warning(
                            "respawning front-end worker %d in %.2fs "
                            "(budget %d/%d)", i, backoff,
                            self._budget_used[i],
                            int(self.cfg.restart_budget))
                        continue
                    if now >= self._next_spawn[i]:
                        self._slots[i] = self._spawn(i, first=False)
                        self.respawns_total += 1
                if alive < self.n - sum(self._gave_up):
                    self._healthy_since = now
                elif (any(self._budget_used)
                      and float(self.cfg.restart_healthy_s) > 0
                      and now - self._healthy_since
                      >= float(self.cfg.restart_healthy_s)):
                    log.info(
                        "front-end workers healthy for %.0fs: restart "
                        "budget restored (was %s consumed)",
                        float(self.cfg.restart_healthy_s),
                        self._budget_used)
                    self._budget_used = [0] * self.n

    def alive(self) -> int:
        with self._lock:
            return sum(1 for p in self._slots
                       if p is not None and p.poll() is None)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"workers": self.n,
                    "workers_alive": sum(
                        1 for p in self._slots
                        if p is not None and p.poll() is None),
                    "respawns_total": self.respawns_total,
                    "restart_budget_used": sum(self._budget_used),
                    "slots_abandoned": sum(self._gave_up)}

    def stop(self) -> None:
        self._stopping.set()
        if self._monitor is not None:
            self._monitor.join(timeout=5.0)
        with self._lock:
            procs = [p for p in self._slots if p is not None]
            self._slots = [None] * self.n
        for p in procs:
            if p.poll() is None:
                p.terminate()
        deadline = time.monotonic() + 5.0
        for p in procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()


class FrontendServer:
    """The multi-worker topology behind the same start/stop surface as
    serving.http.Server — App.serve returns one or the other and
    nothing downstream can tell (tests, __main__, the supervisor's
    drain path all keep working)."""

    def __init__(self, app, host: str, port: int):
        cfg = app.cfg
        self.host = host
        # The placeholder socket resolves port 0 once and holds the
        # port (SO_REUSEPORT, never listening) so every worker — and
        # every respawn — binds the SAME number, and the port cannot be
        # lost to another process while all workers happen to be dead.
        self._placeholder = socket.socket(socket.AF_INET,
                                          socket.SOCK_STREAM)
        self._placeholder.setsockopt(socket.SOL_SOCKET,
                                     socket.SO_REUSEPORT, 1)
        self._placeholder.bind((host, port))
        self.port = self._placeholder.getsockname()[1]
        self.backend = _FrontendBackend(app)
        self._ready_lock = threading.Lock()
        #: DISTINCT worker indices seen ready — a respawned worker's
        #: second ready frame must not satisfy the barrier for a
        #: sibling that never bound its listener.
        self._ready_indices: set = set()
        self._ready = threading.Event()
        self.channel = rowchannel.RowChannelServer(
            self.backend.handle_frame,
            threads=cfg.frontend_channel_threads,
            on_ready=self._on_worker_ready)
        self.supervisor = WorkerSupervisor(cfg, host, self.port,
                                           self.channel.port)
        self._stop_callbacks: List[Any] = []
        self._stopped = threading.Event()
        self._started = False

    def _on_worker_ready(self, index: int) -> None:
        with self._ready_lock:
            self._ready_indices.add(index)
            if len(self._ready_indices) >= self.supervisor.n:
                self._ready.set()

    def on_stop(self, fn) -> None:
        self._stop_callbacks.append(fn)

    def start_background(self, ready_timeout_s: float = 20.0
                         ) -> "FrontendServer":
        if not self._started:
            self._started = True
            self.supervisor.start()
            if not self._ready.wait(ready_timeout_s):
                self.stop()
                raise RuntimeError(
                    f"front-end workers failed to come up within "
                    f"{ready_timeout_s:.0f}s "
                    f"({len(self._ready_indices)}/{self.supervisor.n} "
                    "ready)")
            log.info("front end up: %d accept process(es) on %s:%d",
                     self.supervisor.n, self.host, self.port)
        return self

    def serve_forever(self) -> None:
        self.start_background()
        self._stopped.wait()

    def snapshot(self) -> Dict[str, Any]:
        return {**self.supervisor.snapshot(),
                **{f"channel_{k}": v
                   for k, v in self.channel.snapshot().items()},
                **self.backend.snapshot()}

    def stop(self) -> None:
        # Workers first (stop accepting), then the app-level teardown
        # hooks (predict dispatchers, telemetry — mirrors Server.stop's
        # ordering), then the channel and the held port.
        self.supervisor.stop()
        for fn in self._stop_callbacks:
            try:
                fn()
            except Exception:  # noqa: BLE001 — teardown best-effort
                traceback.print_exc()
        self.channel.stop()
        try:
            self._placeholder.close()
        except OSError:
            pass
        self._stopped.set()


if __name__ == "__main__":
    sys.exit(worker_main())
