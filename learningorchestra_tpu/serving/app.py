"""The service application: all 7 reference API surfaces on one server.

The reference deploys 7 Flask microservices on ports 5000-5006 (client
__init__.py:56-333; docker-compose.yml) — database_api, projection,
data_type_handler, histogram, model_builder, tsne, pca. Here each becomes a
router section of one process that embeds the engine (SURVEY.md §7: "one
service binary with the same 7 API surfaces"); per-service ports are
replaced by path prefixes. Status-code conventions follow the reference:
201 for accepted creates, 406 invalid input, 409 duplicate, 404 missing
(e.g. model_builder_image/server.py:52-115).

Async contract preserved: creates return immediately; completion is
observed by polling the dataset metadata ``finished`` flag (GET /files/...),
exactly like the reference client does (client __init__.py:14-32) — with
the upgrade that failed jobs set ``error`` and still flip ``finished``.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from typing import Optional

from learningorchestra_tpu.catalog.dataset import ChunkCorrupt
from learningorchestra_tpu.catalog.ingest import ingest_csv_url
from learningorchestra_tpu.catalog.store import (
    DatasetExists, DatasetNotFound, DatasetStore)
from learningorchestra_tpu import config
from learningorchestra_tpu.config import Settings, settings as global_settings
from learningorchestra_tpu.jobs import JobManager, select_retry_groups
from learningorchestra_tpu.models.builder import ModelBuilder
from learningorchestra_tpu.models.registry import validate_hparams
from learningorchestra_tpu.ops.dtypes import convert_fields
from learningorchestra_tpu.ops.histogram import create_histogram
from learningorchestra_tpu.ops.projection import create_projection
from learningorchestra_tpu.parallel import distributed, spmd
from learningorchestra_tpu.parallel.mesh import MeshRuntime
from learningorchestra_tpu.serving.batcher import (
    BatcherStopped, DeadlineExceeded, DispatcherCrashed, ModelQuarantined,
    PredictBatcher, PredictTimeout, QueueFull)
from learningorchestra_tpu.serving.http import (
    FileResponse, HtmlResponse, HttpError, IdempotencyCache, Router,
    Server, TextResponse)
from learningorchestra_tpu.utils import (
    alerts, flightrec, resources, timeseries, tracing)
from learningorchestra_tpu.utils.structlog import get_logger
from learningorchestra_tpu.viz.service import (
    ImageExists, ImageNotFound, ImageService, create_embedding_image)

log = get_logger("serving")


class App:
    def __init__(self, cfg: Optional[Settings] = None, recover: bool = True):
        self.cfg = cfg or global_settings
        self.store = DatasetStore(self.cfg)
        if recover and self.cfg.persist:
            self.store.load_all(resume_ingests=True)
        self.runtime = MeshRuntime(self.cfg)
        self.jobs = JobManager(self.store, cfg=self.cfg)
        # Interrupted ingests restart from their last journal-committed
        # source byte instead of failing (the reference restarted a crashed
        # ingest from zero — or rather, never: finished stayed false
        # forever, SURVEY.md §5).
        for rname in self.store.resumable_ingests:
            from learningorchestra_tpu.catalog.ingest import resume_ingest

            self.jobs.submit(
                "ingest_resume", rname,
                lambda rname=rname: resume_ingest(self.store, rname,
                                                  self.cfg))
        self.builder = ModelBuilder(self.store, self.runtime, self.cfg)
        # The online inference tier: request handlers are thin
        # enqueue/await shims into this worker, which owns the device
        # (serving/batcher.py). Shares the builder's model registry, so
        # a fresh fit is immediately servable.
        self.predictor = PredictBatcher(self.builder.registry, self.cfg)
        self.images = {m: ImageService(m, self.cfg) for m in ("tsne", "pca")}
        #: POST replay cache: a create retried with the same
        #: Idempotency-Key (the client SDK sends one per logical create)
        #: returns the first attempt's outcome instead of a spurious 409.
        self.idempotency = IdempotencyCache()
        #: Telemetry history (utils/timeseries.py): the background
        #: sampler snapshots _metrics_doc on its own clock (started in
        #: serve(), so bare App construction spawns no threads), and
        #: every registry read contributes a sample too, gated to the
        #: same cadence — history accrues whether or not anything
        #: scrapes the server, and survives restarts via the rotating
        #: delta segments under <store_root>/_telemetry/.
        self.history = timeseries.TelemetryHistory(
            self.cfg, source=self._metrics_doc)
        #: The SLO alert engine (utils/alerts.py), evaluated over the
        #: same registry snapshot both /metrics formats render — reads
        #: of /metrics, /alerts, /healthz and the status page drive its
        #: evaluation windows (the Prometheus scrape-window model).
        #: With the history store attached, the serving SLO rules run
        #: as multi-window burn rates over it (fast 5 m + slow 1 h):
        #: brief spikes stop paging, slow burns stop hiding.
        self.alerts = alerts.default_engine(self.cfg,
                                            history=self.history)
        #: Flight recorder (utils/flightrec.py): on an alert firing, a
        #: /healthz flip to 503, a dispatcher quarantine or a
        #: supervisor incident, a bounded-retention evidence bundle
        #: (spans, history window, resources, alerts, config, versions)
        #: lands under <store_root>/_flightrec/.
        self.flightrec = flightrec.FlightRecorder(self.cfg, gather={
            "spans": lambda: tracing.recent_span_docs(2048),
            "history": lambda: self.history.query(
                window_s=self.cfg.flightrec_window_s),
            "resources": lambda: resources.process_snapshot(self.cfg),
            "alerts": self.alerts.snapshot,
        })
        flightrec.set_recorder(self.flightrec)
        #: Last /healthz verdict — the firing edge (healthy → 503) is a
        #: flight-recorder trigger.
        self._was_healthy: Optional[bool] = None
        #: Graceful-drain latch (SIGTERM / App.drain): once set, new
        #: work answers 503 + Retry-After + Connection: close while
        #: in-flight predicts and queued jobs run to completion —
        #: a planned restart loses zero accepted requests.
        self._draining = threading.Event()
        #: The multi-worker front end when ``LO_TPU_HTTP_WORKERS > 1``
        #: (serving/frontend.py FrontendServer), set by :meth:`serve` —
        #: its worker/channel counters feed ``/metrics`` (``frontend``
        #: section → ``lo_frontend_*``) and the health rollup.
        self._frontend = None
        #: This host's ReplicaServer (catalog/replicate.py) when
        #: ``LO_TPU_REPLICA_PORT`` is set, started by :meth:`serve` —
        #: its push/fetch counters ride the ``replication`` metrics
        #: section.
        self._replica_server = None
        self.router = Router()
        self._register()
        if recover and self.cfg.persist:
            # Jobs killed by infrastructure (a pod worker death, a process
            # restart mid-job) re-run automatically from their recorded
            # specs — the Spark lost-task re-execution analogue. Must run
            # after _register: the retry runners reuse the same builder /
            # op entry points the routes do.
            self._rescan_failed_jobs()

    # -- helpers -------------------------------------------------------------

    def drain_error(self) -> HttpError:
        """The draining 503: Retry-After sized to the drain window,
        ``Connection: close`` so the keep-alive socket is shed and the
        client's retry lands on a healthy peer instead of this exiting
        process. One constructor — the threaded drain gate and the
        row-channel predict path answer identically."""
        return HttpError(
            503, "server draining for shutdown; retry elsewhere",
            headers={"Retry-After": str(max(
                1, math.ceil(self.cfg.drain_timeout_s))),
                "Connection": "close"})

    def map_exception(self, e: Exception) -> Optional[HttpError]:
        """Domain exception → the reference's status codes — THE one
        mapping, shared by the threaded handler stack (``_wrap``) and
        the multi-worker row-channel path (serving/frontend.py), so the
        process hop can never answer a different status than the
        single-process oracle. Returns None for exceptions the serving
        layer does not own (the caller re-raises → 500 boundary)."""
        try:
            raise e
        except HttpError as he:
            return he
        except QueueFull as qe:
            # Predict queue at capacity: backpressure, not failure.
            # Retry-After + 503 is the contract the client's jittered
            # backoff already honors (PR 2/PR 4); the hint is COMPUTED
            # from predicted queue wait (depth × recent per-row service
            # rate, serving/batcher.py) — when to come back, not a
            # constant.
            return HttpError(
                503, str(qe),
                headers={"Retry-After":
                         str(max(1, math.ceil(qe.retry_after_s)))})
        except DeadlineExceeded as de:
            # The caller's end-to-end budget is unmeetable or already
            # spent: a TERMINAL 504 — distinct from the retryable 503
            # family on purpose (the client never retries it;
            # re-sending abandoned work only deepens overload). No
            # Retry-After: there is nothing to wait for, the budget
            # belonged to the caller.
            return HttpError(504, str(de))
        except ModelQuarantined as me:
            # Terminal until an operator (or a re-save) lifts it — a
            # long Retry-After so stock clients' bounded backoff gives
            # up fast instead of hammering a dead model.
            return HttpError(
                503, str(me),
                headers={"Retry-After": str(max(
                    1, math.ceil(self.cfg.restart_backoff_max_s)))})
        except DispatcherCrashed as ce:
            # The dispatcher crashed after this request's batch hit the
            # device; the supervised restart is already under way —
            # hint its first backoff step.
            return HttpError(
                503, str(ce),
                headers={"Retry-After": str(max(
                    1, math.ceil(self.cfg.serve_restart_backoff_s)))})
        except PredictTimeout as te:
            return HttpError(503, str(te), headers={"Retry-After": "5"})
        except BatcherStopped as se:
            # A request raced the model's dispatcher teardown (DELETE
            # or shutdown): transient — the retry gets the terminal
            # answer (404 if deleted, a fresh dispatcher otherwise).
            return HttpError(503, str(se), headers={"Retry-After": "1"})
        except ChunkCorrupt as xe:
            # Integrity failure the replica couldn't heal: a precise
            # 500 naming the chunk/checksums, not a parse traceback.
            return HttpError(500, str(xe))
        except spmd.PodDegraded as pe:
            # A degraded pod is mid-recovery (its supervisor restarts
            # it under a new mesh epoch): answer 503 + Retry-After
            # COMPUTED from the recovery machinery's own knobs — the
            # supervisor needs a health-poll interval to notice plus
            # its first restart backoff — instead of a hard-coded
            # constant.
            return HttpError(
                503, str(pe),
                headers={"Retry-After": str(max(1, math.ceil(
                    self.cfg.health_interval_s
                    + self.cfg.restart_backoff_s)))})
        except DatasetNotFound as ne:
            return HttpError(404, f"dataset not found: {ne}")
        except ImageNotFound as ie:
            return HttpError(404, f"image not found: {ie}")
        except (DatasetExists, ImageExists) as ee:
            return HttpError(409, f"duplicate: {ee}")
        except KeyError as ke:
            return HttpError(404, str(ke))
        except PermissionError as pr:
            return HttpError(403, str(pr))
        except ValueError as ve:
            return HttpError(406, str(ve))
        except Exception:  # noqa: BLE001 — not serving-owned: 500 boundary
            return None

    def _wrap(self, fn, replay_posts: bool = True):
        """Translate domain exceptions to the reference's status codes.

        The conversion runs INSIDE the idempotency replay boundary: a
        duplicate create replays the first attempt's mapped status
        (e.g. 409), never a generic 500 wrapper around the raw domain
        exception. ``replay_posts=False`` exempts a POST route from the
        replay cache entirely — the online ``/predict`` endpoint is
        read-like (it creates nothing), so a retried request must hit
        the model again, never replay a cached response.
        """

        def convert(req):
            if req.method in ("POST", "PATCH", "DELETE") and \
                    self._draining.is_set():
                # Draining: no NEW work — in-flight requests finish,
                # reads keep serving (operators watch the drain through
                # them).
                raise self.drain_error()
            try:
                return fn(req)
            except HttpError:
                raise
            except Exception as e:  # noqa: BLE001 — mapped or re-raised
                mapped = self.map_exception(e)
                if mapped is None:
                    raise
                raise mapped from e

        def inner(req):
            if req.method == "POST" and replay_posts:
                key = req.header("Idempotency-Key")
                # Key scoped per path: a client reusing one key against a
                # different endpoint must not replay the wrong response.
                return self.idempotency.run(
                    f"{req.path}|{key}" if key else None,
                    lambda: convert(req))
            return convert(req)

        return inner

    def _route(self, method: str, pattern: str, replay_posts: bool = True):
        def deco(fn):
            return self.router.route(method, pattern)(
                self._wrap(fn, replay_posts=replay_posts))

        return deco

    def _deadline_ms(self, header: Optional[str]) -> Optional[float]:
        """The effective deadline budget for one predict request:
        client header clamped to ``serve_deadline_cap_ms``, falling back
        to ``serve_deadline_default_ms`` (0 = none). A malformed header
        is a client error worth naming, not silently ignoring."""
        cap = float(self.cfg.serve_deadline_cap_ms)
        if cap <= 0:
            return None                    # deadline handling disabled
        if header is None or not str(header).strip():
            default = float(self.cfg.serve_deadline_default_ms)
            return min(default, cap) if default > 0 else None
        try:
            budget = float(header)
        except ValueError:
            raise ValueError(
                f"X-Deadline-Ms must be a number of milliseconds, got "
                f"{header!r}") from None
        if budget <= 0:
            # The caller's budget is already spent: pass it through —
            # the predict tier answers the terminal 504 WITH per-model
            # accounting (deadline_exceeded counter + trace record),
            # which raising here would silently skip.
            return budget
        return min(budget, cap)

    # -- routes --------------------------------------------------------------

    def _register(self) -> None:
        app = self

        # ---- database_api (reference database_api_image/server.py:33-96)
        @self._route("POST", "/files")
        def create_file(req):
            filename, url = req.require("filename", "url")
            # Optional per-request override of the range-partitioned
            # ingest fan-out (LO_TPU_INGEST_PARTITIONS supplies the
            # default); 0/1 forces the serial path for this file.
            partitions = req.body.get("partitions")
            cfg = app.cfg
            if partitions is not None:
                cfg = cfg.replace(ingest_partitions=int(partitions))
            app.store.create(filename, url=url)
            app.jobs.submit(
                "ingest", filename,
                lambda: ingest_csv_url(app.store, filename, url, cfg))
            return 201, {"result": f"file {filename} created",
                         "filename": filename}

        @self._route("GET", "/files")
        def list_files(_req):
            return 200, app.store.metadata_docs()

        @self._route("GET", "/files/{name}")
        def read_file(req):
            limit = min(req.q("limit", 10, int), app.cfg.read_limit_cap)
            skip = req.q("skip", 0, int)
            query = req.q("query")
            query = json.loads(query) if query else {}
            return 200, app.store.read(req.params["name"], skip=skip,
                                       limit=limit, query=query)

        @self._route("DELETE", "/files/{name}")
        def delete_file(req):
            app.store.delete(req.params["name"])
            return 200, {"result": "deleted"}

        # ---- projection (reference projection_image/server.py:50-115)
        @self._route("POST", "/projections/{parent}")
        def projection(req):
            parent = req.params["parent"]
            name, fields = req.require("projection_filename", "fields")
            if not app.store.exists(parent):
                raise DatasetNotFound(parent)
            # Validate fields synchronously (reference returns 406 inline).
            parent_fields = app.store.get(parent).metadata.fields
            missing = [f for f in fields if f not in parent_fields]
            if missing:
                raise ValueError(f"fields not in dataset: {missing}")
            app.store.create(name, parent=parent, extra={"job": {
                "kind": "projection", "parent": parent, "name": name,
                "fields": list(fields)}})
            app.jobs.submit(
                "projection", name,
                lambda: create_projection(app.store, parent, name, fields,
                                          existing=True))
            return 201, {"result": f"projection {name} created"}

        # ---- histogram (reference histogram_image/server.py)
        @self._route("POST", "/histograms/{parent}")
        def histogram(req):
            spmd.require_pod_health()
            parent = req.params["parent"]
            name, fields = req.require("histogram_filename", "fields")
            if not app.store.exists(parent):
                raise DatasetNotFound(parent)
            parent_fields = app.store.get(parent).metadata.fields
            missing = [f for f in fields if f not in parent_fields]
            if missing:
                raise ValueError(f"fields not in dataset: {missing}")
            app.store.create(name, parent=parent, extra={"job": {
                "kind": "histogram", "parent": parent, "name": name,
                "fields": list(fields)}})
            app.jobs.submit(
                "histogram", name,
                lambda: create_histogram(app.store, app.runtime, parent,
                                         name, fields, existing=True))
            return 201, {"result": f"histogram {name} created"}

        # ---- data_type_handler (reference data_type_handler server.py:46-76)
        @self._route("PATCH", "/fieldtypes/{name}")
        def fieldtypes(req):
            convert_fields(app.store, req.params["name"], req.body)
            return 200, {"result": "types converted"}

        # ---- model_builder (reference model_builder_image/server.py:52-115)
        @self._route("POST", "/models")
        def models(req):
            spmd.require_pod_health()
            (train, test, pred_name, classifiers, label) = req.require(
                "training_filename", "test_filename", "prediction_filename",
                "classificators_list", "label")
            steps = req.body.get("steps", ())
            code = req.body.get("preprocessor_code")
            hparams = req.body.get("hparams")
            sync = bool(req.body.get("sync", True))
            app.builder.validate(train, test, classifiers, pred_name)
            # Hyperparameter admission: unknown names / out-of-range
            # values 406 HERE, naming the offending key — never a
            # TypeError-500 from a **kwargs splat deep inside a trainer
            # (or worse, a stranded async prediction dataset).
            for c in classifiers:
                validate_hparams(c, (hparams or {}).get(c))

            if sync:
                # The reference's POST /models blocks until all fits finish
                # (SURVEY.md §3.2 "synchronous 201").
                reports = app.builder.build(train, test, pred_name,
                                            classifiers, label, steps=steps,
                                            preprocessor_code=code,
                                            hparams=hparams)
                return 201, {"result": [
                    {"classifier": r.kind, "fit_time": r.fit_time,
                     **r.metrics} for r in reports]}

            # Create every prediction dataset up front (metadata-first), so
            # a failure at ANY point of the async build is pollable on all
            # of them — never the reference's finished:false-forever state.
            # Each carries the job spec that created it: if the pod dies
            # mid-build, the restarted incarnation re-runs the build from
            # this record (exec preprocessor code is excluded — an exec
            # job is not provably re-runnable, so it fails permanently).
            pred_datasets = [f"{pred_name}_{c}" for c in classifiers]
            job_spec = None if code is not None else {
                "kind": "model_builder", "train": train, "test": test,
                "pred_name": pred_name, "classifiers": list(classifiers),
                "label": label, "steps": list(steps),
                "hparams": hparams or {}}
            for c in classifiers:
                extra = {"classifier": c, "label": label}
                if job_spec is not None:
                    extra["job"] = job_spec
                app.store.create(f"{pred_name}_{c}", parent=test,
                                 extra=extra)

            def run():
                app.builder.build(train, test, pred_name, classifiers, label,
                                  steps=steps, preprocessor_code=code,
                                  hparams=hparams, existing=True)

            app.jobs.submit("model_builder", pred_datasets, run)
            return 201, {"result": "model build started",
                         "prediction_datasets": pred_datasets}

        # ---- device-resident hyperparameter search (models/tune.py):
        # one family, a population of configs vmapped into one device
        # program, masked k-fold CV over the resident design, successive
        # halving on checkpoint rungs. The leaderboard lands in the
        # marker dataset's metadata; promote=true additionally refits
        # the winner on all rows and persists it under tune_filename in
        # the trained-model registry.
        @self._route("POST", "/tune")
        def tune_sweep(req):
            spmd.require_pod_health()
            (train, out, classifier, configs, label) = req.require(
                "training_filename", "tune_filename", "classificator",
                "configs", "label")
            steps = req.body.get("steps", ())
            folds = req.body.get("folds")
            rungs = req.body.get("rungs")
            promote = bool(req.body.get("promote", False))
            sync = bool(req.body.get("sync", True))
            # Admission BEFORE any dataset exists: a bad config 406s
            # naming the offending key (models/registry.HPARAM_SPECS)
            # instead of stranding a doomed async marker.
            app.builder.validate_tune(train, out, classifier, configs)

            if sync:
                board = app.builder.tune(train, out, classifier, configs,
                                         label, steps=steps, folds=folds,
                                         rungs=rungs, promote=promote)
                return 201, {"result": board}

            # Metadata-first marker + recorded job spec: a pod death
            # mid-sweep re-runs the sweep from this record, and the
            # rung-boundary fit checkpoints make the re-run resume
            # instead of restarting (builder.tune → tune.sweep).
            job_spec = {"kind": "tune", "train": train, "out": out,
                        "classifier": classifier,
                        "configs": list(configs), "label": label,
                        "steps": list(steps), "folds": folds,
                        "rungs": rungs, "promote": promote}
            app.store.create(out, parent=train,
                             extra={"classifier": classifier,
                                    "label": label, "tune": True,
                                    "job": job_spec})

            def run():
                app.builder.tune(train, out, classifier, configs, label,
                                 steps=steps, folds=folds, rungs=rungs,
                                 promote=promote, existing=True)

            app.jobs.submit("tune", out, run)
            return 201, {"result": "tune sweep started", "poll": out}

        # ---- trained-model registry (upgrade: the reference discards
        # fitted models, SURVEY.md §5; here they persist + re-serve)
        @self._route("GET", "/trained-models")
        def list_trained_models(_req):
            return 200, app.builder.registry.list()

        @self._route("DELETE", "/trained-models/{name}")
        def delete_trained_model(req):
            app.builder.registry.delete(req.params["name"])
            # Compiled predict programs for the deleted model are stale;
            # the next /predict re-stats the manifest and 404s cleanly.
            app.predictor.invalidate(req.params["name"])
            return 200, {"result": "deleted"}

        # ---- online inference (the request/response path the reference
        # never had: predictions only ever materialized as batch jobs).
        # NOT idempotency-replayed: /predict is read-like — two identical
        # POSTs must both hit the model, never a cached response.
        @self._route("POST", "/trained-models/{name}/predict",
                     replay_posts=False)
        def model_predict_online(req):
            spmd.require_pod_health()
            (rows,) = req.require("rows")
            # End-to-end deadline: the client's remaining budget rides
            # the X-Deadline-Ms header (clamped; absent → the server
            # default, 0 = none). Admission, queueing and dispatch all
            # honor it (serving/batcher.py) — expiry is a terminal 504.
            deadline_ms = app._deadline_ms(req.header("X-Deadline-Ms"))
            # Thin enqueue/await shim: feature prep runs here on the
            # handler thread; the per-model dispatcher thread coalesces
            # concurrent requests into one padded AOT device dispatch
            # and scatters the rows back (serving/batcher.py).
            return 200, app.predictor.predict(req.params["name"], rows,
                                              deadline_ms=deadline_ms)

        @self._route("POST", "/trained-models/{name}/predictions")
        def model_predict(req):
            spmd.require_pod_health()
            name = req.params["name"]
            dataset, out = req.require("dataset_name", "prediction_filename")
            if app.store.exists(out):
                raise DatasetExists(out)
            man = app.builder.registry.manifest(name)   # 404 when missing
            if not app.store.exists(dataset):
                raise DatasetNotFound(dataset)
            if man.get("preprocess") is None:
                # Keep the synchronous 406 contract: an exec-preprocessed
                # model can never re-serve, so failing inside the job would
                # just strand a doomed dataset under the requested name.
                raise ValueError(
                    f"model {name} was exec-preprocessed; it carries no "
                    "reproducible preprocessing state to apply to new "
                    "datasets")
            # Metadata-first + async job, like every other compute route: a
            # long predict must not block the HTTP worker, duplicate
            # requests collide on the created dataset (409), and a crash
            # mid-predict leaves a pollable failure record.
            app.store.create(out, parent=dataset,
                             extra={"model": name, "kind": man["kind"],
                                    "job": {"kind": "model_predict",
                                            "model": name,
                                            "dataset": dataset,
                                            "out": out}})
            app.jobs.submit(
                "model_predict", out,
                lambda: app.builder.predict(name, dataset, out,
                                            existing=True))
            return 201, {"result": f"prediction dataset {out} created",
                         "prediction_filename": out}

        # ---- tsne / pca images (reference tsne_image/server.py:57-155)
        for method in ("tsne", "pca"):
            self._register_images(method)

        # ---- catalog administration
        @self._route("POST", "/catalog/scrub")
        def catalog_scrub(req):
            # Proactive integrity pass over the journaled chunk store:
            # verify every chunk checksum, repair from the replica where
            # possible, report what couldn't be healed. Synchronous by
            # design — an admin operation whose caller wants the verdict.
            name = req.body.get("dataset")
            if name is not None and not app.store.exists(name):
                raise DatasetNotFound(name)
            return 200, app.store.scrub(name)

        # ---- observability (upgrade; reference exposed Spark UIs only)
        @self._route("GET", "/cluster")
        def cluster(_req):
            # The supervisor polls this: ``pod_error`` non-null means the
            # pod is degraded and should be restarted under a new epoch.
            info = distributed.process_info()
            info["mesh"] = dict(app.runtime.mesh.shape)
            info["mesh_epoch"] = spmd.mesh_epoch()
            info["pod_error"] = spmd.pod_error()
            info["healthy"] = info["pod_error"] is None
            info["restarts"] = config.restart_count()
            # Per-process resource snapshots: this process sampled live,
            # workers from their last job-channel shipment — so a
            # multi-process pod's host RSS / device HBM is comparable at
            # a glance (lite form: no per-dataset disk walk).
            info["resources"] = {
                str(info["process_index"]):
                    resources.process_snapshot(app.cfg, lite=True),
                **{str(k): v
                   for k, v in resources.remote_snapshots().items()},
            }
            return 200, info

        @self._route("GET", "/jobs")
        def jobs(_req):
            return 200, app.jobs.records()

        @self._route("GET", "/status")
        def status_page(_req):
            # HTML operator view of the same data /cluster, /jobs and
            # /files serve — the reference's Swarm visualizer equivalent
            # (docker-compose.yml:109-121).
            from learningorchestra_tpu.serving.status_page import (
                render_status)

            info = distributed.process_info()
            info["mesh"] = dict(app.runtime.mesh.shape)
            info["mesh_epoch"] = spmd.mesh_epoch()
            info["pod_error"] = spmd.pod_error()
            info["state"] = "draining" if app.draining else "serving"
            # The page's 5 s auto-refresh doubles as the alert engine's
            # heartbeat on watched deployments (_metrics_doc evaluates).
            mdoc = app._metrics_doc()
            return 200, HtmlResponse(render_status(
                info, app.jobs.records(), app.store.metadata_docs(),
                serving=mdoc.get("serving"),
                alerts=mdoc.get("alerts"),
                resources=mdoc.get("resources"),
                attribution=mdoc.get("latency_attribution"),
                # Bounded window: the sparklines render ~140px — serve
                # them from the in-memory ring, never a decode of every
                # retained disk segment per 5 s auto-refresh.
                history=app.history.query(series=[
                    "serving.qps", "serving.queue_rows",
                    "serving.requests", "resources.host.rss_bytes"],
                    window_s=3600)))

        @self._route("GET", "/metrics")
        def metrics(req):
            doc = app._metrics_doc()
            if req.q("format") == "prometheus":
                from learningorchestra_tpu.utils import prometheus

                # Same registry snapshot, second format: the exposition
                # text is rendered from the identical doc the JSON view
                # serves, so the two can never disagree.
                return 200, TextResponse(prometheus.render(doc))
            return 200, doc

        @self._route("GET", "/metrics/history")
        def metrics_history(req):
            # The retained time-series behind the instantaneous
            # /metrics view: ring + on-disk delta segments, so the
            # answer covers windows no scrape happened to observe —
            # including pre-restart ones.
            app._metrics_doc()          # contribute a sample (gated)
            series = req.q("series")
            window = req.q("window", cast=float)
            return 200, app.history.query(
                series=[s.strip() for s in series.split(",") if s.strip()]
                if series else None,
                window_s=window)

        # ---- tracing (the request/job-scoped view /metrics can't give:
        # "where did THIS request spend its time")
        @self._route("GET", "/traces")
        def traces(req):
            return 200, tracing.recent_traces(
                route=req.q("route"),
                kind=req.q("kind"),
                min_ms=req.q("min_ms", cast=float),
                limit=req.q("limit", 50, int))

        @self._route("GET", "/trace/{trace_id}")
        def trace_by_id(req):
            tree = tracing.trace_tree(req.params["trace_id"])
            if tree is None:
                raise HttpError(
                    404, f"no spans for trace {req.params['trace_id']} "
                    "(expired from the ring buffer, unsampled, or never "
                    "existed)")
            return 200, tree

        # ---- resource & capacity plane (utils/resources.py, /alerts.py)
        @self._route("GET", "/resources")
        def resources_view(_req):
            # Per-device HBM + host + disk + compile accounting for THIS
            # process, plus last-known worker snapshots on a pod.
            doc = resources.process_snapshot(app.cfg)
            workers = resources.remote_snapshots()
            if workers:
                doc["workers"] = {str(k): v for k, v in workers.items()}
            return 200, doc

        @self._route("GET", "/alerts")
        def alerts_view(_req):
            # Reading /alerts advances an evaluation window like every
            # other registry read — an operator polling this page IS the
            # alert engine's clock.
            app._metrics_doc()
            doc = app.alerts.snapshot()
            # The freshest evidence bundle rides along so anything that
            # reports a firing alert can point at it (the client SDK
            # quotes it in raised errors).
            doc["flightrec_latest"] = app.flightrec.latest()
            return 200, doc

        @self._route("GET", "/replication")
        def replication_view(_req):
            # The replication section of /metrics, standalone (the
            # client SDK's Observability.replication() passthrough):
            # per-dataset lag against each peer's acked watermark, the
            # under-replicated list, push/fetch/repair counters. Reading
            # it ticks the push committer's retry check like a scrape.
            doc = app.store.replication_snapshot()
            if app._replica_server is not None:
                doc["server"] = app._replica_server.snapshot()
            return 200, doc

        @self._route("GET", "/healthz")
        def healthz(_req):
            doc = app._health_doc()
            healthy = doc["healthy"]
            if app._was_healthy is not False and not healthy:
                # The healthy → 503 edge is itself an incident worth
                # freezing: by the time a human reads the page, the
                # trace ring has moved on.
                app.flightrec.dump(
                    "healthz:503",
                    detail={"checks": {
                        k: c for k, c in doc["checks"].items()
                        if isinstance(c, dict) and not c.get("ok")}})
                doc["flightrec_latest"] = app.flightrec.latest()
            app._was_healthy = healthy
            return (200 if healthy else 503), doc

        @self._route("GET", "/debug/flightrec")
        def flightrec_list(_req):
            return 200, app.flightrec.list()

        @self._route("POST", "/debug/flightrec", replay_posts=False)
        def flightrec_dump(req):
            # Manual trigger: bypasses the automatic-dump rate limit
            # (an operator asking for evidence should get it), still
            # bounded by retention. Read-like — never idempotency-
            # replayed.
            reason = str(req.body.get("reason") or "manual")
            bundle = app.flightrec.dump(f"manual:{reason}", force=True)
            if bundle is None:
                raise ValueError(
                    "flight recorder disabled (LO_TPU_FLIGHTREC_KEEP=0) "
                    "or dump failed — see server logs")
            return 201, {"result": "flight-recorder bundle dumped",
                         "bundle": bundle,
                         "dir": os.path.join(app.flightrec.root, bundle)}

        @self._route("POST", "/debug/profile")
        def debug_profile(req):
            # Knob-gated (LO_TPU_DEBUG_PROFILE): profiling costs real
            # overhead and writes operator-readable traces to disk, so
            # it is an explicit opt-in → 403 otherwise.
            if not app.cfg.debug_profile:
                raise PermissionError(
                    "on-demand profiling is disabled; set "
                    "LO_TPU_DEBUG_PROFILE=1 to enable POST /debug/profile")
            try:
                seconds = float(req.body.get("seconds", 2.0))
            except (TypeError, ValueError):
                raise ValueError("seconds must be a number") from None
            if seconds <= 0 or seconds > resources.PROFILE_MAX_SECONDS:
                raise ValueError(
                    f"seconds must be in (0, "
                    f"{resources.PROFILE_MAX_SECONDS:.0f}]")
            out_dir = os.path.join(
                app.cfg.store_root, "_profiles",
                time.strftime("%Y%m%d-%H%M%S"))
            rec = app.jobs.submit(
                "debug_profile", [],
                lambda: resources.capture_profile(out_dir, seconds))
            return 201, {"result": "profile capture started",
                         "dir": out_dir, "seconds": seconds,
                         "job_id": rec.job_id}

    def _metrics_doc(self) -> dict:
        """The one metrics registry snapshot both /metrics formats render
        (JSON as-is; ?format=prometheus through utils/prometheus). The
        alert engine evaluates over this exact snapshot — window-gated,
        so scrape cadence is evaluation cadence — and its state rides
        back in the same document, so an alert can never fire on a
        number the operator cannot see."""
        from learningorchestra_tpu import jobs as jobs_module
        from learningorchestra_tpu.catalog import ingest as ingest_module
        from learningorchestra_tpu.catalog import readpipe
        from learningorchestra_tpu.models import tune as tune_module
        from learningorchestra_tpu.utils import fitckpt
        from learningorchestra_tpu.utils.profiling import op_timer

        by_status: dict = {}
        for r in self.jobs.records():
            by_status[r["status"]] = by_status.get(r["status"], 0) + 1
        pod_error = spmd.pod_error()
        doc = {"state": "draining" if self.draining else "serving",
               "ops": op_timer.snapshot(),
               "jobs": by_status,
               # Job-tier fault counters (watchdog kills, checkpoint
               # resumes) + the fit-checkpoint store's disk footprint —
               # the resumable-fit plane's health at a glance.
               "job_fault": jobs_module.fault_snapshot(),
               "fit_checkpoints": fitckpt.disk_snapshot(self.cfg),
               # Hyperparameter-search plane: populations fitted,
               # candidates evaluated, halving drops, HBM-budget wave
               # spills (rendered as lo_tune_* on the exposition
               # surface).
               "tune": tune_module.counters_snapshot(),
               "integrity": self.store.integrity_snapshot(),
               "read_pipeline": readpipe.snapshot(),
               # Range-partitioned ingest plane (lo_ingest_partition_*)
               # and the shard-placement planner's local/remote feed
               # classification (lo_shard_*_total) — the local fraction
               # is the placement health signal.
               "ingest": ingest_module.counters_snapshot(),
               "shard": readpipe.shard_snapshot(),
               "serving": self.predictor.snapshot(),
               "tracing": tracing.counters_snapshot(),
               # The span-taxonomy aggregation: per-model queue-wait /
               # device / design histograms, per-family fit sub-phases,
               # per-route handling — "where did the p99 go" without
               # grepping /traces.
               "latency_attribution": tracing.attribution_snapshot(),
               "resources": resources.process_snapshot(self.cfg),
               "compile": resources.compile_snapshot(),
               "pod": {"error": pod_error,
                       "degraded": pod_error is not None},
               "profile_dir": self.cfg.profile_dir or None,
               # Cross-host replication plane: per-dataset lag against
               # each peer's acked watermark, push/fetch/repair
               # counters, and the under-replicated list the
               # data_under_replicated alert and /healthz check read.
               # Snapshotting doubles as the read-driven retry tick.
               "replication": self.store.replication_snapshot()}
        if self._replica_server is not None:
            doc["replication"]["server"] = self._replica_server.snapshot()
        if self._frontend is not None:
            # Multi-worker topology only: accept-process liveness,
            # respawn accounting and row-channel frame counters
            # (rendered as lo_frontend_* on the exposition surface).
            doc["frontend"] = self._frontend.snapshot()
        # History BEFORE alert evaluation: the burn-rate rules read the
        # store, so the sample that triggered this read must be in it.
        self.history.observe(doc)
        doc["telemetry"] = self.history.snapshot()
        transitions = self.alerts.observe(doc)
        doc["alerts"] = self.alerts.snapshot()
        for t in transitions:
            if t["to"] == "firing":
                # Freeze the evidence at the transition: rate-limited
                # (flightrec_min_interval_s), so a flapping rule
                # records its first edge, not one bundle per flap.
                self.flightrec.dump(f"alert:{t['alert']}", detail=t,
                                    doc=doc)
        doc["flightrec"] = self.flightrec.snapshot()
        return doc

    def _health_doc(self) -> dict:
        """The deep ``GET /healthz`` rollup: pod health, disk headroom,
        predict-dispatcher liveness, lifecycle state, and the alert
        summary — 200 when every check passes and no critical alert
        fires, 503 (with this same JSON detail) otherwise. A DRAINING
        server reports ``state: draining`` and is unhealthy by design:
        load balancers must stop routing to a process about to exit,
        while the in-flight work it still owes completes behind the
        gate."""
        mdoc = self._metrics_doc()
        disk = (mdoc.get("resources") or {}).get("disk") or {}
        watermark = int(self.cfg.disk_free_watermark_mb) * (1 << 20)
        free = disk.get("free_bytes")
        disk_ok = (watermark <= 0 or free is None or free >= watermark)
        dispatchers = self.predictor.health()
        pod_error = (mdoc.get("pod") or {}).get("error")
        firing = self.alerts.firing()
        critical = self.alerts.firing(severity="critical")
        draining = self._draining.is_set()
        checks = {
            "pod": {"ok": pod_error is None, "error": pod_error},
            "disk": {"ok": disk_ok, "free_bytes": free,
                     "watermark_bytes": watermark},
            "dispatchers": dispatchers,
            "lifecycle": {"ok": not draining,
                          "state": "draining" if draining else "serving"},
            "alerts": {"ok": not critical, "firing": firing,
                       "critical": critical},
        }
        if self._frontend is not None:
            # At least one accept process must be alive for the port to
            # answer at all; a respawn window (some dead, some alive)
            # degrades capacity, not health — the kernel routes around
            # dead listeners and the supervisor is already respawning.
            fr = mdoc.get("frontend") or {}
            checks["frontend"] = {
                "ok": (fr.get("workers_alive") or 0) > 0,
                "workers": fr.get("workers"),
                "workers_alive": fr.get("workers_alive"),
                "slots_abandoned": fr.get("slots_abandoned"),
            }
        rep = mdoc.get("replication") or {}
        if rep.get("enabled"):
            # Peer topology only (check absent otherwise, so single-host
            # deployments keep their healthz schema): a host that cannot
            # replicate committed data is a durability incident — depool
            # it and let the runbook's re-replicate leg clear the lag.
            under = rep.get("under_replicated") or []
            checks["replication"] = {
                "ok": not under,
                "peers": rep.get("peers"),
                "max_lag_bytes": rep.get("max_lag_bytes"),
                "under_replicated": under,
            }
        return {"healthy": all(c["ok"] for c in checks.values()),
                "state": "draining" if draining else "serving",
                "checks": checks,
                "mesh_epoch": spmd.mesh_epoch(),
                # The freshest evidence bundle, if any: a degraded
                # verdict points at its black box (the client SDK
                # quotes this id in the error it raises).
                "flightrec_latest": self.flightrec.latest()}

    def _register_images(self, method: str) -> None:
        app = self
        svc = self.images[method]

        @self._route("POST", f"/{method}/images/{{parent}}")
        def create_image(req, method=method, svc=svc):
            spmd.require_pod_health()
            name = req.body.get("image_name") or req.body.get(
                f"{method}_filename")
            if not name:
                raise ValueError("missing image_name")
            label = req.body.get("label_name")
            svc.validate_new(name)
            if not app.store.exists(req.params["parent"]):
                raise DatasetNotFound(req.params["parent"])
            parent = req.params["parent"]
            # Validate label synchronously like the reference (tsne.py:154-186)
            if label is not None and label not in app.store.get(
                    parent).metadata.fields:
                raise ValueError(f"label field not in dataset: {label}")
            marker = f"img.{method}.{name}"
            # A finished marker whose PNG is gone (deleted, or the job
            # failed) is stale — clear it so the name is reusable. An
            # unfinished marker means a build is in flight: 409.
            if app.store.exists(marker):
                if not app.store.get(marker).metadata.finished:
                    raise DatasetExists(
                        f"{method} image {name} build in progress")
                app.store.delete(marker)
            app.store.create(marker, parent=parent)
            kwargs = {k: req.body[k] for k in
                      ("perplexity", "iters") if k in req.body}

            def run():
                create_embedding_image(app.store, app.runtime, method,
                                       parent, name, label=label,
                                       image_root=app.cfg.image_root,
                                       marker=marker, **kwargs)
                app.store.finish(marker)

            app.jobs.submit(f"{method}_image", marker, run)
            return 201, {"result": f"{method} image {name} started",
                         "poll": marker}

        @self._route("GET", f"/{method}/images")
        def list_images(_req, svc=svc):
            return 200, svc.list_names()

        @self._route("GET", f"/{method}/images/{{name}}")
        def get_image(req, svc=svc):
            return 200, FileResponse(svc.get_path(req.params["name"]))

        @self._route("DELETE", f"/{method}/images/{{name}}")
        def delete_image(req, method=method, svc=svc):
            svc.delete(req.params["name"])
            # Drop the poll-marker dataset too, so the name can be reused.
            marker = f"img.{method}.{req.params['name']}"
            if app.store.exists(marker):
                app.store.delete(marker)
            return 200, {"result": "deleted"}

    # -- automatic job retry (elastic recovery, supervisor.py) ---------------

    def _retry_runner(self, spec, names):
        """The re-run callable for one recorded job spec (owning the
        failed output datasets ``names``), or None for an unknown kind
        (a newer incarnation's spec — leave it failed)."""
        kind = spec.get("kind")
        if kind == "model_builder":
            # Re-fit only the classifiers whose outputs failed: ones that
            # finished before the pod died keep their results (re-running
            # them would append duplicate prediction rows).
            pred = spec["pred_name"]
            classifiers = [c for c in spec["classifiers"]
                           if f"{pred}_{c}" in set(names)]
            return lambda: self.builder.build(
                spec["train"], spec["test"], pred,
                classifiers, spec["label"],
                steps=spec.get("steps") or (),
                hparams=spec.get("hparams") or {}, existing=True)
        if kind == "histogram":
            return lambda: create_histogram(
                self.store, self.runtime, spec["parent"], spec["name"],
                spec["fields"], existing=True)
        if kind == "projection":
            return lambda: create_projection(
                self.store, spec["parent"], spec["name"], spec["fields"],
                existing=True)
        if kind == "model_predict":
            return lambda: self.builder.predict(
                spec["model"], spec["dataset"], spec["out"], existing=True)
        if kind == "tune":
            # The re-run resumes from the sweep's rung-boundary fit
            # checkpoints (same config key), so a pod death at rung k
            # costs rung k, not the whole population.
            return lambda: self.builder.tune(
                spec["train"], spec["out"], spec["classifier"],
                spec["configs"], spec["label"],
                steps=spec.get("steps") or (),
                folds=spec.get("folds"), rungs=spec.get("rungs"),
                promote=bool(spec.get("promote")), existing=True)
        return None

    def _rescan_failed_jobs(self) -> None:
        """Re-run jobs the previous incarnation lost to infrastructure.

        The watchdog fails a dispatched job's outputs with ``pod
        failure:`` when a worker dies; a process restart mid-job marks
        unfinished outputs ``interrupted:`` (catalog load_all). Both mean
        the JOB was sound but the pod wasn't — so after the supervisor
        restarts the pod, re-run each such job from the spec recorded in
        its outputs' metadata, up to ``Settings.job_retries`` attempts
        per output (tracked in its ``retries`` counter). Outputs are
        reset via ``DatasetStore.reopen`` first, so pollers see them go
        back in flight and a partial write never duplicates rows.
        """
        if self.cfg.job_retries <= 0:
            return
        groups = select_retry_groups(self.store.metadata_docs(),
                                     self.cfg.job_retries)
        for group in groups:
            spec, names = group["spec"], group["datasets"]
            runner = self._retry_runner(spec, names)
            if runner is None:
                log.warning("not retrying %s: unknown job kind %r",
                            names, spec.get("kind"))
                continue
            for name in names:
                self.store.reopen(name)
            log.info("retrying %s job for %s (pod recovered)",
                     spec["kind"], names)
            self.jobs.submit(f"retry_{spec['kind']}", names, runner)

    # -- lifecycle -----------------------------------------------------------

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    def begin_drain(self) -> None:
        """Flip the app into the draining state: new work (POST/PATCH/
        DELETE) answers 503 + Retry-After + ``Connection: close``,
        reads and already-accepted work continue, ``/healthz`` reports
        ``draining`` (→ 503, so load balancers depool this process).
        Idempotent."""
        if not self._draining.is_set():
            self._draining.set()
            log.warning("draining: new work rejected 503; waiting for "
                        "in-flight predicts and queued jobs")

    def drain(self, timeout_s: Optional[float] = None) -> bool:
        """Gate off new work, then wait (up to ``timeout_s``, default
        ``LO_TPU_DRAIN_TIMEOUT_S``) for every accepted predict to
        scatter back and every queued job to reach a terminal state —
        job completion implies its journal fsyncs committed, so nothing
        durable is in flight when this returns. Then stop the predict
        dispatchers. Returns True when fully quiesced within the
        window, False when the timeout expired with work still running
        (the caller exits anyway — bounded beats perfect on the way
        down)."""
        self.begin_drain()
        deadline = time.monotonic() + float(
            self.cfg.drain_timeout_s if timeout_s is None else timeout_s)
        quiesced = False
        while time.monotonic() < deadline:
            if self.predictor.quiesced() and self.jobs.running_count() == 0:
                quiesced = True
                break
            time.sleep(0.05)
        if quiesced:
            log.info("drain complete: all accepted work finished")
        else:
            log.error("drain timeout: exiting with work still in flight "
                      "(predict queues quiesced=%s, running jobs=%d)",
                      self.predictor.quiesced(), self.jobs.running_count())
        self.predictor.stop()
        return quiesced

    def serve(self, background: bool = False):
        if int(self.cfg.http_workers) > 1:
            # Multi-worker front end (ROADMAP item 1): N SO_REUSEPORT
            # accept processes own the HTTP sockets, THIS process owns
            # the device and every serving semantic, and the two meet
            # on the row channel (serving/frontend.py). Same start/
            # stop/port surface as the threaded Server, so callers
            # cannot tell the topologies apart.
            from learningorchestra_tpu.serving.frontend import (
                FrontendServer)

            server = FrontendServer(self, self.cfg.host, self.cfg.port)
            self._frontend = server
        else:
            # LO_TPU_HTTP_WORKERS unset/1: today's single-process
            # topology, byte-for-byte — the oracle the multi-worker
            # path is tested against.
            server = Server(self.router, self.cfg.host, self.cfg.port,
                            request_timeout_s=self.cfg.http_timeout_s)
            self._frontend = None
        # Stopping the server stops the predict dispatcher threads too
        # (queued requests fail fast instead of waiting out their
        # timeout against a dead worker).
        server.on_stop(self.predictor.stop)
        if int(self.cfg.replica_port) > 0:
            # This host's receive side of the replication plane: peers
            # push journal prefixes here and fetch chunks back out for
            # remote repair. Writes land under replica_root (or
            # <store_root>/_replicas), the same layout the local-mirror
            # restore path already reads; fetches also consult the
            # primary store_root so peers can heal from datasets this
            # host natively owns.
            from learningorchestra_tpu.catalog import replicate

            self._replica_server = replicate.ReplicaServer(
                root=(self.cfg.replica_root
                      or os.path.join(self.cfg.store_root, "_replicas")),
                host=self.cfg.host, port=int(self.cfg.replica_port),
                extra_roots=(self.cfg.store_root,),
                timeout_s=self.cfg.replica_timeout_s)
            server.on_stop(self._replica_server.stop)
        # The push committer (if peers are configured) dies with the
        # server so a drain never strands a half-pushed journal suffix
        # silently — the watermark keeps it resumable on restart.
        server.on_stop(self.store.stop_replication)
        # The telemetry sampler lives exactly as long as the server:
        # started here (bare App construction spawns no threads — tests
        # drive history via reads), stopped with it — and the stop
        # flushes the partial segment so a restarted process serves the
        # pre-shutdown window from disk.
        self.history.start()
        server.on_stop(self.history.stop)
        if background:
            return server.start_background()
        server.serve_forever()
        return server
