"""Cluster status page — the HTML view of /cluster + /jobs + /files.

The reference ships a live Docker Swarm visualizer on port 80
(docker-compose.yml:109-121) so operators can see cluster topology and
task placement in a browser. Here the equivalent operator surface is one
self-refreshing HTML page over the same data the JSON routes serve:
process/mesh topology, the job ledger, and the dataset catalog. No
JavaScript framework, no assets — a single stdlib-rendered page, because
the deployment story is "one binary" (SURVEY.md §7).
"""

from __future__ import annotations

from html import escape
from typing import Any, Dict, List, Optional

_STYLE = """
body { font-family: system-ui, sans-serif; margin: 2rem; color: #1a1a2e;
       background: #f7f7fb; }
h1 { font-size: 1.4rem; } h2 { font-size: 1.1rem; margin-top: 1.6rem; }
table { border-collapse: collapse; width: 100%; background: #fff;
        box-shadow: 0 1px 2px rgba(0,0,0,.08); }
th, td { text-align: left; padding: .35rem .6rem; font-size: .85rem;
         border-bottom: 1px solid #e8e8ef; }
th { background: #eceff6; }
.badge { display: inline-block; padding: .1rem .45rem; border-radius: .6rem;
         font-size: .75rem; color: #fff; }
.done { background: #2e7d32; } .failed { background: #c62828; }
.running { background: #1565c0; } .queued { background: #8d6e63; }
.kv { display: inline-block; margin-right: 1.2rem; }
.kv b { color: #444; }
"""

_STATUS_CLASS = {"done": "done", "failed": "failed",
                 "running": "running", "queued": "queued",
                 "firing": "failed", "ok": "done",
                 "quarantined": "failed", "draining": "queued",
                 "serving": "done"}


def _fmt_bytes(n: Any) -> str:
    if not isinstance(n, (int, float)):
        return ""
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024
    return ""


def _badge(status: str) -> str:
    cls = _STATUS_CLASS.get(status, "queued")
    return f'<span class="badge {cls}">{escape(status)}</span>'


def _table(headers: List[str], rows: List[List[str]]) -> str:
    head = "".join(f"<th>{escape(h)}</th>" for h in headers)
    body = "".join(
        "<tr>" + "".join(f"<td>{c}</td>" for c in row) + "</tr>"
        for row in rows)
    return f"<table><tr>{head}</tr>{body}</table>"


def _phase_breakdown(attribution: Optional[Dict[str, Any]],
                     model: str) -> str:
    """One cell answering "where did this model's p99 go": the per-
    phase p99s the span-taxonomy aggregation attributes to it
    (queue wait / device dispatch / design build)."""
    if not attribution:
        return ""
    parts = []
    for phase, short in (("queue.wait", "queue"),
                         ("dispatch.device", "device"),
                         ("design.build", "design")):
        ent = (attribution.get(phase) or {}).get(model)
        if ent and ent.get("p99_ms") is not None:
            parts.append(f"{short} {ent['p99_ms']:g}")
    return escape(" / ".join(parts))


def _sparkline(points: List[List[float]], width: int = 140,
               height: int = 28) -> str:
    """One series as an inline SVG polyline — no JS, no assets, exactly
    like the rest of the page. Flat series render mid-height so 'no
    traffic' looks calm, not broken."""
    if len(points) < 2:
        return '<span style="color:#aaa">no history</span>'
    ts = [p[0] for p in points]
    vs = [p[1] for p in points]
    t0, t1 = min(ts), max(ts)
    v0, v1 = min(vs), max(vs)
    tspan = (t1 - t0) or 1.0
    vspan = (v1 - v0) or 1.0
    coords = " ".join(
        f"{(t - t0) / tspan * (width - 2) + 1:.1f},"
        f"{height - 1 - (v - v0) / vspan * (height - 2):.1f}"
        for t, v in zip(ts, vs))
    return (f'<svg width="{width}" height="{height}" '
            f'viewBox="0 0 {width} {height}">'
            f'<polyline points="{coords}" fill="none" '
            f'stroke="#1565c0" stroke-width="1.5"/></svg>')


def _history_section(history: Optional[Dict[str, Any]]) -> str:
    """Sparklines over the telemetry history store — the page's answer
    to "what happened while nobody was watching" (the JSON form lives
    at /metrics/history)."""
    if not history or not history.get("series"):
        return ""
    cells = []
    for name, points in sorted(history["series"].items()):
        last = points[-1][1] if points else ""
        cells.append(
            f'<span class="kv"><b>{escape(str(name))}</b> '
            f'{_sparkline(points)} {escape(f"{last:g}")}</span>')
    span = ""
    if history.get("from") and history.get("to"):
        span = (f'<p style="color:#888;font-size:.75rem">'
                f'{history.get("samples", 0)} samples over '
                f'{history["to"] - history["from"]:.0f}s — full series '
                f'at <a href="/metrics/history">/metrics/history</a></p>')
    return f"<h2>History</h2><p>{''.join(cells)}</p>{span}"


def _serving_section(serving: Optional[Dict[str, Any]],
                     attribution: Optional[Dict[str, Any]] = None) -> str:
    """The online-inference panel: queue depth, p99, QPS per model — so
    backpressure is visible at a glance without curling /metrics. The
    phase column decomposes each model's latency (queue / device /
    design p99s from the span-taxonomy aggregation)."""
    if not serving:
        return ""
    agg = "".join(
        f'<span class="kv"><b>{escape(str(k))}</b> {escape(str(v))}</span>'
        for k, v in serving.items()
        if k not in ("models", "aot") and v is not None)
    rows = []
    for name, m in sorted((serving.get("models") or {}).items()):
        rows.append([
            escape(str(name)),
            _badge("quarantined" if m.get("quarantined") else "ok"),
            _replica_cells(m.get("replicas")),
            escape(str(m.get("requests", 0))),
            escape(str(m.get("qps", 0))),
            escape(str(m.get("mean_batch_rows", 0))),
            escape(str(m.get("queue_rows", 0))),
            escape("" if m.get("p50_ms") is None else str(m["p50_ms"])),
            escape("" if m.get("p99_ms") is None else str(m["p99_ms"])),
            _phase_breakdown(attribution, str(name)),
            escape(str(m.get("rejected", 0))),
            escape(str(m.get("deadline_exceeded", 0))),
            escape(str(m.get("dispatcher_restarts", 0))),
        ])
    table = _table(["model", "state", "replicas", "requests", "qps",
                    "rows/batch", "queue", "p50 (ms)", "p99 (ms)",
                    "phase p99s (ms)", "rejected (503)", "expired (504)",
                    "restarts"], rows)
    return (f"<h2>Online predict ({len(rows)} models)</h2>"
            f"<p>{agg}</p>{table}")


def _replica_cells(replicas: Optional[List[Dict[str, Any]]]) -> str:
    """One compact line per device replica: index, queue depth, qps and
    quarantine flag — the router's view of the replica plane, readable
    without curling the per-replica Prometheus series."""
    if not replicas:
        return ""
    parts = []
    for r in replicas:
        state = " ⛔" if r.get("quarantined") else ""
        parts.append(escape(
            f"r{r.get('replica', '?')}: q={r.get('queue_rows', 0)} "
            f"qps={r.get('qps', 0)}{state}"))
    return "<br>".join(parts)


def _alerts_section(alerts: Optional[Dict[str, Any]]) -> str:
    """The SLO panel: every rule with its state, so 'is the service
    healthy against its SLOs' is answerable without curling /alerts."""
    if not alerts or not alerts.get("rules"):
        return ""
    firing = alerts.get("firing") or []
    rows = []
    for name, r in sorted(alerts["rules"].items()):
        rows.append([
            escape(str(name)),
            escape(str(r.get("severity", ""))),
            _badge("firing" if r.get("firing") else "ok"),
            escape("" if r.get("value") is None
                   else f"{r['value']:.6g}"),
            escape(f"{r.get('op', '>')} {r.get('threshold'):.6g}"),
            escape(str(r.get("fired_count", 0))),
        ])
    head = (f'<p><span class="kv"><b>firing</b> '
            f'{escape(", ".join(firing) or "none")}</span></p>')
    return (f"<h2>Alerts ({len(firing)} firing)</h2>{head}"
            + _table(["rule", "severity", "state", "value", "threshold",
                      "times fired"], rows))


def _resources_section(res: Optional[Dict[str, Any]]) -> str:
    """One line of capacity vitals: host RSS/fds, device bytes, disk
    headroom, compile totals — the /resources snapshot at a glance."""
    if not res:
        return ""
    host = res.get("host") or {}
    dev = res.get("devices") or {}
    disk = res.get("disk") or {}
    comp = res.get("compile") or {}
    kvs = [
        ("host rss", _fmt_bytes(host.get("rss_bytes"))),
        ("open fds", host.get("open_fds")),
        ("device bytes", _fmt_bytes(dev.get("total_bytes_in_use"))),
        # Per-device occupancy as compact d<i>=<bytes> pairs — with
        # replicated serving params every replica's device shows up,
        # not just device 0; devices holding nothing are elided.
        ("per device", " ".join(
            f"d{i}={_fmt_bytes(d['bytes_in_use'])}"
            for i, d in enumerate(dev.get("devices") or [])
            if d.get("bytes_in_use")) or None),
        ("device source", dev.get("source")),
        ("store", _fmt_bytes(disk.get("store_bytes"))),
        ("disk free", _fmt_bytes(disk.get("free_bytes"))),
        ("compiles", comp.get("compiles")),
        ("compile s", comp.get("compile_s")),
    ]
    line = "".join(
        f'<span class="kv"><b>{escape(str(k))}</b> {escape(str(v))}</span>'
        for k, v in kvs if v not in (None, ""))
    return f"<h2>Resources</h2><p>{line}</p>"


def render_status(cluster: Dict[str, Any], jobs: List[Dict[str, Any]],
                  datasets: List[Dict[str, Any]],
                  refresh_seconds: int = 5,
                  serving: Optional[Dict[str, Any]] = None,
                  alerts: Optional[Dict[str, Any]] = None,
                  resources: Optional[Dict[str, Any]] = None,
                  attribution: Optional[Dict[str, Any]] = None,
                  history: Optional[Dict[str, Any]] = None) -> str:
    """Render the operator page. Inputs are exactly what the JSON routes
    return, so the page can never disagree with the API."""
    mesh = cluster.get("mesh") or {}
    mesh_txt = " × ".join(f"{escape(str(k))}={escape(str(v))}"
                          for k, v in mesh.items()) or "—"
    cluster_kvs = "".join(
        f'<span class="kv"><b>{escape(str(k))}</b> {escape(str(v))}</span>'
        for k, v in cluster.items() if k != "mesh")

    job_rows = []
    for j in sorted(jobs, key=lambda j: j.get("started_at", 0),
                    reverse=True):
        job_rows.append([
            escape(str(j.get("job_id", ""))),
            escape(str(j.get("kind", ""))),
            escape(str(j.get("dataset", ""))),
            _badge(str(j.get("status", ""))),
            escape(f"{j['duration']:.1f}"
                   if j.get("duration") is not None else ""),
            escape(str(j.get("error") or "")),
        ])

    ds_rows = []
    for d in sorted(datasets, key=lambda d: str(d.get("filename", ""))):
        state = ("failed" if d.get("error")
                 else "done" if d.get("finished") else "running")
        ds_rows.append([
            escape(str(d.get("filename", ""))),
            escape(str(d.get("parent_filename") or d.get("url") or "")),
            _badge(state),
            escape(str(len(d.get("fields") or []))),
            escape(str(d.get("error") or "")),
        ])

    return f"""<!doctype html>
<html><head><meta charset="utf-8">
<meta http-equiv="refresh" content="{refresh_seconds}">
<title>learningorchestra-tpu cluster</title>
<style>{_STYLE}</style></head>
<body>
<h1>learningorchestra-tpu — cluster status</h1>
<p>{cluster_kvs}<span class="kv"><b>mesh</b> {mesh_txt}</span></p>
{_alerts_section(alerts)}
{_resources_section(resources)}
{_serving_section(serving, attribution)}
{_history_section(history)}
<h2>Jobs ({len(jobs)})</h2>
{_table(["job", "kind", "target datasets", "status", "runtime (s)",
         "error"], job_rows)}
<h2>Datasets ({len(datasets)})</h2>
{_table(["name", "origin", "state", "fields", "error"], ds_rows)}
<p style="color:#888;font-size:.75rem">auto-refreshes every
{refresh_seconds}s — JSON at <a href="/cluster">/cluster</a>,
<a href="/jobs">/jobs</a>, <a href="/files">/files</a>,
<a href="/metrics">/metrics</a>,
<a href="/metrics/history">/metrics/history</a>,
<a href="/traces">/traces</a>,
<a href="/debug/flightrec">/debug/flightrec</a>,
<a href="/resources">/resources</a>,
<a href="/alerts">/alerts</a>,
<a href="/healthz">/healthz</a>; Prometheus at
<a href="/metrics?format=prometheus">/metrics?format=prometheus</a></p>
</body></html>"""
