from learningorchestra_tpu.serving.app import App  # noqa: F401
from learningorchestra_tpu.serving.http import Server  # noqa: F401
