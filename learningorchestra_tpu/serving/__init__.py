"""Serving package. ``App`` and ``Server`` are lazy attributes (PEP 562)
rather than eager imports: front-end worker processes (serving/
frontend.py) import sibling modules from this package and must NOT pull
``app``'s transitive jax/device stack into every accept process — the
whole point of the worker split is that only the batcher process owns
the device."""


def __getattr__(name):
    if name == "App":
        from learningorchestra_tpu.serving.app import App

        return App
    if name == "Server":
        from learningorchestra_tpu.serving.http import Server

        return Server
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
