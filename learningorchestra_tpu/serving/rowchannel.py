"""The row channel: length-prefixed frames between front-end workers and
the device-owning process, plus the binary columnar predict body codec.

The multi-worker front end (serving/frontend.py) splits HTTP handling
from device ownership: N accept processes parse sockets and JSON, ONE
process owns the batcher and the device. This module is the seam between
them —

- a **frame protocol**: ``u32 header_len | u32 payload_len |
  header JSON | payload bytes``. Headers are small JSON dicts carrying a
  ``kind`` plus routing fields (frame id, model name, trace context via
  ``tracing.to_wire``); payloads carry the bulk bytes (row buffers,
  proxied request/response bodies) so row data never round-trips through
  JSON on the channel;
- a **binary columnar body codec** (``application/x-lo-columnar``): a
  16-byte header + a packed float32 row-major matrix. Decoding is
  ``np.frombuffer(...).reshape(...)`` — the bytes the socket delivered
  ARE the design matrix ``design_from_rows`` feeds to the device, zero
  per-row decode. The same content type works against the single-process
  topology (serving/http.py reads it) so clients need not know the
  server's worker count;
- the **channel server** run by the device-owning process: one reader
  thread per worker connection, frames handled on a bounded pool
  (``LO_TPU_FRONTEND_CHANNEL_THREADS``) because predict frames block
  awaiting the batcher — the explicit analogue of the threaded server's
  handler threads. Replies are written under a per-connection lock so
  concurrent handlers never interleave frames.

Frame kinds worker → primary: ``predict`` (hot path: model, deadline
header, trace wire doc; payload = columnar buffer or raw JSON body),
``http`` (generic proxy: method/url/headers; payload = body), ``spans``
(the worker's sampled span docs for a finished trace — merged via
``tracing.ingest`` so ``GET /trace/{id}`` shows one trace across both
processes), ``ready`` (worker listener bound — the supervisor's startup
barrier). Primary → worker: ``probs`` (payload = float32 probability
matrix; the worker formats the JSON response), ``error`` (mapped status/
message/headers — backpressure 503s, deadline 504s, quarantine, drain),
``http_ok`` (proxied status/headers; payload = body bytes).
"""

from __future__ import annotations

import json
import socket
import struct
import threading
import traceback
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from learningorchestra_tpu.utils.structlog import get_logger

log = get_logger("serving.rowchannel")

#: Content type of the binary columnar predict body.
COLUMNAR_CONTENT_TYPE = "application/x-lo-columnar"

#: Columnar body header: magic, version, dtype code, flags, rows, cols.
_COLUMNAR_MAGIC = b"LOCB"
_COLUMNAR_HEADER = struct.Struct("<4sBBHII")
_DTYPE_F32 = 1

#: Frame length prefix: header bytes, payload bytes.
_FRAME_PREFIX = struct.Struct("<II")
#: Hard caps so a corrupt peer cannot make either side allocate wildly.
MAX_HEADER_BYTES = 1 << 20
MAX_PAYLOAD_BYTES = 256 << 20


class ChannelProtocolError(RuntimeError):
    """A malformed frame on the worker channel — the connection is torn
    down (a desynced length-prefixed stream cannot be resynced)."""


# -- binary columnar body codec ----------------------------------------------

def encode_columnar(X: np.ndarray) -> bytes:
    """Pack a 2-D float32 matrix as a columnar request body (client
    side, and the worker's re-encode of numeric JSON list rows)."""
    X = np.ascontiguousarray(np.asarray(X, dtype=np.float32))
    if X.ndim != 2:
        raise ValueError("columnar body requires a 2-D matrix")
    n, d = X.shape
    return _COLUMNAR_HEADER.pack(_COLUMNAR_MAGIC, 1, _DTYPE_F32, 0, n, d) \
        + X.tobytes()


def decode_columnar(body: bytes) -> np.ndarray:
    """Binary columnar body → float32 design matrix, zero row decode.

    Raises ``ValueError`` on any malformation — the serving layer maps
    it to the same 406 a malformed JSON row gets, never a 500.
    """
    if len(body) < _COLUMNAR_HEADER.size:
        raise ValueError(
            f"malformed columnar body: {len(body)} bytes is shorter than "
            f"the {_COLUMNAR_HEADER.size}-byte header")
    magic, version, dtype, _flags, n, d = _COLUMNAR_HEADER.unpack_from(body)
    if magic != _COLUMNAR_MAGIC or version != 1:
        raise ValueError(
            "malformed columnar body: bad magic/version (want "
            f"{_COLUMNAR_MAGIC!r} v1, got {magic!r} v{version})")
    if dtype != _DTYPE_F32:
        raise ValueError(
            f"malformed columnar body: unsupported dtype code {dtype} "
            "(only float32=1 is defined)")
    want = _COLUMNAR_HEADER.size + 4 * n * d
    if n <= 0 or d <= 0 or len(body) != want:
        raise ValueError(
            f"malformed columnar body: header says {n}x{d} float32 "
            f"({want} bytes total) but body is {len(body)} bytes")
    # frombuffer is the zero-copy step: the socket's bytes become the
    # design matrix directly (read-only, which every downstream consumer
    # honors — padding into the AOT bucket copies anyway).
    return np.frombuffer(body, dtype=np.float32,
                         offset=_COLUMNAR_HEADER.size).reshape(n, d)


# -- frame codec ---------------------------------------------------------------

def pack_frame(header: Dict[str, Any], payload: bytes = b"") -> bytes:
    hdr = json.dumps(header, separators=(",", ":")).encode()
    return _FRAME_PREFIX.pack(len(hdr), len(payload)) + hdr + payload


def recv_exact(sock: socket.socket, n: int) -> bytes:
    """Blocking read of exactly ``n`` bytes; b"" on clean EOF at a frame
    boundary, ChannelProtocolError on EOF mid-frame."""
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            if buf:
                raise ChannelProtocolError("EOF mid-frame")
            return b""
        buf += chunk
    return bytes(buf)


def recv_frame(sock: socket.socket
               ) -> Optional[Tuple[Dict[str, Any], bytes]]:
    """Blocking frame read (primary side); None on clean EOF."""
    prefix = recv_exact(sock, _FRAME_PREFIX.size)
    if not prefix:
        return None
    hlen, plen = _FRAME_PREFIX.unpack(prefix)
    if hlen > MAX_HEADER_BYTES or plen > MAX_PAYLOAD_BYTES:
        raise ChannelProtocolError(
            f"oversized frame: header {hlen}B payload {plen}B")
    hdr_bytes = recv_exact(sock, hlen)
    if len(hdr_bytes) != hlen:
        raise ChannelProtocolError("EOF mid-frame")
    payload = recv_exact(sock, plen) if plen else b""
    if len(payload) != plen:
        raise ChannelProtocolError("EOF mid-frame")
    try:
        header = json.loads(hdr_bytes)
    except json.JSONDecodeError as e:
        raise ChannelProtocolError(f"bad frame header: {e}") from None
    if not isinstance(header, dict) or "kind" not in header:
        raise ChannelProtocolError("frame header missing 'kind'")
    return header, payload


# -- primary-side channel server ----------------------------------------------

class RowChannelServer:
    """The device-owning process's end of the row channel.

    ``handler(header, payload) -> (header, payload) | None`` runs on the
    bounded pool; a None return means no reply (fire-and-forget frames:
    ``spans``, ``ready``). Unexpected handler exceptions answer a
    generic ``error`` frame so a worker is never left holding a pending
    request forever.
    """

    def __init__(self, handler: Callable[[Dict[str, Any], bytes],
                                         Optional[Tuple[Dict[str, Any],
                                                        bytes]]],
                 host: str = "127.0.0.1", threads: int = 16,
                 on_ready: Optional[Callable[[int], None]] = None):
        self._handler = handler
        self._on_ready = on_ready
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock.bind((host, 0))
        self._sock.listen(64)
        self.host, self.port = self._sock.getsockname()[:2]
        self._pool = ThreadPoolExecutor(
            max_workers=max(1, int(threads)),
            thread_name_prefix="lo-rowchan")
        self._lock = threading.Lock()
        self._conns: Dict[int, Tuple[socket.socket, threading.Lock]] = {}
        self._next_conn = 0
        self._stopped = threading.Event()
        self.frames = 0
        self.replies = 0
        self.protocol_errors = 0
        # thread-lifecycle: owner=RowChannelServer; exits when stop()
        # closes the listen socket (accept raises OSError) and sets
        # _stopped; daemon so a leaked server cannot hang interpreter
        # exit.
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="lo-rowchan-accept")
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                conn, _addr = self._sock.accept()
            except OSError:
                return                      # stop() closed the listener
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                cid = self._next_conn = self._next_conn + 1
                self._conns[cid] = (conn, threading.Lock())
            # thread-lifecycle: owner=RowChannelServer; one reader per
            # worker connection, exits on peer EOF / protocol error /
            # stop()'s socket close; daemon for the same leak bound as
            # the accept thread.
            threading.Thread(target=self._reader_loop, args=(cid, conn),
                             daemon=True,
                             name=f"lo-rowchan-reader-{cid}").start()

    def _reader_loop(self, cid: int, conn: socket.socket) -> None:
        try:
            while True:
                frame = recv_frame(conn)
                if frame is None:
                    return
                with self._lock:
                    self.frames += 1
                self._pool.submit(self._handle_one, cid, *frame)
        except ChannelProtocolError as e:
            with self._lock:
                self.protocol_errors += 1
            log.error("row-channel conn %d protocol error: %s", cid, e)
        except OSError:
            return                          # torn down under us
        finally:
            self._drop_conn(cid)

    def _drop_conn(self, cid: int) -> None:
        with self._lock:
            ent = self._conns.pop(cid, None)
        if ent is not None:
            try:
                ent[0].close()
            except OSError:
                pass

    def _handle_one(self, cid: int, header: Dict[str, Any],
                    payload: bytes) -> None:
        if header.get("kind") == "ready":
            if self._on_ready is not None:
                try:
                    self._on_ready(int(header.get("index", -1)))
                except Exception:  # noqa: BLE001 — callback best-effort
                    traceback.print_exc()
            return
        try:
            reply = self._handler(header, payload)
        except Exception as e:  # noqa: BLE001 — worker must get an answer
            traceback.print_exc()
            reply = ({"kind": "error", "id": header.get("id"),
                      "status": 500,
                      "message": f"internal error: {e}"}, b"")
        if reply is None:
            return
        self.send(cid, reply[0], reply[1])

    def send(self, cid: int, header: Dict[str, Any],
             payload: bytes = b"") -> bool:
        """Write one frame to worker connection ``cid`` (per-connection
        write lock — concurrent pool handlers never interleave bytes).
        False when the worker is gone: its HTTP client sees the reset
        and the stock retry path takes over — nothing to do here."""
        with self._lock:
            ent = self._conns.get(cid)
        if ent is None:
            return False
        conn, wlock = ent
        data = pack_frame(header, payload)
        try:
            with wlock:
                conn.sendall(data)
            with self._lock:
                self.replies += 1
            return True
        except OSError:
            self._drop_conn(cid)
            return False

    def connections(self) -> int:
        with self._lock:
            return len(self._conns)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"connections": len(self._conns),
                    "frames_total": self.frames,
                    "replies_total": self.replies,
                    "protocol_errors_total": self.protocol_errors}

    def stop(self) -> None:
        self._stopped.set()
        # shutdown() BEFORE close(): closing an fd does NOT wake a
        # thread blocked in accept()/recv() on it (the fd stays
        # referenced) — without the shutdown, the accept thread sits
        # out the join timeout below and process exit stalls ~5 s
        # (observed live via the SIGTERM drain path).
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for conn, _lock in conns:
            for fn in (lambda: conn.shutdown(socket.SHUT_RDWR),
                       conn.close):
                try:
                    fn()
                except OSError:
                    pass
        self._pool.shutdown(wait=False)
        self._accept_thread.join(timeout=5.0)
