"""``python -m learningorchestra_tpu.serving`` — run the service.

Replaces the reference's per-service Flask ``app.run`` entrypoints + Docker
Swarm stack (reference run.sh, docker-compose.yml). Multi-host TPU pods run
this same module on every host; ``parallel.distributed.initialize`` joins
them into one mesh (env: LO_TPU_COORDINATOR / NUM_PROCESSES / PROCESS_ID).
"""

import argparse

from learningorchestra_tpu.config import settings
from learningorchestra_tpu.parallel import distributed
from learningorchestra_tpu.serving.app import App
from learningorchestra_tpu.utils import structlog

log = structlog.get_logger("serving.main")


def main() -> None:
    structlog.configure()
    parser = argparse.ArgumentParser(description="learningorchestra_tpu server")
    parser.add_argument("--host", default=settings.host)
    parser.add_argument("--port", type=int, default=settings.port)
    parser.add_argument("--store-root", default=settings.store_root)
    parser.add_argument("--no-recover", action="store_true",
                        help="skip loading persisted datasets at startup")
    args = parser.parse_args()

    settings.host = args.host
    settings.port = args.port
    settings.store_root = args.store_root

    distributed.initialize()
    import jax

    if jax.process_count() > 1 and jax.process_index() != 0:
        # Pod topology: process 0 owns the catalog and the REST surface;
        # every other process runs the SPMD worker loop, executing the
        # same mesh computations process 0 dispatches (parallel/spmd.py).
        # The store points at the shared store_root — the data plane the
        # reference's Spark executors got from Mongo.
        from learningorchestra_tpu.catalog.store import DatasetStore
        from learningorchestra_tpu.parallel import spmd
        from learningorchestra_tpu.parallel.mesh import MeshRuntime

        log.info("learningorchestra_tpu worker %d/%d (devices: %s, "
                 "mesh epoch %d)", jax.process_index(),
                 jax.process_count(),
                 distributed.process_info()["devices"],
                 spmd.mesh_epoch())
        reason = spmd.worker_loop(DatasetStore(settings),
                                  MeshRuntime(settings))
        if reason != "shutdown":
            # Controller lost or this worker's epoch went stale: this
            # incarnation cannot continue, but the POD should — exit
            # with the restartable code so the host's supervisor
            # (supervisor.py) restarts the process into the pod's next
            # incarnation instead of counting a local failure.
            from learningorchestra_tpu.supervisor import RESTARTABLE_EXIT

            raise SystemExit(RESTARTABLE_EXIT)
        return

    from learningorchestra_tpu.parallel import spmd

    spmd.ensure_channel()  # workers connect at boot; listener must exist
    app = App(settings, recover=not args.no_recover)
    log.info("learningorchestra_tpu serving on %s:%d (devices: %s)",
             args.host, args.port, distributed.process_info()["devices"])
    try:
        app.serve()
    finally:
        spmd.shutdown_workers()


if __name__ == "__main__":
    main()
