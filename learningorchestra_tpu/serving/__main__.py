"""``python -m learningorchestra_tpu.serving`` — run the service.

Replaces the reference's per-service Flask ``app.run`` entrypoints + Docker
Swarm stack (reference run.sh, docker-compose.yml). Multi-host TPU pods run
this same module on every host; ``parallel.distributed.initialize`` joins
them into one mesh (env: LO_TPU_COORDINATOR / NUM_PROCESSES / PROCESS_ID).
"""

import argparse

from learningorchestra_tpu.config import settings
from learningorchestra_tpu.parallel import distributed
from learningorchestra_tpu.serving.app import App


def main() -> None:
    parser = argparse.ArgumentParser(description="learningorchestra_tpu server")
    parser.add_argument("--host", default=settings.host)
    parser.add_argument("--port", type=int, default=settings.port)
    parser.add_argument("--store-root", default=settings.store_root)
    parser.add_argument("--no-recover", action="store_true",
                        help="skip loading persisted datasets at startup")
    args = parser.parse_args()

    settings.host = args.host
    settings.port = args.port
    settings.store_root = args.store_root

    distributed.initialize()
    app = App(settings, recover=not args.no_recover)
    print(f"learningorchestra_tpu serving on {args.host}:{args.port} "
          f"(devices: {distributed.process_info()['devices']})", flush=True)
    app.serve()


if __name__ == "__main__":
    main()
