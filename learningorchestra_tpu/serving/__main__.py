"""``python -m learningorchestra_tpu.serving`` — run the service.

Replaces the reference's per-service Flask ``app.run`` entrypoints + Docker
Swarm stack (reference run.sh, docker-compose.yml). Multi-host TPU pods run
this same module on every host; ``parallel.distributed.initialize`` joins
them into one mesh (env: LO_TPU_COORDINATOR / NUM_PROCESSES / PROCESS_ID).
"""

import argparse
import os
import signal
import threading

from learningorchestra_tpu.config import settings
from learningorchestra_tpu.parallel import distributed
from learningorchestra_tpu.serving.app import App
from learningorchestra_tpu.utils import structlog

log = structlog.get_logger("serving.main")


def install_graceful_shutdown(app: App, server) -> threading.Event:
    """Wire SIGTERM/SIGINT to a graceful drain of ``app`` + ``server``:
    the signal gates off new work (503 + Retry-After + Connection:
    close), in-flight predicts and queued jobs finish within
    ``LO_TPU_DRAIN_TIMEOUT_S``, then the server stops and the returned
    event is set — a planned restart loses zero accepted requests.
    Exposed so the chaos drain test drives the EXACT production signal
    path through a child process (tests/drain_child.py)."""
    stopped = threading.Event()
    drain_started = threading.Event()

    def _graceful(signum, _frame):
        # Signal frame: do nothing blocking here. The drain itself —
        # waiting out in-flight predicts and queued jobs, then stopping
        # the server — runs on its own thread; SIGTERM/SIGINT land in
        # the main thread, which is parked on `stopped` by the caller.
        if drain_started.is_set():
            # Second signal while draining = the operator insists. The
            # drain is timeout-bounded but server.stop() is not — if it
            # wedged, nothing else would ever release the main thread,
            # leaving the process killable only by SIGKILL. Exit with
            # the conventional fatal-signal code so a supervisor reads
            # it as a kill, not a clean stop.
            log.error("second signal %d during drain: forcing exit",
                      signum)
            os._exit(128 + signum)
        drain_started.set()
        log.warning("signal %d received: graceful drain (up to %.0fs)",
                    signum, app.cfg.drain_timeout_s)

        def _drain():
            try:
                app.drain()
            finally:
                server.stop()
                stopped.set()

        # thread-lifecycle: owner=serving.__main__; exits after
        # drain+server.stop complete and sets `stopped`, which releases
        # the main thread to exit the process (daemon: a wedged stop
        # cannot outlive the interpreter).
        threading.Thread(target=_drain, name="lo-drain",
                         daemon=True).start()

    signal.signal(signal.SIGTERM, _graceful)
    signal.signal(signal.SIGINT, _graceful)
    return stopped


def main() -> None:
    structlog.configure()
    parser = argparse.ArgumentParser(description="learningorchestra_tpu server")
    parser.add_argument("--host", default=settings.host)
    parser.add_argument("--port", type=int, default=settings.port)
    parser.add_argument("--store-root", default=settings.store_root)
    parser.add_argument("--no-recover", action="store_true",
                        help="skip loading persisted datasets at startup")
    args = parser.parse_args()

    settings.host = args.host
    settings.port = args.port
    settings.store_root = args.store_root

    distributed.initialize()
    import jax

    if jax.process_count() > 1 and jax.process_index() != 0:
        # Pod topology: process 0 owns the catalog and the REST surface;
        # every other process runs the SPMD worker loop, executing the
        # same mesh computations process 0 dispatches (parallel/spmd.py).
        # The store points at the shared store_root — the data plane the
        # reference's Spark executors got from Mongo.
        from learningorchestra_tpu.catalog.store import DatasetStore
        from learningorchestra_tpu.parallel import spmd
        from learningorchestra_tpu.parallel.mesh import MeshRuntime

        log.info("learningorchestra_tpu worker %d/%d (devices: %s, "
                 "mesh epoch %d)", jax.process_index(),
                 jax.process_count(),
                 distributed.process_info()["devices"],
                 spmd.mesh_epoch())
        reason = spmd.worker_loop(DatasetStore(settings),
                                  MeshRuntime(settings))
        if reason != "shutdown":
            # Controller lost or this worker's epoch went stale: this
            # incarnation cannot continue, but the POD should — exit
            # with the restartable code so the host's supervisor
            # (supervisor.py) restarts the process into the pod's next
            # incarnation instead of counting a local failure.
            from learningorchestra_tpu.supervisor import RESTARTABLE_EXIT

            raise SystemExit(RESTARTABLE_EXIT)
        return

    from learningorchestra_tpu.parallel import spmd

    spmd.ensure_channel()  # workers connect at boot; listener must exist
    app = App(settings, recover=not args.no_recover)
    log.info("learningorchestra_tpu serving on %s:%d (devices: %s, "
             "http workers: %d)", args.host, args.port,
             distributed.process_info()["devices"],
             max(1, settings.http_workers))
    server = app.serve(background=True)
    stopped = install_graceful_shutdown(app, server)
    try:
        stopped.wait()
    finally:
        spmd.shutdown_workers()


if __name__ == "__main__":
    main()
