"""Continuous micro-batching for the online predict tier.

The request-handler + batcher-worker split: HTTP handler threads are thin
enqueue/await shims — parse rows, enqueue, block on an event — and ONE
dispatcher thread per model owns the device. The dispatcher coalesces
whatever is waiting (up to ``serve_max_batch`` rows, lingering
``serve_max_wait_ms`` for stragglers when the batch isn't full) into one
padded AOT dispatch (models/aot.py) and scatters the probability rows
back to the waiting requests. Per-request device dispatch drowns in
fixed overhead — the same reason Spark's scheduler batches task rounds
(PAPERS 1612.01437) and MLlib pipelines its fits (1505.06807); keeping
the device fed with coalesced batches is what turns a ~100 µs dispatch
tax per request into a ~100 µs tax per *batch*.

Backpressure: each model's queue is bounded (``serve_queue_depth`` rows).
A request that would overflow it raises :class:`QueueFull`, which the
serving layer maps to 503 + Retry-After — the contract the client SDK's
jittered backoff already honors (PR 2/PR 4), so overload degrades into
client-side pacing instead of collapse.

Instrumentation feeds the ``serving`` section of ``/metrics`` and the
status page: per-model and aggregate request/row/batch counts, rejected
and failed counts, mean batch occupancy (rows per dispatch — the
batching win, directly), live queue depth, a log-bucketed end-to-end
latency histogram (p50/p99 are estimated from its buckets — exact over
the model's whole life, and the same series Prometheus scrapes; the old
rolling-sample percentiles forgot everything past 2048 requests), and
QPS over the last ~30 s.

Tracing: each traced request's trace context rides its queue entry, so
the dispatcher can attribute — per request — ``queue.wait`` (enqueue →
taken), and link one ``batch.coalesce`` span per coalesced dispatch as
the parent of every co-batched request's ``dispatch.device`` span:
queue wait, device time, and scatter tail finally separate per request
instead of blurring into one p99.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from learningorchestra_tpu.config import Settings, settings as global_settings
from learningorchestra_tpu.models.aot import AotCache, design_from_rows
from learningorchestra_tpu.models.persistence import ModelRegistry
from learningorchestra_tpu.utils import profiling, tracing

#: Completion timestamps kept per model for the QPS window.
_QPS_SAMPLES = 2048
#: Seconds of request-completion history the QPS figure covers.
_QPS_WINDOW_S = 30.0


class QueueFull(Exception):
    """The model's predict queue is at capacity — answer 503 and tell the
    client when to come back."""

    def __init__(self, model: str, depth: int, retry_after_s: float = 1.0):
        super().__init__(
            f"predict queue full for model {model} ({depth} rows waiting); "
            "retry after backoff")
        self.retry_after_s = retry_after_s


class PredictTimeout(Exception):
    """A queued request outlived ``serve_timeout_s`` without a result."""


class BatcherStopped(Exception):
    """The model's dispatcher was torn down while this request raced it
    (DELETE of the model, or server shutdown). Transient from the
    client's view: mapped to 503 + Retry-After, and the retry gets the
    terminal answer — 404 if the model is gone, a fresh dispatcher if it
    was re-saved."""


class _Pending:
    """One enqueued request: its design rows, the AOT entry its design
    was built against, the submitting request's trace context (so the
    dispatcher thread can record spans INTO that request's trace), and
    the slot the dispatcher scatters the result (or error) into."""

    __slots__ = ("X", "entry", "ctx", "done", "probs", "error",
                 "t_enqueue", "t_taken")

    def __init__(self, X: np.ndarray, entry: Any):
        self.X = X
        self.entry = entry
        self.ctx = tracing.current()
        self.done = threading.Event()
        self.probs: Optional[np.ndarray] = None
        self.error: Optional[Exception] = None
        self.t_enqueue = time.monotonic()
        self.t_taken: Optional[float] = None


class _Stats:
    """Lock-protected counters + latency histogram for one model.

    Latency lives in log-bucketed histograms (the shared
    ``profiling.BUCKETS_S`` ladder): a LIFETIME histogram — the exact
    cumulative series Prometheus scrapes (scrapers window it themselves
    with ``rate()``) — plus a two-epoch rotating window (epochs of
    ``_QPS_WINDOW_S``) that the JSON view's ``p50_ms``/``p99_ms``
    estimate from, so a latency regression on a long-lived server moves
    the operator-facing percentiles within seconds instead of drowning
    in millions of historical observations. QPS keeps a timestamp ring
    (a rate needs exact recency)."""

    def __init__(self):
        self.requests = 0
        self.rows = 0
        self.batches = 0
        self.batched_rows = 0
        self.rejected = 0
        self.timeouts = 0
        self.errors = 0
        self.lat_buckets = profiling.new_histogram()
        self.lat_sum_s = 0.0
        #: Two-epoch rotating window for recency-sensitive percentiles:
        #: p50/p99 read prev+current, covering the last 1-2 epochs.
        self._lat_recent = profiling.new_histogram()
        self._lat_prev = profiling.new_histogram()
        self._rotated_at = time.monotonic()
        #: Completion monotonic timestamps ring (QPS only).
        self.completions: collections.deque = collections.deque(
            maxlen=_QPS_SAMPLES)

    def _maybe_rotate(self, now: float) -> None:
        gap = now - self._rotated_at
        if gap > 2 * _QPS_WINDOW_S:
            # Idle longer than both epochs: everything in the window is
            # stale — clear it rather than promoting a minutes-old epoch
            # into "recent" (percentiles then fall back to the lifetime
            # shape until fresh traffic refills the window).
            self._lat_prev = profiling.new_histogram()
            self._lat_recent = profiling.new_histogram()
            self._rotated_at = now
        elif gap > _QPS_WINDOW_S:
            self._lat_prev = self._lat_recent
            self._lat_recent = profiling.new_histogram()
            self._rotated_at = now

    def observe(self, latency_s: float) -> None:
        """Record one completed request's latency (caller holds the
        stats lock)."""
        now = time.monotonic()
        self._maybe_rotate(now)
        profiling.observe(self.lat_buckets, latency_s)
        profiling.observe(self._lat_recent, latency_s)
        self.lat_sum_s += latency_s
        self.completions.append(now)

    def snapshot(self, queue_rows: int) -> Dict[str, Any]:
        now = time.monotonic()
        self._maybe_rotate(now)
        recent = [t for t in self.completions if now - t <= _QPS_WINDOW_S]
        # Divide by the full window once it has rolled over; before that
        # (young server) by the observed span, floored so one lone
        # sample can't read as thousands of QPS.
        span = (_QPS_WINDOW_S if len(recent) < len(self.completions)
                else max(now - recent[0], 1.0) if recent else None)
        qps = (len(recent) / span) if recent and span else 0.0
        # Recent-window percentiles (prev + current epoch); an idle
        # model falls back to its lifetime shape rather than reading
        # None the moment traffic pauses.
        window = [a + b for a, b in zip(self._lat_prev, self._lat_recent)]
        source = window if sum(window) else self.lat_buckets

        def pct(q: float) -> Optional[float]:
            est = profiling.quantile_from_buckets(source, q)
            return None if est is None else round(est * 1e3, 3)

        return {
            "requests": self.requests,
            "rows": self.rows,
            "batches": self.batches,
            "mean_batch_rows": (round(self.batched_rows / self.batches, 3)
                                if self.batches else 0.0),
            "rejected": self.rejected,
            "timeouts": self.timeouts,
            "errors": self.errors,
            "queue_rows": queue_rows,
            "qps": round(qps, 3),
            "p50_ms": pct(0.50),
            "p99_ms": pct(0.99),
            "latency": {"buckets": list(self.lat_buckets),
                        "sum_s": round(self.lat_sum_s, 6)},
        }


class ModelBatcher:
    """The per-model queue + the dispatcher thread that owns the device."""

    def __init__(self, name: str, cfg: Settings, stats: _Stats):
        self.name = name
        self.cfg = cfg
        self.stats = stats
        self._cond = threading.Condition()
        self._queue: collections.deque = collections.deque()
        self._queue_rows = 0
        self._stopped = False
        # thread-lifecycle: owner=ModelBatcher; exits when stop() sets
        # _stopped under the cond (joined there, 5s timeout); _loop's
        # per-group try/except scatters dispatch errors to requests, and
        # an escape above it is caught by the test harness's
        # threading.excepthook sanitizer (the PR 6 silent-death class).
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name=f"lo-predict-{name}")
        self._thread.start()

    # -- handler side --------------------------------------------------------

    def submit(self, X: np.ndarray, entry: Any) -> np.ndarray:
        """Enqueue one request's rows and block until its batch lands.
        ``entry`` is the AOT entry ``X`` was designed against — the
        dispatcher evaluates through it, never through a fresher one
        (a hot-swap between preprocessing and dispatch must not run
        old-state rows through new params). Raises QueueFull at
        capacity (→ 503 upstream) and re-raises any dispatch-side error
        on the submitting thread."""
        n = len(X)
        with self._cond:
            if self._stopped:
                raise BatcherStopped(
                    f"predict dispatcher for model {self.name} stopped")
            depth = int(self.cfg.serve_queue_depth)
            if self._queue_rows + n > depth:
                with _stats_lock:
                    self.stats.rejected += 1
                raise QueueFull(self.name, self._queue_rows)
            pending = _Pending(X, entry)
            self._queue.append(pending)
            self._queue_rows += n
            self._cond.notify_all()
        if not pending.done.wait(float(self.cfg.serve_timeout_s)):
            # Withdraw the dead request: if it is still queued, the
            # device must not burn a dispatch computing rows nobody
            # will read (the 503'd client is already re-sending them).
            # Already-taken requests compute wastefully once — bounded.
            with self._cond:
                try:
                    self._queue.remove(pending)
                    self._queue_rows -= n
                except ValueError:
                    pass                    # dispatcher already took it
            with _stats_lock:
                self.stats.timeouts += 1
            raise PredictTimeout(
                f"predict timed out after {self.cfg.serve_timeout_s}s "
                f"queued on model {self.name}")
        if pending.error is not None:
            raise pending.error
        lat = time.monotonic() - pending.t_enqueue
        with _stats_lock:
            self.stats.requests += 1
            self.stats.rows += n
            self.stats.observe(lat)
        return pending.probs

    def queue_rows(self) -> int:
        with self._cond:
            return self._queue_rows

    def thread_alive(self) -> bool:
        """Liveness probe for the health rollup: True while the
        dispatcher thread runs OR it was stopped deliberately — only a
        dead-but-not-stopped thread (the PR 6 silent-death class the
        thread sanitizer hunts) reads as unhealthy."""
        with self._cond:
            if self._stopped:
                return True
        return self._thread.is_alive()

    # -- worker side ---------------------------------------------------------

    def _take_batch(self) -> List[_Pending]:
        """Pop up to ``serve_max_batch`` rows' worth of waiting requests,
        lingering up to ``serve_max_wait_ms`` for a fuller batch. Whole
        requests only — a single request never splits across dispatches,
        so scatter-back is a simple offset walk."""
        max_rows = max(1, int(self.cfg.serve_max_batch))
        with self._cond:
            # Plain wait: submit() and stop() both notify under the
            # cond, so an idle dispatcher sleeps silently instead of
            # polling.
            while not self._queue and not self._stopped:
                self._cond.wait()
            if self._stopped and not self._queue:
                return []
            deadline = (time.monotonic()
                        + float(self.cfg.serve_max_wait_ms) / 1e3)
            # _queue_rows is maintained by submit/_take_batch/timeout
            # withdrawal under this cond — O(1) vs re-walking the deque
            # on every linger wakeup.
            while self._queue_rows < max_rows and not self._stopped:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
            batch: List[_Pending] = []
            rows = 0
            while self._queue and rows + len(self._queue[0].X) <= max_rows:
                p = self._queue.popleft()
                rows += len(p.X)
                batch.append(p)
            if not batch and self._queue:
                # Head request alone exceeds max_batch (only possible if
                # someone shrank serve_max_batch at runtime): dispatch it
                # solo; aot.predict chunks it across max-bucket calls.
                batch.append(self._queue.popleft())
                rows = len(batch[0].X)
            self._queue_rows -= rows
            t_taken = time.monotonic()
            for p in batch:
                p.t_taken = t_taken
            return batch

    def _loop(self) -> None:
        while True:
            batch = self._take_batch()
            if not batch:
                # Empty means stopped-and-drained OR a timeout
                # withdrawal emptied the queue during the linger wait —
                # only the former ends the thread (a dead dispatcher
                # with _stopped False would black-hole the model).
                if self._stopped:
                    return
                continue
            # Per-request queue-wait attribution: enqueue → taken by the
            # dispatcher, recorded into EACH request's own trace (the
            # p99 blur the rolling-sample window could never decompose).
            for p in batch:
                if p.ctx is not None and p.ctx.sampled:
                    tracing.record_span(
                        "queue.wait", (p.t_taken or p.t_enqueue)
                        - p.t_enqueue, ctx=p.ctx,
                        attrs={"model": self.name, "rows": len(p.X)})
            # Group by the entry captured at enqueue: requests that
            # straddle a hot-swap evaluate through the version their
            # design matrix was built for (mixing would run old-state
            # rows through new params — silently wrong numbers, or a
            # width mismatch erroring innocent co-batched requests).
            # One dispatch per group; mixed-version batches only occur
            # in the swap instant itself.
            groups: Dict[int, List[_Pending]] = {}
            for p in batch:
                groups.setdefault(id(p.entry), []).append(p)
            for grp in groups.values():
                try:
                    t0 = time.monotonic()
                    X = (grp[0].X if len(grp) == 1
                         else np.concatenate([p.X for p in grp], axis=0))
                    probs = grp[0].entry.predict(X)
                    t_device = time.monotonic() - t0
                    off = 0
                    for p in grp:
                        p.probs = probs[off:off + len(p.X)]
                        off += len(p.X)
                    with _stats_lock:
                        self.stats.batches += 1
                        self.stats.batched_rows += off
                    # One batch.coalesce span per coalesced dispatch
                    # (recorded into the first traced request's trace),
                    # linked as PARENT of every co-batched request's
                    # dispatch.device span: the trace shows N requests
                    # sharing one device program, and scatter time is
                    # the coalesce−device gap.
                    coalesce = time.monotonic() - t0
                    bsid = None
                    for p in grp:
                        if p.ctx is not None and p.ctx.sampled:
                            bsid = tracing.record_span(
                                "batch.coalesce", coalesce, ctx=p.ctx,
                                attrs={"model": self.name,
                                       "requests": len(grp), "rows": off})
                            break
                    for p in grp:
                        if p.ctx is not None and p.ctx.sampled:
                            tracing.record_span(
                                "dispatch.device", t_device, ctx=p.ctx,
                                parent_id=bsid,
                                attrs={"co_batched": len(grp),
                                       "batch_rows": off})
                except Exception as exc:  # noqa: BLE001 — scattered per req
                    with _stats_lock:
                        self.stats.errors += len(grp)
                    for p in grp:
                        p.error = exc
                finally:
                    for p in grp:
                        p.done.set()

    def stop(self) -> None:
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
        self._thread.join(timeout=5.0)
        # Fail anything still queued so no handler thread waits out its
        # full timeout against a dead worker.
        with self._cond:
            leftovers = list(self._queue)
            self._queue.clear()
            self._queue_rows = 0
        for p in leftovers:
            p.error = BatcherStopped(
                f"predict dispatcher for model {self.name} stopped")
            p.done.set()


#: One lock for all stats mutation — counters are tiny and contention is
#: request-rate, not row-rate.
_stats_lock = threading.Lock()


class PredictBatcher:
    """The serving facade: per-model batchers created lazily, shared AOT
    cache, aggregate metrics. Held by the App; handlers call
    :meth:`predict` and everything else is internal."""

    def __init__(self, registry: ModelRegistry,
                 cfg: Optional[Settings] = None):
        self.cfg = cfg or global_settings
        self.aot = AotCache(registry, self.cfg)
        self._lock = threading.Lock()
        self._batchers: Dict[str, ModelBatcher] = {}
        self._stats: Dict[str, _Stats] = {}
        self._stopped = False

    def _batcher(self, name: str) -> ModelBatcher:
        with self._lock:
            if self._stopped:
                # A handler racing Server.stop() must not resurrect a
                # dispatcher thread nothing will ever stop again.
                raise BatcherStopped(
                    f"predict tier stopped; model {name} not served")
            b = self._batchers.get(name)
            if b is None:
                # Re-validate before spawning a dispatcher: a request
                # racing DELETE can reach here after invalidate()
                # already tore the batcher down — without this check it
                # would resurrect a dispatcher thread for a model that
                # can never serve again.
                self.aot.registry.version(name)   # ModelNotFound → 404
                stats = self._stats.setdefault(name, _Stats())
                b = ModelBatcher(name, self.cfg, stats)
                self._batchers[name] = b
            return b

    def predict(self, name: str, rows: Sequence[Any]) -> Dict[str, Any]:
        """The whole handler shim: rows → design matrix (host-side, on
        the handler thread so feature prep overlaps other models'
        device work) → enqueue/await → JSON-able result."""
        if int(self.cfg.serve_queue_depth) <= 0:
            # Existence check BEFORE creating a stats slot: _stats
            # entries are permanent (invalidate() keeps them for
            # /metrics continuity), so minting one per client-supplied
            # name would let a scanner grow this dict — and /metrics —
            # without bound. Unknown models 404 here like everywhere
            # else; real ones count the rejection below.
            self.aot.registry.version(name)   # ModelNotFound → 404
            # Count the rejection: a tier bouncing 100% of traffic must
            # show it on /metrics, not read as zero rejections.
            with self._lock:
                stats = self._stats.setdefault(name, _Stats())
            with _stats_lock:
                stats.rejected += 1
            raise QueueFull(name, 0)
        # Load/compile (and 404/406) BEFORE enqueueing: a bad model name
        # must not cost a queue slot, and first-touch compile happens on
        # the handler thread instead of stalling the dispatch loop.
        entry = self.aot.entry(name)
        # Shape-check the body before len()/preprocessing: {"rows":
        # null} or a scalar must 406 like every other malformed input,
        # not 500 on a TypeError.
        if not isinstance(rows, (list, tuple)):
            raise ValueError(
                "rows must be a non-empty JSON array of feature rows")
        # Cap check BEFORE preprocessing: the client's cap-discovery
        # probe deliberately oversends and expects a cheap 406 — don't
        # vocab-encode/fillna 256 rows just to throw them away. The cap
        # folds in serve_queue_depth: a request bigger than the whole
        # queue can NEVER be accepted, so it must get this terminal 406
        # (whose cap the client re-splits to) rather than burn its
        # retry budget on guaranteed QueueFull 503s.
        cap = min(int(self.cfg.serve_max_batch),
                  int(self.cfg.serve_queue_depth))
        if len(rows) > cap:
            raise ValueError(
                f"request carries {len(rows)} rows; per-request cap is "
                f"serve_max_batch={cap} — split client-side "
                "(Model.predict_online does)")
        t0 = time.monotonic()
        X = design_from_rows(rows, entry.preprocess)
        # Host-side feature prep on the handler thread, attributed per
        # request — the queue.wait / dispatch.device spans downstream
        # come from the dispatcher (ModelBatcher._loop).
        tracing.record_span("design.build", time.monotonic() - t0,
                            attrs={"model": name, "rows": len(rows)})
        probs = self._batcher(name).submit(X, entry)
        # .tolist() (C-speed) — this runs per request on the hot path.
        return {
            "model": name,
            "kind": entry.kind,
            "predictions": np.argmax(probs, axis=1).tolist(),
            # tolist() on float32 already widens to exact Python floats
            # — an astype(float64) first would copy for identical JSON.
            "probabilities": probs.tolist(),
        }

    def invalidate(self, name: Optional[str] = None) -> None:
        """Drop compiled programs (and the dispatcher thread) for a
        deleted/re-saved model; stats survive so /metrics history does
        not reset."""
        self.aot.invalidate(name)
        with self._lock:
            if name is None:
                doomed = list(self._batchers.values())
                self._batchers.clear()
            else:
                b = self._batchers.pop(name, None)
                doomed = [b] if b is not None else []
        for b in doomed:
            b.stop()

    def health(self) -> Dict[str, Any]:
        """Dispatcher-thread liveness for ``GET /healthz``: a model whose
        dispatcher thread died without being stopped would black-hole
        its requests — the silent failure mode the deep health rollup
        exists to surface."""
        with self._lock:
            batchers = dict(self._batchers)
        dead = sorted(n for n, b in batchers.items()
                      if not b.thread_alive())
        return {"ok": not dead, "dispatchers": len(batchers),
                "dead": dead}

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            names = list(self._stats)
            queue = {n: (self._batchers[n].queue_rows()
                         if n in self._batchers else 0) for n in names}
        with _stats_lock:
            models = {n: self._stats[n].snapshot(queue[n]) for n in names}
        agg: Dict[str, Any] = {
            "requests": sum(m["requests"] for m in models.values()),
            "rows": sum(m["rows"] for m in models.values()),
            "batches": sum(m["batches"] for m in models.values()),
            "rejected": sum(m["rejected"] for m in models.values()),
            "timeouts": sum(m["timeouts"] for m in models.values()),
            "errors": sum(m["errors"] for m in models.values()),
            "queue_rows": sum(m["queue_rows"] for m in models.values()),
            "qps": round(sum(m["qps"] for m in models.values()), 3),
        }
        batches = agg["batches"]
        agg["mean_batch_rows"] = (
            round(sum(m["mean_batch_rows"] * m["batches"]
                      for m in models.values()) / batches, 3)
            if batches else 0.0)
        return {**agg, "aot": self.aot.snapshot(), "models": models}

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
            batchers = list(self._batchers.values())
            self._batchers.clear()
        for b in batchers:
            b.stop()
