"""Continuous micro-batching for the online predict tier.

The request-handler + batcher-worker split: HTTP handler threads are thin
enqueue/await shims — parse rows, enqueue, block on an event — and ONE
dispatcher thread per (model, replica) owns that replica's device
(``serve_replicas`` replicas per model; the default 1 is the classic
one-dispatcher-per-model tier). A cost-based router picks the replica
with the lowest predicted queue wait per request. The dispatcher coalesces
whatever is waiting (up to ``serve_max_batch`` rows, lingering
``serve_max_wait_ms`` for stragglers when the batch isn't full) into one
padded AOT dispatch (models/aot.py) and scatters the probability rows
back to the waiting requests. Per-request device dispatch drowns in
fixed overhead — the same reason Spark's scheduler batches task rounds
(PAPERS 1612.01437) and MLlib pipelines its fits (1505.06807); keeping
the device fed with coalesced batches is what turns a ~100 µs dispatch
tax per request into a ~100 µs tax per *batch*.

Backpressure: each model's queue is bounded (``serve_queue_depth`` rows).
A request that would overflow it raises :class:`QueueFull`, which the
serving layer maps to 503 + Retry-After — the contract the client SDK's
jittered backoff already honors (PR 2/PR 4), so overload degrades into
client-side pacing instead of collapse.

Instrumentation feeds the ``serving`` section of ``/metrics`` and the
status page: per-model and aggregate request/row/batch counts, rejected
and failed counts, mean batch occupancy (rows per dispatch — the
batching win, directly), live queue depth, a log-bucketed end-to-end
latency histogram (p50/p99 are estimated from its buckets — exact over
the model's whole life, and the same series Prometheus scrapes; the old
rolling-sample percentiles forgot everything past 2048 requests), and
QPS over the last ~30 s.

Tracing: each traced request's trace context rides its queue entry, so
the dispatcher can attribute — per request — ``queue.wait`` (enqueue →
taken), and link one ``batch.coalesce`` span per coalesced dispatch as
the parent of every co-batched request's ``dispatch.device`` span:
queue wait, device time, and scatter tail finally separate per request
instead of blurring into one p99.

Fault domain (PR 11):

- **End-to-end deadlines** — a request may carry a deadline budget
  (``X-Deadline-Ms`` → :meth:`PredictBatcher.predict`). Admission
  rejects up front when the predicted queue wait (queue depth × the
  recent per-row service rate, an EWMA the dispatcher maintains)
  already exceeds the remaining budget; the dispatcher discards
  requests that expired while queued BEFORE padding them into a batch
  (device time is never spent answering a caller that gave up); both
  map to a terminal 504 (:class:`DeadlineExceeded`), never a retryable
  503, and the expiry is recorded on the request's trace.
- **Dispatcher self-healing** — the per-model dispatcher thread runs
  under in-process supervision: an exception escaping the dispatch loop
  (the PR 6 silent-death class) restarts the loop under exponential
  backoff, re-queuing in-flight requests the device never saw and
  failing already-dispatched ones 503 (:class:`DispatcherCrashed` — the
  client retries). ``serve_quarantine_crashes`` consecutive crashes
  quarantine the model (:class:`ModelQuarantined`, terminal 503 naming
  the quarantine + the ``serving_quarantined`` alert) instead of
  crash-looping; DELETE or re-save lifts it.
- **Chaos seams** — ``serving.batcher.pre_dispatch`` fires after a
  batch is taken but before any device work (raise-mode = a dispatcher
  crash whose batch is safely re-queued), ``serving.batcher.
  mid_dispatch`` after the device computed but before scatter
  (raise-mode = a crash whose batch must fail 503: re-dispatching would
  double-spend device time).
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from learningorchestra_tpu.config import Settings, settings as global_settings
from learningorchestra_tpu.models.aot import AotCache, design_from_rows
from learningorchestra_tpu.models.persistence import ModelRegistry
from learningorchestra_tpu.utils import (
    failpoints, flightrec, profiling, tracing)
from learningorchestra_tpu.utils.structlog import get_logger

log = get_logger("serving.batcher")

#: Chaos seams for the predict dispatch path (docs/fault_tolerance.md §7).
FP_PRE_DISPATCH = failpoints.declare("serving.batcher.pre_dispatch")
FP_MID_DISPATCH = failpoints.declare("serving.batcher.mid_dispatch")

#: EWMA weight of the newest per-row service-rate sample — a few batches
#: of history, so the queue-wait prediction tracks load shifts within
#: seconds without one outlier dispatch whipsawing admission.
_RATE_ALPHA = 0.3
#: Supervised dispatcher restarts back off exponentially up to this cap
#: (seconds) — also bounds how long stop() can wait behind a backoff.
_RESTART_BACKOFF_CAP_S = 5.0
#: Retry-After hints computed from predicted queue wait clamp into this
#: range (seconds): at least 1 (the header is integral and 0 means
#: hammer-now), at most 60 (a confused rate estimate must not park
#: clients for an hour).
_RETRY_AFTER_MIN_S, _RETRY_AFTER_MAX_S = 1.0, 60.0

#: Completion timestamps kept per model for the QPS window.
_QPS_SAMPLES = 2048
#: Seconds of request-completion history the QPS figure covers.
_QPS_WINDOW_S = 30.0


class QueueFull(Exception):
    """The model's predict queue is at capacity — answer 503 and tell the
    client when to come back."""

    def __init__(self, model: str, depth: int, retry_after_s: float = 1.0):
        super().__init__(
            f"predict queue full for model {model} ({depth} rows waiting); "
            "retry after backoff")
        self.retry_after_s = retry_after_s


class PredictTimeout(Exception):
    """A queued request outlived ``serve_timeout_s`` without a result."""


class BatcherStopped(Exception):
    """The model's dispatcher was torn down while this request raced it
    (DELETE of the model, or server shutdown). Transient from the
    client's view: mapped to 503 + Retry-After, and the retry gets the
    terminal answer — 404 if the model is gone, a fresh dispatcher if it
    was re-saved."""


class DeadlineExceeded(Exception):
    """The request's end-to-end deadline budget cannot be (or was not)
    met — terminal: mapped to **504**, which the client never retries
    (re-sending work whose caller already gave up only deepens the
    overload). ``phase`` says where the budget died: ``admission``
    (predicted queue wait exceeded the remaining budget up front — the
    rows never even queued) or ``queue`` (it expired waiting — the rows
    were discarded before any device dispatch)."""

    def __init__(self, model: str, budget_ms: float, waited_ms: float,
                 phase: str, predicted_wait_ms: Optional[float] = None):
        detail = (f"; predicted queue wait {predicted_wait_ms:.0f}ms"
                  if predicted_wait_ms is not None else "")
        super().__init__(
            f"deadline exceeded for model {model}: budget {budget_ms:.0f}ms"
            f", waited {waited_ms:.0f}ms at {phase}{detail}")
        self.model = model
        self.budget_ms = budget_ms
        self.waited_ms = waited_ms
        self.phase = phase


class DispatcherCrashed(Exception):
    """This request's batch was in flight when the dispatcher thread
    crashed AFTER device dispatch — its results are lost and re-running
    them would double-spend device time, so it fails here. Transient:
    mapped to 503 + Retry-After; the supervised restart is already
    bringing the dispatcher back for the retry."""


class ModelQuarantined(Exception):
    """The model's dispatcher crashed ``serve_quarantine_crashes``
    consecutive times and the model is quarantined: predicts answer this
    terminal 503 naming the quarantine instead of feeding a crash loop.
    DELETE or re-save (anything that invalidates the batcher) lifts it."""


class _Pending:
    """One enqueued request: its design rows, the AOT entry its design
    was built against, the submitting request's trace context (so the
    dispatcher thread can record spans INTO that request's trace), its
    optional deadline, and the slot the dispatcher scatters the result
    (or error) into. ``dispatched`` flips just before the device runs
    its batch — the supervision's re-queue-or-fail decision on a crash."""

    __slots__ = ("X", "entry", "ctx", "done", "probs", "error",
                 "t_enqueue", "t_taken", "deadline", "budget_ms",
                 "dispatched")

    def __init__(self, X: np.ndarray, entry: Any,
                 deadline: Optional[float] = None,
                 budget_ms: Optional[float] = None):
        self.X = X
        self.entry = entry
        self.ctx = tracing.current()
        self.done = threading.Event()
        self.probs: Optional[np.ndarray] = None
        self.error: Optional[Exception] = None
        self.t_enqueue = time.monotonic()
        self.t_taken: Optional[float] = None
        #: Absolute monotonic instant the caller's budget runs out, or
        #: None for no deadline.
        self.deadline = deadline
        self.budget_ms = budget_ms
        self.dispatched = False


class _Stats:
    """Lock-protected counters + latency histogram for one model.

    Latency lives in log-bucketed histograms (the shared
    ``profiling.BUCKETS_S`` ladder): a LIFETIME histogram — the exact
    cumulative series Prometheus scrapes (scrapers window it themselves
    with ``rate()``) — plus a two-epoch rotating window (epochs of
    ``_QPS_WINDOW_S``) that the JSON view's ``p50_ms``/``p99_ms``
    estimate from, so a latency regression on a long-lived server moves
    the operator-facing percentiles within seconds instead of drowning
    in millions of historical observations. QPS keeps a timestamp ring
    (a rate needs exact recency)."""

    def __init__(self):
        self.requests = 0
        self.rows = 0
        self.batches = 0
        self.batched_rows = 0
        self.rejected = 0
        self.timeouts = 0
        self.errors = 0
        self.deadline_exceeded = 0
        self.dispatcher_restarts = 0
        self.quarantined = 0
        #: EWMA of device seconds per row over recent dispatches — the
        #: service rate behind predicted queue wait (deadline admission
        #: and computed Retry-After hints). 0.0 until the first dispatch
        #: (a cold model admits everything: no evidence, no rejection).
        self.service_s_per_row = 0.0
        self.lat_buckets = profiling.new_histogram()
        self.lat_sum_s = 0.0
        #: Two-epoch rotating window for recency-sensitive percentiles:
        #: p50/p99 read prev+current, covering the last 1-2 epochs.
        self._lat_recent = profiling.new_histogram()
        self._lat_prev = profiling.new_histogram()
        self._rotated_at = time.monotonic()
        #: Completion monotonic timestamps ring (QPS only).
        self.completions: collections.deque = collections.deque(
            maxlen=_QPS_SAMPLES)

    def _maybe_rotate(self, now: float) -> None:
        gap = now - self._rotated_at
        if gap > 2 * _QPS_WINDOW_S:
            # Idle longer than both epochs: everything in the window is
            # stale — clear it rather than promoting a minutes-old epoch
            # into "recent" (percentiles then fall back to the lifetime
            # shape until fresh traffic refills the window).
            self._lat_prev = profiling.new_histogram()
            self._lat_recent = profiling.new_histogram()
            self._rotated_at = now
        elif gap > _QPS_WINDOW_S:
            self._lat_prev = self._lat_recent
            self._lat_recent = profiling.new_histogram()
            self._rotated_at = now

    def observe(self, latency_s: float) -> None:
        """Record one completed request's latency (caller holds the
        stats lock)."""
        now = time.monotonic()
        self._maybe_rotate(now)
        profiling.observe(self.lat_buckets, latency_s)
        profiling.observe(self._lat_recent, latency_s)
        self.lat_sum_s += latency_s
        self.completions.append(now)

    def observe_dispatch(self, rows: int, device_s: float) -> None:
        """Fold one dispatch's per-row device time into the service-rate
        EWMA (caller holds the stats lock)."""
        if rows <= 0:
            return
        sample = max(0.0, device_s) / rows
        self.service_s_per_row = (
            sample if self.service_s_per_row <= 0.0
            else (1 - _RATE_ALPHA) * self.service_s_per_row
            + _RATE_ALPHA * sample)

    def predicted_wait_s(self, queue_rows: int) -> float:
        """Expected seconds until ``queue_rows`` currently-queued rows
        have been served — depth × the recent per-row service rate. 0.0
        before any dispatch established a rate."""
        return max(0, queue_rows) * self.service_s_per_row

    def snapshot(self, queue_rows: int) -> Dict[str, Any]:
        now = time.monotonic()
        self._maybe_rotate(now)
        recent = [t for t in self.completions if now - t <= _QPS_WINDOW_S]
        # Divide by the full window once it has rolled over; before that
        # (young server) by the observed span, floored so one lone
        # sample can't read as thousands of QPS.
        span = (_QPS_WINDOW_S if len(recent) < len(self.completions)
                else max(now - recent[0], 1.0) if recent else None)
        qps = (len(recent) / span) if recent and span else 0.0
        # Recent-window percentiles (prev + current epoch); an idle
        # model falls back to its lifetime shape rather than reading
        # None the moment traffic pauses.
        window = [a + b for a, b in zip(self._lat_prev, self._lat_recent)]
        source = window if sum(window) else self.lat_buckets

        def pct(q: float) -> Optional[float]:
            est = profiling.quantile_from_buckets(source, q)
            return None if est is None else round(est * 1e3, 3)

        return {
            "requests": self.requests,
            "rows": self.rows,
            "batches": self.batches,
            # Rows the DEVICE actually saw — the deadline tests pin that
            # expired rows never count here.
            "batched_rows": self.batched_rows,
            "mean_batch_rows": (round(self.batched_rows / self.batches, 3)
                                if self.batches else 0.0),
            "rejected": self.rejected,
            "timeouts": self.timeouts,
            "errors": self.errors,
            "deadline_exceeded": self.deadline_exceeded,
            "dispatcher_restarts": self.dispatcher_restarts,
            "quarantined": self.quarantined,
            "service_us_per_row": round(self.service_s_per_row * 1e6, 3),
            "queue_rows": queue_rows,
            "qps": round(qps, 3),
            "p50_ms": pct(0.50),
            "p99_ms": pct(0.99),
            "latency": {"buckets": list(self.lat_buckets),
                        "sum_s": round(self.lat_sum_s, 6)},
        }


class ModelBatcher:
    """The per-(model, replica) queue + the dispatcher thread that owns
    that replica's device. With ``serve_replicas`` = 1 (the default)
    there is exactly one of these per model — the pre-replication tier,
    byte-for-byte. ``stats`` is the REPLICA's own counter block: the
    service-rate EWMA behind admission control and routing is
    per-replica, so one slow device only slows its own queue's
    predictions."""

    def __init__(self, name: str, cfg: Settings, stats: _Stats,
                 replica: int = 0):
        self.name = name
        self.cfg = cfg
        self.stats = stats
        #: Which AOT replica (device index) this dispatcher dispatches
        #: to; 0 is the single-device topology.
        self.replica = int(replica)
        self._cond = threading.Condition()
        self._queue: collections.deque = collections.deque()
        self._queue_rows = 0
        self._stopped = False
        #: Set by stop(): interrupts a supervised-restart backoff sleep.
        self._stopping = threading.Event()
        #: Consecutive dispatcher crashes (reset by a clean batch);
        #: reaching serve_quarantine_crashes quarantines the model.
        self._crashes = 0
        #: Quarantine reason once terminal, else None.
        self._quarantined: Optional[str] = None
        #: The batch the dispatcher currently holds outside the queue —
        #: what supervision re-queues or fails after a crash. Touched
        #: only by the dispatcher thread (and by supervision after that
        #: same thread's loop died), so it needs no lock.
        self._inflight: List[_Pending] = []
        # thread-lifecycle: owner=ModelBatcher; exits when stop() sets
        # _stopped under the cond (joined there, bounded timeout) or on
        # quarantine. _run supervises _loop: an exception escaping the
        # dispatch loop (the PR 6 silent-death class) restarts it under
        # exponential backoff instead of dying silently; per-request
        # model errors are scattered by _loop's per-group try/except and
        # never reach supervision.
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=(f"lo-predict-{name}" if self.replica == 0
                  else f"lo-predict-{name}-r{self.replica}"))
        self._thread.start()

    # -- handler side --------------------------------------------------------

    def quarantined(self) -> Optional[str]:
        with self._cond:
            return self._quarantined

    def submit(self, X: np.ndarray, entry: Any,
               deadline: Optional[float] = None,
               budget_ms: Optional[float] = None) -> np.ndarray:
        """Enqueue one request's rows and block until its batch lands.
        ``entry`` is the AOT entry ``X`` was designed against — the
        dispatcher evaluates through it, never through a fresher one
        (a hot-swap between preprocessing and dispatch must not run
        old-state rows through new params). ``deadline`` is the absolute
        monotonic instant the caller's budget expires (None = none).
        Raises QueueFull at capacity (→ 503 upstream), DeadlineExceeded
        (→ terminal 504) when the budget is already unmeetable or runs
        out in queue, and re-raises any dispatch-side error on the
        submitting thread."""
        n = len(X)
        with self._cond:
            if self._quarantined:
                raise ModelQuarantined(self._quarantined)
            if self._stopped:
                raise BatcherStopped(
                    f"predict dispatcher for model {self.name} stopped")
            queue_rows = self._queue_rows
            if deadline is not None:
                # Admission control: if the rows already waiting are
                # predicted to outlast the remaining budget, spending a
                # queue slot (and later device time) on this request
                # only manufactures a guaranteed-dead answer.
                with _stats_lock:
                    wait_s = self.stats.predicted_wait_s(queue_rows)
                remaining = deadline - time.monotonic()
                if wait_s > remaining:
                    with _stats_lock:
                        self.stats.deadline_exceeded += 1
                    exc = DeadlineExceeded(
                        self.name, budget_ms or 0.0,
                        max(0.0, (budget_ms or 0.0) - remaining * 1e3),
                        "admission", predicted_wait_ms=wait_s * 1e3)
                    tracing.record_span(
                        "deadline.rejected", 0.0,
                        attrs={"model": self.name, "rows": n,
                               "budget_ms": budget_ms,
                               "predicted_wait_ms": round(wait_s * 1e3, 3)},
                        status="error", error=str(exc))
                    raise exc
            depth = int(self.cfg.serve_queue_depth)
            if queue_rows + n > depth:
                with _stats_lock:
                    self.stats.rejected += 1
                    # Computed backpressure hint: how long the queue is
                    # predicted to take to drain, clamped — not the old
                    # hard-coded constant.
                    retry_after = min(
                        _RETRY_AFTER_MAX_S,
                        max(_RETRY_AFTER_MIN_S,
                            self.stats.predicted_wait_s(queue_rows)))
                raise QueueFull(self.name, queue_rows,
                                retry_after_s=retry_after)
            pending = _Pending(X, entry, deadline=deadline,
                               budget_ms=budget_ms)
            self._queue.append(pending)
            self._queue_rows += n
            self._cond.notify_all()
        wait_s = float(self.cfg.serve_timeout_s)
        if deadline is not None:
            wait_s = min(wait_s, max(0.0, deadline - time.monotonic()))
        if not pending.done.wait(wait_s):
            # Withdraw the dead request: if it is still queued, the
            # device must not burn a dispatch computing rows nobody
            # will read (the 503'd client is already re-sending them).
            # Already-taken requests compute wastefully once — bounded.
            withdrew = True
            with self._cond:
                try:
                    self._queue.remove(pending)
                    self._queue_rows -= n
                except ValueError:
                    withdrew = False        # dispatcher already took it
            waited_ms = (time.monotonic() - pending.t_enqueue) * 1e3
            if deadline is not None and time.monotonic() >= deadline:
                # Count only when WE removed it: a pending the
                # dispatcher already took is either discarded by
                # _discard_expired (which counts it there) or computed
                # as bounded waste — counting here too would double the
                # rate alert's numerator for one expiry.
                exc = DeadlineExceeded(self.name, budget_ms or 0.0,
                                       waited_ms, "queue")
                if withdrew:
                    with _stats_lock:
                        self.stats.deadline_exceeded += 1
                    tracing.record_span(
                        "deadline.expired", waited_ms / 1e3,
                        attrs={"model": self.name, "rows": n,
                               "budget_ms": budget_ms},
                        status="error", error=str(exc))
                raise exc
            with _stats_lock:
                self.stats.timeouts += 1
            raise PredictTimeout(
                f"predict timed out after {self.cfg.serve_timeout_s}s "
                f"queued on model {self.name}")
        if pending.error is not None:
            raise pending.error
        lat = time.monotonic() - pending.t_enqueue
        with _stats_lock:
            self.stats.requests += 1
            self.stats.rows += n
            self.stats.observe(lat)
        return pending.probs

    def queue_rows(self) -> int:
        with self._cond:
            return self._queue_rows

    def thread_alive(self) -> bool:
        """Liveness probe for the health rollup: True while the
        dispatcher thread runs OR it exited deliberately (stop or
        quarantine — both answer requests with a mapped status) — only a
        dead-but-not-stopped thread (the PR 6 silent-death class the
        thread sanitizer hunts) reads as unhealthy."""
        with self._cond:
            if self._stopped or self._quarantined:
                return True
        return self._thread.is_alive()

    def outstanding(self) -> int:
        """Requests this batcher still owes an answer: queued plus taken
        but not yet scattered — the drain loop's quiesce probe."""
        with self._cond:
            queued = len(self._queue)
        return queued + sum(1 for p in self._inflight
                            if not p.done.is_set())

    # -- worker side ---------------------------------------------------------

    def _take_batch(self) -> Tuple[List[_Pending], List[_Pending]]:
        """Pop up to ``serve_max_batch`` rows' worth of waiting requests,
        lingering up to ``serve_max_wait_ms`` for a fuller batch. Whole
        requests only — a single request never splits across dispatches,
        so scatter-back is a simple offset walk. Requests whose deadline
        already passed are DISCARDED here instead of batched — padding a
        dead caller's rows into a dispatch spends device time answering
        nobody — and returned separately for 504 scatter + accounting
        (outside the cond)."""
        max_rows = max(1, int(self.cfg.serve_max_batch))
        expired: List[_Pending] = []
        with self._cond:
            # Plain wait: submit() and stop() both notify under the
            # cond, so an idle dispatcher sleeps silently instead of
            # polling.
            while not self._queue and not self._stopped:
                self._cond.wait()
            if self._stopped and not self._queue:
                return [], []
            deadline = (time.monotonic()
                        + float(self.cfg.serve_max_wait_ms) / 1e3)
            # _queue_rows is maintained by submit/_take_batch/timeout
            # withdrawal under this cond — O(1) vs re-walking the deque
            # on every linger wakeup.
            while self._queue_rows < max_rows and not self._stopped:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
            batch: List[_Pending] = []
            rows = 0
            now = time.monotonic()
            while self._queue and rows + len(self._queue[0].X) <= max_rows:
                p = self._queue.popleft()
                if p.deadline is not None and now >= p.deadline:
                    self._queue_rows -= len(p.X)
                    expired.append(p)
                    continue
                rows += len(p.X)
                batch.append(p)
            if not batch and self._queue:
                # Head request alone exceeds max_batch (only possible if
                # someone shrank serve_max_batch at runtime): dispatch it
                # solo; aot.predict chunks it across max-bucket calls.
                # Same expiry rule as the normal pop — an oversized
                # request is not a license to dispatch a dead caller.
                p = self._queue.popleft()
                if p.deadline is not None and now >= p.deadline:
                    self._queue_rows -= len(p.X)
                    expired.append(p)
                else:
                    batch.append(p)
                    rows = len(p.X)
            self._queue_rows -= rows
            t_taken = time.monotonic()
            for p in batch:
                p.t_taken = t_taken
            self._inflight = batch
            return batch, expired

    def _discard_expired(self, expired: List[_Pending]) -> None:
        """504 the requests whose deadline passed while queued: error
        scatter + counter + a trace record of the expiry — the device
        never saw their rows (the acceptance invariant the deadline
        chaos test pins via the dispatch counters)."""
        with _stats_lock:
            self.stats.deadline_exceeded += len(expired)
        for p in expired:
            waited_s = time.monotonic() - p.t_enqueue
            exc = DeadlineExceeded(self.name, p.budget_ms or 0.0,
                                   waited_s * 1e3, "queue")
            if p.ctx is not None and p.ctx.sampled:
                tracing.record_span(
                    "deadline.expired", waited_s, ctx=p.ctx,
                    attrs={"model": self.name, "rows": len(p.X),
                           "budget_ms": p.budget_ms},
                    status="error", error=str(exc))
            p.error = exc
            p.done.set()

    def _loop(self) -> None:
        while True:
            batch, expired = self._take_batch()
            if expired:
                self._discard_expired(expired)
            if not batch:
                # Empty means stopped-and-drained OR a timeout
                # withdrawal emptied the queue during the linger wait —
                # only the former ends the thread (a dead dispatcher
                # with _stopped False would black-hole the model).
                if self._stopped:
                    return
                continue
            # Per-request queue-wait attribution: enqueue → taken by the
            # dispatcher, recorded into EACH request's own trace (the
            # p99 blur the rolling-sample window could never decompose).
            for p in batch:
                if p.ctx is not None and p.ctx.sampled:
                    tracing.record_span(
                        "queue.wait", (p.t_taken or p.t_enqueue)
                        - p.t_enqueue, ctx=p.ctx,
                        attrs={"model": self.name, "rows": len(p.X)})
            # Group by the entry captured at enqueue: requests that
            # straddle a hot-swap evaluate through the version their
            # design matrix was built for (mixing would run old-state
            # rows through new params — silently wrong numbers, or a
            # width mismatch erroring innocent co-batched requests).
            # One dispatch per group; mixed-version batches only occur
            # in the swap instant itself.
            groups: Dict[int, List[_Pending]] = {}
            for p in batch:
                groups.setdefault(id(p.entry), []).append(p)
            for grp in groups.values():
                # Outside the per-group try on purpose: a raise here is
                # a dispatcher CRASH (supervised restart re-queues the
                # group — the device saw nothing), not a per-request
                # model error to scatter.
                failpoints.fire(FP_PRE_DISPATCH)
                entry = grp[0].entry
                for p in grp:
                    p.dispatched = True
                try:
                    t0 = time.monotonic()
                    X = (grp[0].X if len(grp) == 1
                         else np.concatenate([p.X for p in grp], axis=0))
                    # Replica 0 calls the bare form so tests/stub
                    # entries that monkeypatch a one-arg predict keep
                    # working; other replicas pass their device index
                    # through to the per-replica ladder.
                    probs = (entry.predict(X) if self.replica == 0
                             else entry.predict(X, self.replica))
                    t_device = time.monotonic() - t0
                except Exception as exc:  # noqa: BLE001 — scattered per req
                    with _stats_lock:
                        self.stats.errors += len(grp)
                    for p in grp:
                        p.error = exc
                        p.done.set()
                    continue
                # A raise here crashes the dispatcher AFTER the device
                # computed: supervision fails the group 503 (re-running
                # it would double-spend device time) — the asymmetry the
                # pre/mid chaos pair exists to prove.
                failpoints.fire(FP_MID_DISPATCH)
                try:
                    self._scatter(grp, probs, t0, t_device)
                finally:
                    for p in grp:
                        p.done.set()
            self._inflight = []
            # A clean batch ends any crash streak — quarantine is for
            # models that cannot dispatch at all, not ones that crashed
            # transiently N times over a whole process lifetime.
            self._crashes = 0

    def _scatter(self, grp: List[_Pending], probs: np.ndarray,
                 t0: float, t_device: float) -> None:
        """Scatter one dispatched group's results (or a scatter-side
        error) back to its requests. Its own except keeps the old
        contract: ANY failure after the device ran still hands every
        request a typed error — completing a request with neither probs
        nor error would surface as an opaque 500 downstream."""
        try:
            off = 0
            for p in grp:
                p.probs = probs[off:off + len(p.X)]
                off += len(p.X)
            with _stats_lock:
                self.stats.batches += 1
                self.stats.batched_rows += off
                self.stats.observe_dispatch(off, t_device)
            # One batch.coalesce span per coalesced dispatch
            # (recorded into the first traced request's trace),
            # linked as PARENT of every co-batched request's
            # dispatch.device span: the trace shows N requests
            # sharing one device program, and scatter time is
            # the coalesce−device gap.
            coalesce = time.monotonic() - t0
            bsid = None
            for p in grp:
                if p.ctx is not None and p.ctx.sampled:
                    bsid = tracing.record_span(
                        "batch.coalesce", coalesce, ctx=p.ctx,
                        attrs={"model": self.name,
                               "requests": len(grp), "rows": off})
                    break
            for p in grp:
                if p.ctx is not None and p.ctx.sampled:
                    tracing.record_span(
                        "dispatch.device", t_device, ctx=p.ctx,
                        parent_id=bsid,
                        attrs={"model": self.name,
                               "co_batched": len(grp),
                               "batch_rows": off})
        except Exception as exc:  # noqa: BLE001 — scattered per req
            with _stats_lock:
                self.stats.errors += len(grp)
            for p in grp:
                p.error = exc

    # -- supervision ---------------------------------------------------------

    def _run(self) -> None:
        """The dispatcher thread body: `_loop` under supervision. A
        crash (exception escaping the loop — the class that used to
        black-hole the model until process restart) restarts the loop
        under exponential backoff; `serve_quarantine_crashes`
        consecutive crashes quarantine the model instead."""
        while True:
            try:
                self._loop()
                return                      # stopped and drained
            except Exception as exc:  # noqa: BLE001 — supervised boundary
                if not self._survive_crash(exc):
                    return

    def _survive_crash(self, exc: Exception) -> bool:
        """Handle one dispatcher crash; True = restart the loop."""
        log.error("dispatcher for model %s crashed: %s: %s",
                  self.name, type(exc).__name__, exc, exc_info=exc)
        inflight = [p for p in self._inflight if not p.done.is_set()]
        self._inflight = []
        requeue = [p for p in inflight if not p.dispatched]
        lost = [p for p in inflight if p.dispatched]
        self._crashes += 1
        with _stats_lock:
            self.stats.dispatcher_restarts += 1
        threshold = max(1, int(self.cfg.serve_quarantine_crashes))
        if self._crashes >= threshold:
            with self._cond:
                self._quarantined = (
                    f"model {self.name} quarantined after {self._crashes} "
                    f"consecutive dispatcher crashes "
                    f"(last: {type(exc).__name__}: {exc}); DELETE or "
                    "re-save the model to lift the quarantine")
                leftovers = list(self._queue)
                self._queue.clear()
                self._queue_rows = 0
            with _stats_lock:
                self.stats.quarantined = 1
            log.error("%s", self._quarantined)
            qerr = ModelQuarantined(self._quarantined)
            for p in requeue + lost + leftovers:
                p.error = qerr
                p.done.set()
            # Freeze the evidence AFTER failing the waiters: the dump
            # (span snapshot, history window, disk writes) can take
            # real time, and blocked callers must get their prompt 503
            # instead of burning deadline budget behind it — the trace
            # ring and history are unaffected by the ordering.
            # Best-effort by contract (flightrec.incident never
            # raises).
            flightrec.incident(
                "serving.quarantine",
                detail={"model": self.name, "crashes": self._crashes,
                        "reason": self._quarantined})
            return False
        # Already-dispatched requests lost their results with the crash;
        # re-running them would double-spend device time — fail them 503
        # (the client's backoff retries against the restarted loop).
        cerr = DispatcherCrashed(
            f"predict dispatcher for model {self.name} crashed mid-batch "
            f"({type(exc).__name__}: {exc}); dispatcher restarting — retry")
        for p in lost:
            p.error = cerr
            p.done.set()
        with self._cond:
            if self._stopped:
                # stop() raced the crash: it is joining this thread and
                # will fail whatever remains queued; don't re-queue onto
                # a dispatcher that is never coming back.
                for p in requeue:
                    p.error = BatcherStopped(
                        f"predict dispatcher for model {self.name} stopped")
                    p.done.set()
                return False
            # The device never saw these rows: put them back at the
            # FRONT in their original order so the restarted loop serves
            # them first — a stock client completes without even a
            # retry.
            for p in reversed(requeue):
                self._queue.appendleft(p)
                self._queue_rows += len(p.X)
        backoff = min(_RESTART_BACKOFF_CAP_S,
                      float(self.cfg.serve_restart_backoff_s)
                      * (2 ** (self._crashes - 1)))
        log.warning("restarting dispatcher for model %s in %.2fs "
                    "(crash %d/%d before quarantine)",
                    self.name, backoff, self._crashes, threshold)
        if self._stopping.wait(backoff):
            return False                   # stop() interrupted the backoff
        return True

    def stop(self) -> None:
        self._stopping.set()
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
        self._thread.join(timeout=_RESTART_BACKOFF_CAP_S + 5.0)
        # Fail anything still queued so no handler thread waits out its
        # full timeout against a dead worker.
        with self._cond:
            leftovers = list(self._queue)
            self._queue.clear()
            self._queue_rows = 0
        for p in leftovers:
            p.error = BatcherStopped(
                f"predict dispatcher for model {self.name} stopped")
            p.done.set()


#: One lock for all stats mutation — counters are tiny and contention is
#: request-rate, not row-rate.
_stats_lock = threading.Lock()


class PredictBatcher:
    """The serving facade: per-model replica sets created lazily, shared
    AOT cache, aggregate metrics. Held by the App; handlers call
    :meth:`predict` and everything else is internal.

    With ``serve_replicas`` > 1 each model gets one :class:`ModelBatcher`
    (queue + dispatcher thread + stats block) PER replica, and
    :meth:`predict_probs` routes each request to the replica with the
    lowest predicted queue wait (queue depth × that replica's own
    service-rate EWMA, ties broken by raw depth then replica index —
    deterministic, and concentrating idle traffic on replica 0 keeps the
    single-replica path exercised). Quarantine is per-replica: a crashed
    replica degrades capacity while its siblings keep answering, and the
    model-level quarantine (terminal 503) only applies when EVERY
    replica is quarantined."""

    def __init__(self, registry: ModelRegistry,
                 cfg: Optional[Settings] = None):
        self.cfg = cfg or global_settings
        self.aot = AotCache(registry, self.cfg)
        #: Replica count resolved once by the AOT cache — the dispatcher
        #: sets here are sized to the same topology the ladders compile
        #: for.
        self.replicas = self.aot.replicas
        self._lock = threading.Lock()
        self._batchers: Dict[str, List[ModelBatcher]] = {}
        self._stats: Dict[str, List[_Stats]] = {}
        self._stopped = False
        #: Requests currently inside :meth:`predict` — including the
        #: handler phase (design build, first-touch compile) BEFORE the
        #: rows reach any queue. The drain quiesce probe must count
        #: these too: stopping the dispatchers while an accepted request
        #: is still preprocessing would 503 it mid-drain.
        self._active = 0

    def _replica_set(self, name: str) -> List[ModelBatcher]:
        """The model's full dispatcher set, created lazily (all replicas
        at once — a model is either replicated or not, never half)."""
        with self._lock:
            if self._stopped:
                # A handler racing Server.stop() must not resurrect a
                # dispatcher thread nothing will ever stop again.
                raise BatcherStopped(
                    f"predict tier stopped; model {name} not served")
            bs = self._batchers.get(name)
            if bs is None:
                # Re-validate before spawning dispatchers: a request
                # racing DELETE can reach here after invalidate()
                # already tore the batchers down — without this check it
                # would resurrect dispatcher threads for a model that
                # can never serve again.
                self.aot.registry.version(name)   # ModelNotFound → 404
                stats = self._stats.setdefault(
                    name, [_Stats() for _ in range(self.replicas)])
                with _stats_lock:
                    # Fresh dispatchers (post-DELETE/re-save) lift any
                    # previous quarantine; the counter history survives.
                    for st in stats:
                        st.quarantined = 0
                bs = [ModelBatcher(name, self.cfg, stats[i], replica=i)
                      for i in range(self.replicas)]
                self._batchers[name] = bs
            return bs

    def _batcher(self, name: str) -> ModelBatcher:
        """The replica this request dispatches to: the cost-based
        router. Cost = predicted queue wait (depth × that replica's own
        service-rate EWMA), ties broken by raw queue depth, then replica
        index. Quarantined replicas are excluded; only when EVERY
        replica is quarantined does the model answer the terminal
        quarantine 503."""
        bs = self._replica_set(name)
        if len(bs) == 1:
            b = bs[0]
            reason = b.quarantined()
            if reason:
                raise ModelQuarantined(reason)
            return b
        live = [b for b in bs if b.quarantined() is None]
        if not live:
            raise ModelQuarantined(bs[0].quarantined())
        depths = [(b, b.queue_rows()) for b in live]
        with _stats_lock:
            scored = [(b.stats.predicted_wait_s(q), q, b.replica, b)
                      for b, q in depths]
        return min(scored)[3]

    def predict(self, name: str, rows: Sequence[Any],
                deadline_ms: Optional[float] = None) -> Dict[str, Any]:
        """The whole handler shim: rows → design matrix (host-side, on
        the handler thread so feature prep overlaps other models'
        device work) → enqueue/await → JSON-able result.

        ``deadline_ms`` is the caller's remaining end-to-end budget; the
        clock starts HERE (so design-build time counts against it), and
        expiry anywhere downstream raises :class:`DeadlineExceeded`
        (→ terminal 504)."""
        kind, probs = self.predict_probs(name, rows, deadline_ms)
        # .tolist() (C-speed) — this runs per request on the hot path.
        return {
            "model": name,
            "kind": kind,
            "predictions": np.argmax(probs, axis=1).tolist(),
            # tolist() on float32 already widens to exact Python floats
            # — an astype(float64) first would copy for identical JSON.
            "probabilities": probs.tolist(),
        }

    def predict_probs(self, name: str, rows: Sequence[Any],
                      deadline_ms: Optional[float] = None
                      ) -> Tuple[str, np.ndarray]:
        """The raw form of :meth:`predict`: ``(model kind, float32
        probability matrix)`` with NO response formatting — what the
        multi-worker front end's row channel calls, so the JSON encode
        of a forwarded request happens in the worker process (off this
        process's GIL) while the numbers stay bit-identical (the worker
        runs the same argmax/tolist on the same float32 bytes).
        Accounting, deadlines, backpressure and drain quiescing are
        identical by construction: :meth:`predict` is this plus
        formatting."""
        with self._lock:
            self._active += 1
        try:
            entry, probs = self._predict(name, rows, deadline_ms)
            return entry.kind, probs
        finally:
            with self._lock:
                self._active -= 1

    def predict_with_epoch(self, name: str, rows: Sequence[Any],
                           deadline_ms: Optional[float] = None
                           ) -> Tuple[str, np.ndarray, int]:
        """:meth:`predict_probs` plus the swap epoch of the AOT entry
        the rows evaluated through — the hot-swap consistency probe: the
        epoch is stamped once per (name, version) cache insert under the
        cache lock, so two responses with the same epoch are guaranteed
        to have been served by the SAME model version on every replica
        (no mixed-version pair can share an epoch). Accounting is
        identical to :meth:`predict_probs` by construction."""
        with self._lock:
            self._active += 1
        try:
            entry, probs = self._predict(name, rows, deadline_ms)
            return entry.kind, probs, entry.swap_epoch
        finally:
            with self._lock:
                self._active -= 1

    def _predict(self, name: str, rows: Sequence[Any],
                 deadline_ms: Optional[float]) -> Tuple[Any, np.ndarray]:
        deadline = budget_ms = None
        if deadline_ms is not None:
            if deadline_ms <= 0:
                # The budget arrived already spent: terminal 504 —
                # counted and traced like any other miss, so a client
                # burning 100% of its requests this way still moves
                # lo_serving_deadline_exceeded_total and the rate alert.
                self.aot.registry.version(name)   # unknown model → 404
                with self._lock:
                    stats = self._stats.setdefault(
                        name, [_Stats() for _ in range(self.replicas)])
                with _stats_lock:
                    # Never routed, so it charges replica 0 — the
                    # aggregate (what the rate alert reads) is the sum.
                    stats[0].deadline_exceeded += 1
                exc = DeadlineExceeded(name, float(deadline_ms), 0.0,
                                       "admission")
                tracing.record_span(
                    "deadline.rejected", 0.0,
                    attrs={"model": name,
                           "budget_ms": float(deadline_ms)},
                    status="error", error=str(exc))
                raise exc
            budget_ms = float(deadline_ms)
            deadline = time.monotonic() + budget_ms / 1e3
        if int(self.cfg.serve_queue_depth) <= 0:
            # Existence check BEFORE creating a stats slot: _stats
            # entries are permanent (invalidate() keeps them for
            # /metrics continuity), so minting one per client-supplied
            # name would let a scanner grow this dict — and /metrics —
            # without bound. Unknown models 404 here like everywhere
            # else; real ones count the rejection below.
            self.aot.registry.version(name)   # ModelNotFound → 404
            # Count the rejection: a tier bouncing 100% of traffic must
            # show it on /metrics, not read as zero rejections.
            with self._lock:
                stats = self._stats.setdefault(
                    name, [_Stats() for _ in range(self.replicas)])
            with _stats_lock:
                stats[0].rejected += 1
            raise QueueFull(name, 0)
        # Quarantine check BEFORE any per-request work: a fully
        # quarantined model's terminal 503 should cost a dict lookup,
        # not a design build (the _batcher() re-check still guards the
        # race). Partially quarantined sets fall through — the router
        # only considers live replicas.
        with self._lock:
            bs = self._batchers.get(name)
        if bs is not None:
            reasons = [b.quarantined() for b in bs]
            if all(reasons):
                raise ModelQuarantined(reasons[0])
        # Load/compile (and 404/406) BEFORE enqueueing: a bad model name
        # must not cost a queue slot, and first-touch compile happens on
        # the handler thread instead of stalling the dispatch loop.
        entry = self.aot.entry(name)
        # Shape-check the body before len()/preprocessing: {"rows":
        # null} or a scalar must 406 like every other malformed input,
        # not 500 on a TypeError. An ndarray means a binary columnar
        # body already decoded (serving/rowchannel.py) — design rows
        # with zero per-row parse left to do.
        if not isinstance(rows, (list, tuple, np.ndarray)):
            raise ValueError(
                "rows must be a non-empty JSON array of feature rows")
        # Cap check BEFORE preprocessing: the client's cap-discovery
        # probe deliberately oversends and expects a cheap 406 — don't
        # vocab-encode/fillna 256 rows just to throw them away. The cap
        # folds in serve_queue_depth: a request bigger than the whole
        # queue can NEVER be accepted, so it must get this terminal 406
        # (whose cap the client re-splits to) rather than burn its
        # retry budget on guaranteed QueueFull 503s.
        cap = min(int(self.cfg.serve_max_batch),
                  int(self.cfg.serve_queue_depth))
        if len(rows) > cap:
            raise ValueError(
                f"request carries {len(rows)} rows; per-request cap is "
                f"serve_max_batch={cap} — split client-side "
                "(Model.predict_online does)")
        t0 = time.monotonic()
        X = design_from_rows(rows, entry.preprocess)
        # Host-side feature prep on the handler thread, attributed per
        # request — the queue.wait / dispatch.device spans downstream
        # come from the dispatcher (ModelBatcher._loop).
        tracing.record_span("design.build", time.monotonic() - t0,
                            attrs={"model": name, "rows": len(rows)})
        probs = self._batcher(name).submit(X, entry, deadline=deadline,
                                           budget_ms=budget_ms)
        return entry, probs

    def invalidate(self, name: Optional[str] = None) -> None:
        """Drop compiled programs (and the dispatcher thread) for a
        deleted/re-saved model; stats survive so /metrics history does
        not reset — except the quarantined LEVEL, which this call is
        the documented lift for: a DELETEd model never creates another
        batcher, so clearing it only on batcher re-creation would pin
        the gauge (and the serving_quarantined alert) at 1 forever."""
        self.aot.invalidate(name)
        with self._lock:
            if name is None:
                doomed = [b for bs in self._batchers.values() for b in bs]
                self._batchers.clear()
                cleared = [st for sts in self._stats.values() for st in sts]
            else:
                bs = self._batchers.pop(name, None)
                doomed = list(bs) if bs is not None else []
                sts = self._stats.get(name)
                cleared = list(sts) if sts is not None else []
        for b in doomed:
            b.stop()
        with _stats_lock:
            for st in cleared:
                st.quarantined = 0

    def health(self) -> Dict[str, Any]:
        """Dispatcher-thread liveness for ``GET /healthz``: a model whose
        dispatcher thread died without being stopped would black-hole
        its requests — the silent failure mode the deep health rollup
        exists to surface. Quarantined models are listed (they answer a
        mapped terminal 503, so they don't flip ``ok`` — the
        ``serving_quarantined`` alert carries the paging signal)."""
        with self._lock:
            batchers = dict(self._batchers)
        dead = sorted(n for n, bs in batchers.items()
                      if any(not b.thread_alive() for b in bs))
        # A model is "quarantined" (terminal 503) only when EVERY
        # replica is; partially quarantined models keep serving and are
        # named per replica below — capacity degraded, not availability.
        quarantined = sorted(n for n, bs in batchers.items()
                             if all(b.quarantined() for b in bs))
        quarantined_replicas = {
            n: [b.replica for b in bs if b.quarantined()]
            for n, bs in sorted(batchers.items())
            if any(b.quarantined() for b in bs)}
        return {"ok": not dead,
                "dispatchers": sum(len(bs) for bs in batchers.values()),
                "replicas": self.replicas,
                "dead": dead, "quarantined": quarantined,
                "quarantined_replicas": quarantined_replicas}

    def quiesced(self) -> bool:
        """True when no request is anywhere inside the tier — neither
        in :meth:`predict`'s handler phase (design build / first-touch
        compile, before any queue) nor queued/in-flight on a dispatcher
        — the drain loop's completion probe (new work is gated off
        upstream while draining, so this only ever goes to True and
        stays)."""
        with self._lock:
            if self._active > 0:
                return False
            batchers = [b for bs in self._batchers.values() for b in bs]
        return all(b.outstanding() == 0 for b in batchers)

    def _model_snapshot(self, sts: List[_Stats],
                        queues: List[int]) -> Dict[str, Any]:
        """One model's snapshot doc across its replicas (caller holds
        ``_stats_lock``). A single replica delegates to its stats block
        verbatim — the exact pre-replication document, so the
        replicas=1 metric surface is byte-for-byte. Multi-replica docs
        sum counters, sum per-replica QPS, weight the service rate by
        dispatched rows, and merge the latency HISTOGRAMS element-wise
        before estimating percentiles (a percentile of percentiles
        would be meaningless). Both carry a ``replicas`` list with each
        replica's slim occupancy/rate/health row."""
        per = [st.snapshot(q) for st, q in zip(sts, queues)]
        if len(per) == 1:
            doc = per[0]
        else:
            doc = {k: sum(p[k] for p in per)
                   for k in ("requests", "rows", "batches", "batched_rows",
                             "rejected", "timeouts", "errors",
                             "deadline_exceeded", "dispatcher_restarts",
                             "queue_rows")}
            doc["quarantined"] = (
                1 if all(p["quarantined"] for p in per) else 0)
            doc["qps"] = round(sum(p["qps"] for p in per), 3)
            doc["mean_batch_rows"] = (
                round(doc["batched_rows"] / doc["batches"], 3)
                if doc["batches"] else 0.0)
            br = doc["batched_rows"]
            doc["service_us_per_row"] = (
                round(sum(p["service_us_per_row"] * p["batched_rows"]
                          for p in per) / br, 3) if br else 0.0)
            life = [sum(v) for v in
                    zip(*(st.lat_buckets for st in sts))]
            window = [sum(v) for v in zip(
                *([a + b for a, b in zip(st._lat_prev, st._lat_recent)]
                  for st in sts))]
            source = window if sum(window) else life

            def pct(q: float) -> Optional[float]:
                est = profiling.quantile_from_buckets(source, q)
                return None if est is None else round(est * 1e3, 3)

            doc["p50_ms"] = pct(0.50)
            doc["p99_ms"] = pct(0.99)
            doc["latency"] = {
                "buckets": life,
                "sum_s": round(sum(st.lat_sum_s for st in sts), 6)}
        doc["replicas"] = [
            {"replica": i,
             "queue_rows": p["queue_rows"],
             "qps": p["qps"],
             "service_us_per_row": p["service_us_per_row"],
             "requests": p["requests"],
             "rows": p["rows"],
             "batches": p["batches"],
             "batched_rows": p["batched_rows"],
             "mean_batch_rows": p["mean_batch_rows"],
             "dispatcher_restarts": p["dispatcher_restarts"],
             "quarantined": p["quarantined"]}
            for i, p in enumerate(per)]
        return doc

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            names = list(self._stats)
            queue = {n: ([b.queue_rows() for b in self._batchers[n]]
                         if n in self._batchers
                         else [0] * len(self._stats[n])) for n in names}
        with _stats_lock:
            models = {n: self._model_snapshot(self._stats[n], queue[n])
                      for n in names}
        agg: Dict[str, Any] = {
            "requests": sum(m["requests"] for m in models.values()),
            "rows": sum(m["rows"] for m in models.values()),
            "batches": sum(m["batches"] for m in models.values()),
            "rejected": sum(m["rejected"] for m in models.values()),
            "timeouts": sum(m["timeouts"] for m in models.values()),
            "errors": sum(m["errors"] for m in models.values()),
            "deadline_exceeded": sum(m["deadline_exceeded"]
                                     for m in models.values()),
            "dispatcher_restarts": sum(m["dispatcher_restarts"]
                                       for m in models.values()),
            "quarantined": sum(m["quarantined"] for m in models.values()),
            "queue_rows": sum(m["queue_rows"] for m in models.values()),
            "qps": round(sum(m["qps"] for m in models.values()), 3),
        }
        batches = agg["batches"]
        agg["mean_batch_rows"] = (
            round(sum(m["mean_batch_rows"] * m["batches"]
                      for m in models.values()) / batches, 3)
            if batches else 0.0)
        return {**agg, "aot": self.aot.snapshot(), "models": models}

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
            batchers = [b for bs in self._batchers.values() for b in bs]
            self._batchers.clear()
        for b in batchers:
            b.stop()
