"""Minimal threaded HTTP/JSON framework on the Python stdlib.

The reference runs 7 separate Flask apps, one per microservice, each with
its own port and copy-pasted error mapping (reference
microservices/*/server.py). This framework provides the same request
surface — JSON bodies, query params, path params, file responses, and the
406/409/404 error mapping convention (e.g. model_builder_image/
server.py:52-115) — in ~150 lines with no third-party dependency, served by
``ThreadingHTTPServer`` so long-running jobs never block other requests.
"""

from __future__ import annotations

import collections
import json
import re
import socket
import threading
import traceback
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from learningorchestra_tpu.utils import failpoints, tracing

#: Inbound X-Request-Id values become trace ids verbatim when they look
#: like ids; anything else (oversized, control chars, header-injection
#: attempts) is replaced with a fresh id rather than propagated.
_REQUEST_ID_RE = re.compile(r"^[A-Za-z0-9._-]{1,64}$")

#: Chaos seam at the response-write boundary — the handler computed an
#: answer the client may never (or only very late) receive. raise-mode
#: proves the error path still answers (one-shot re-entry); slow/hang
#: exercise client-side read timeouts against a committed server.
FP_PRE_RESPONSE = failpoints.declare("serving.http.pre_response")


def parse_body(raw: Optional[bytes], content_type: str) -> Optional[Dict]:
    """Request body bytes → handler body dict — THE body parse, shared
    by the threaded handler and the row-channel proxy path so a body
    parses identically whichever topology served it.

    JSON is the default; a binary columnar body
    (``application/x-lo-columnar``) decodes to ``{"rows": <float32
    matrix>}`` — the zero-copy predict fast path — and malformation maps
    to the same 406 a malformed JSON row gets, never a 500."""
    if not raw:
        return None
    base = (content_type or "").split(";", 1)[0].strip().lower()
    if base == "application/x-lo-columnar":
        from learningorchestra_tpu.serving.rowchannel import (
            decode_columnar)

        try:
            return {"rows": decode_columnar(raw)}
        except ValueError as e:
            raise HttpError(406, str(e)) from None
    try:
        return json.loads(raw)
    except json.JSONDecodeError:
        raise HttpError(400, "invalid JSON body") from None


class HttpError(Exception):
    def __init__(self, status: int, message: str,
                 headers: Optional[Dict[str, str]] = None):
        super().__init__(message)
        self.status = status
        self.message = message
        #: Extra response headers — e.g. the 503 pod-degraded answer
        #: carries Retry-After so clients back off for a restart window
        #: instead of hammering a pod mid-recovery.
        self.headers = dict(headers or {})


class Request:
    def __init__(self, method: str, path: str, params: Dict[str, str],
                 query: Dict[str, List[str]], body: Optional[Dict[str, Any]],
                 headers: Optional[Dict[str, str]] = None):
        self.method = method
        self.path = path
        self.params = params
        self.query = query
        self.body = body or {}
        #: Request headers, case-insensitively readable via ``header()``.
        self.headers = dict(headers or {})
        self._headers_lower = {k.lower(): v for k, v in self.headers.items()}

    def header(self, name: str, default: Optional[str] = None):
        return self._headers_lower.get(name.lower(), default)

    def q(self, name: str, default=None, cast=None):
        vals = self.query.get(name)
        if not vals:
            return default
        return cast(vals[0]) if cast else vals[0]

    def require(self, *names: str) -> List[Any]:
        out = []
        for n in names:
            if n not in self.body:
                raise HttpError(400, f"missing required field: {n}")
            out.append(self.body[n])
        return out


class FileResponse:
    def __init__(self, path: str, content_type: str = "image/png"):
        self.path = path
        self.content_type = content_type


class HtmlResponse:
    """An HTML page body — the cluster status view (the stand-in for the
    reference's dockersamples/visualizer on :80, docker-compose.yml:109-121)
    is the only non-JSON, non-file surface."""

    def __init__(self, html: str, status: int = 200):
        self.html = html
        self.status = status


class TextResponse:
    """A plain-text body — the Prometheus exposition surface
    (``GET /metrics?format=prometheus``); the version suffix in the
    default content type is the exposition-format handshake scrapers
    expect."""

    def __init__(self, text: str,
                 content_type: str =
                 "text/plain; version=0.0.4; charset=utf-8",
                 status: int = 200):
        self.text = text
        self.content_type = content_type
        self.status = status


class Router:
    def __init__(self):
        self._routes: List[Tuple[str, re.Pattern, str, Callable]] = []

    def route(self, method: str, pattern: str):
        """Register ``pattern`` like "/files/{name}"."""
        regex = re.compile(
            "^" + re.sub(r"\{(\w+)\}", r"(?P<\1>[^/]+)", pattern) + "$")

        def deco(fn):
            self._routes.append((method.upper(), regex, pattern, fn))
            return fn

        return deco

    def dispatch(self, req_method: str, url: str, body: Optional[Dict],
                 headers: Optional[Dict[str, str]] = None,
                 attrs: Optional[Dict[str, Any]] = None) -> Tuple[int, Any]:
        """``attrs`` (the request's root-span attribute dict, recorded
        by reference at span exit) receives the matched route PATTERN —
        so per-route latency attribution aggregates
        ``/trained-models/{name}/predict`` as ONE label instead of one
        per model name (bounded cardinality by construction)."""
        parsed = urlparse(url)
        for method, regex, pattern, fn in self._routes:
            if method != req_method:
                continue
            m = regex.match(parsed.path)
            if not m:
                continue
            if attrs is not None:
                attrs["route"] = pattern
            req = Request(req_method, parsed.path, m.groupdict(),
                          parse_qs(parsed.query), body, headers)
            return fn(req)
        raise HttpError(404, f"no route: {req_method} {parsed.path}")


class IdempotencyCache:
    """Replay cache keyed by the client's ``Idempotency-Key`` header.

    Closes the POST-retry gap: a create whose response was lost to a
    connection drop (or a pod-recovery window) can be retried with the
    same key and receives the FIRST attempt's recorded outcome — success
    or error — instead of a spurious 409 from the already-landed create.
    A concurrent duplicate (client retried while the first attempt is
    still executing) waits for the original instead of racing it.
    Bounded FIFO so a long-lived server doesn't leak a record per create.
    """

    def __init__(self, cap: int = 1024, wait_timeout_s: float = 600.0):
        self._lock = threading.Lock()
        self._entries: "collections.OrderedDict[str, dict]" = \
            collections.OrderedDict()
        self._cap = cap
        self._wait_timeout_s = wait_timeout_s

    def run(self, key: Optional[str], fn: Callable[[], Tuple[int, Any]]):
        if not key:
            return fn()
        with self._lock:
            ent = self._entries.get(key)
            if ent is None:
                ent = {"done": threading.Event(), "outcome": None}
                self._entries[key] = ent
                while len(self._entries) > self._cap:
                    # Evict the oldest *completed* entry — in-flight
                    # ones must stay visible to their duplicates, but a
                    # long-running oldest entry (a minutes-long sync
                    # build) must not block eviction behind it.
                    victim = next((k for k, e in self._entries.items()
                                   if e["done"].is_set()), None)
                    if victim is None:
                        break
                    del self._entries[victim]
                owner = True
            else:
                owner = False
        if not owner:
            if not ent["done"].wait(self._wait_timeout_s):
                raise HttpError(
                    409, "duplicate request still in flight "
                    f"(Idempotency-Key {key})")
            kind, val = ent["outcome"]
            if kind == "ok":
                return val
            raise HttpError(val.status, val.message, headers=val.headers)
        try:
            out = fn()
            ent["outcome"] = ("ok", out)
            return out
        except HttpError as e:
            if e.status == 503:
                # Transient (pod mid-recovery): drop the entry so the
                # client's Retry-After retry RE-EXECUTES against the
                # recovered pod instead of replaying the 503 forever.
                with self._lock:
                    self._entries.pop(key, None)
            ent["outcome"] = ("err", e)
            raise
        except Exception as e:  # noqa: BLE001 — replay as a 500
            ent["outcome"] = ("err", HttpError(500, f"internal error: {e}"))
            raise
        finally:
            ent["done"].set()


def _make_handler(router: Router, request_timeout_s: Optional[float] = None):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        #: Per-connection socket timeout (socketserver.StreamRequestHandler
        #: applies it in setup()): a client that sends a Content-Length it
        #: never delivers — or goes dark mid-request — times out instead
        #: of pinning a handler thread forever.
        timeout = request_timeout_s or None

        def log_message(self, fmt, *args):  # quiet by default
            pass

        def _read_body(self) -> Optional[Dict]:
            length = int(self.headers.get("Content-Length") or 0)
            if not length:
                return None
            raw = self.rfile.read(length)
            # Shared parse (JSON or binary columnar) — identical to the
            # multi-worker proxy path's, so a client needn't know the
            # server's topology to pick a body format.
            return parse_body(raw,
                              self.headers.get("Content-Type") or "")

        def _send_bytes(self, status: int, content_type: str,
                        data: bytes,
                        headers: Optional[Dict[str, str]] = None) -> None:
            failpoints.fire(FP_PRE_RESPONSE)
            self.send_response(status)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(data)))
            # Every response carries the request's trace id: a client
            # (or a human with curl) can quote it against GET /trace/{id}
            # and the structured logs without any luck in timing.
            rid = getattr(self, "_request_id", None)
            if rid:
                self.send_header("X-Request-Id", rid)
            for k, v in (headers or {}).items():
                self.send_header(k, v)
                if k.lower() == "connection" and v.lower() == "close":
                    # Honor an explicit Connection: close (the draining
                    # 503 sends one): mark the keep-alive connection for
                    # teardown after this response so a draining server
                    # sheds its persistent connections instead of
                    # re-answering 503 on each until the socket times
                    # out.
                    self.close_connection = True
            self.end_headers()
            self.wfile.write(data)

        def _send_json(self, status: int, payload: Any,
                       headers: Optional[Dict[str, str]] = None) -> None:
            self._send_bytes(status, "application/json",
                             json.dumps(payload, default=str).encode(),
                             headers)

        def _send_file(self, resp: FileResponse) -> None:
            with open(resp.path, "rb") as f:
                data = f.read()
            self._send_bytes(200, resp.content_type, data)

        def _send_html(self, resp: HtmlResponse) -> None:
            self._send_bytes(resp.status, "text/html; charset=utf-8",
                             resp.html.encode())

        def _send_text(self, resp: TextResponse) -> None:
            self._send_bytes(resp.status, resp.content_type,
                             resp.text.encode())

        def _handle(self, method: str) -> None:
            # The trace id for this request: the client's X-Request-Id
            # when it looks like one (so retries/evidence quote a stable
            # id end to end), else freshly minted.
            inbound = self.headers.get("X-Request-Id") or ""
            rid = (inbound if _REQUEST_ID_RE.match(inbound)
                   else tracing.new_id())
            self._request_id = rid
            # "path" is the raw URL; "route" is stamped by a MATCHED
            # dispatch with the route PATTERN — what the span and the
            # per-route latency attribution carry, so
            # "/trained-models/{name}/predict" stays one label however
            # many models exist. Unmatched requests (404s) carry no
            # route at all: attribution collapses them into one "-"
            # label instead of letting a URL scanner mint an entry per
            # bogus path and exhaust the bounded table.
            attrs = {"method": method,
                     "path": self.path.split("?", 1)[0]}
            with tracing.trace("http.handle", trace_id=rid, attrs=attrs):
                try:
                    body = self._read_body()
                    status, payload = router.dispatch(
                        method, self.path, body, dict(self.headers.items()),
                        attrs=attrs)
                    attrs["status"] = status
                    if isinstance(payload, FileResponse):
                        self._send_file(payload)
                    elif isinstance(payload, HtmlResponse):
                        self._send_html(payload)
                    elif isinstance(payload, TextResponse):
                        self._send_text(payload)
                    else:
                        self._send_json(status, payload)
                except HttpError as e:
                    attrs["status"] = e.status
                    attrs["error"] = e.message
                    self._send_json(e.status, {"result": e.message},
                                    headers=e.headers)
                except (socket.timeout, TimeoutError):
                    # Connection-level timeout (half-sent body from a hung
                    # or dead client): re-raise so handle_one_request
                    # closes the connection — answering 500 here would
                    # treat a dead peer as a server bug and keep the
                    # handler thread engaged. (The root span records the
                    # error status on its way out.)
                    raise
                except Exception as e:  # noqa: BLE001 — request boundary
                    attrs["status"] = 500
                    traceback.print_exc()
                    self._send_json(500, {"result": f"internal error: {e}"})

        def do_GET(self):
            self._handle("GET")

        def do_POST(self):
            self._handle("POST")

        def do_PATCH(self):
            self._handle("PATCH")

        def do_DELETE(self):
            self._handle("DELETE")

    return Handler


class Server:
    """Threaded HTTP server wrapper with programmatic start/stop (tests run
    it in-process; production runs it via ``python -m
    learningorchestra_tpu.serving``)."""

    def __init__(self, router: Router, host: str, port: int,
                 request_timeout_s: Optional[float] = None):
        self.httpd = ThreadingHTTPServer(
            (host, port), _make_handler(router, request_timeout_s))
        self.host = host
        self.port = self.httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None
        self._stop_callbacks: List[Callable[[], None]] = []

    def on_stop(self, fn: Callable[[], None]) -> None:
        """Register a teardown hook run by :meth:`stop` — the app wires
        its background workers (the predict batcher's dispatcher
        threads) here so stopping the server stops them too."""
        self._stop_callbacks.append(fn)

    def start_background(self) -> "Server":
        # thread-lifecycle: owner=Server; exits when stop() calls
        # httpd.shutdown() (serve_forever returns); daemon so a test
        # that never stops cannot hang interpreter exit.
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True, name="lo-http")
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        self.httpd.serve_forever()

    def stop(self) -> None:
        self.httpd.shutdown()
        # Teardown hooks run BEFORE server_close(): ThreadingHTTPServer
        # joins in-flight handler threads on close (block_on_close), and
        # handlers may be blocked awaiting a batcher result — stopping
        # the workers first fails those requests fast instead of
        # stalling shutdown behind their full serve timeout.
        for fn in self._stop_callbacks:
            try:
                fn()
            except Exception:  # noqa: BLE001 — teardown best-effort
                traceback.print_exc()
        self.httpd.server_close()
