"""Deterministic fault injection — named failpoints through the data plane.

The chaos coverage before this module was whole-process SIGKILL at one
site (tests/chaos_child.py): it proved the pod supervisor works, but said
nothing about torn writes, bit rot, or crashes at *specific* I/O
boundaries inside the chunk store. This is the Jepsen/TiKV-style
failpoint idiom: modules *declare* named injection sites at import time
(``declare("catalog.write_chunk.pre_rename")``) and call
``fire(site, path=...)`` at the guarded operation; tests (or an operator
reproducing a bug) activate sites via

    LO_TPU_FAILPOINTS=site=mode[:nth][,site2=mode2[:nth2]...]

with modes

- ``raise``   — raise :class:`FailpointError` (tests the error path);
- ``crash``   — ``os._exit(41)`` (the kill-at-this-exact-syscall chaos
  the sweep in tests/test_failpoints.py drives through a child process);
- ``hang``    — block ~1 hour (wedge detection / timeout paths);
- ``torn``    — truncate the in-flight file named by ``path`` to half
  its bytes (a torn write that later surfaces as corruption);
- ``bitflip`` — flip one bit mid-file in ``path`` (bit rot);
- ``slow``    — sleep :data:`SLOW_S` seconds (a stall long enough to
  breach any realistic deadline budget without wedging the suite the
  way ``hang`` would — the serving deadline chaos tests lean on it).

``nth`` (default 1) arms the site on its Nth hit — one-shot: after
firing, the site deactivates, so a recovery path re-entering the same
code cannot re-trip it. ``nth`` of **0** arms the site PERSISTENTLY —
it fires on *every* hit and never deactivates: how the dispatcher
quarantine chaos test makes a supervised restart crash again on each
attempt.

Zero overhead when unset: ``fire`` is a single attribute test on a
module-level flag that is False unless the env var (or ``configure``)
armed at least one site. The registry is introspectable (``sites()``)
so the failpoint sweep can enumerate every declared site instead of
hard-coding a list that silently rots.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional

ENV_VAR = "LO_TPU_FAILPOINTS"

#: Exit code for ``crash`` mode — distinguishable from interpreter errors
#: (1) and signals, so the sweep asserts the failpoint (and nothing else)
#: killed the child.
CRASH_EXIT_CODE = 41

_MODES = ("raise", "crash", "hang", "torn", "bitflip", "slow")

#: ``slow`` mode's stall length — long past any sane request deadline
#: budget, short enough that a test leaking one costs seconds, not the
#: suite timeout.
SLOW_S = 2.0


class FailpointError(RuntimeError):
    """Raised by an armed ``raise``-mode failpoint."""


class _Armed:
    __slots__ = ("mode", "nth", "hits", "fired")

    def __init__(self, mode: str, nth: int):
        self.mode = mode
        self.nth = nth
        self.hits = 0
        self.fired = False


_lock = threading.Lock()
_declared: Dict[str, int] = {}      # site -> total hit count (introspection)
_armed: Dict[str, _Armed] = {}
#: Fast-path flag: ``fire`` returns immediately while this is False.
_active = False


def declare(site: str) -> str:
    """Register a failpoint site (module import time). Idempotent;
    returns the site name so call sites can bind it to a constant."""
    with _lock:
        _declared.setdefault(site, 0)
    return site


def sites(prefix: str = "") -> List[str]:
    """All declared sites (optionally filtered by prefix) — the sweep's
    enumeration source."""
    with _lock:
        return sorted(s for s in _declared if s.startswith(prefix))


def hit_counts() -> Dict[str, int]:
    """Site -> times ``fire`` reached it (armed or not) this process."""
    with _lock:
        return dict(_declared)


def parse_spec(spec: str) -> Dict[str, _Armed]:
    """``site=mode[:nth],...`` -> armed map. Raises ValueError on a bad
    mode/count so a typo'd env var fails loudly, not silently-no-op."""
    out: Dict[str, _Armed] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"bad failpoint spec {part!r}: want site=mode")
        site, _, modespec = part.partition("=")
        mode, _, nth_s = modespec.partition(":")
        if mode not in _MODES:
            raise ValueError(
                f"unknown failpoint mode {mode!r} (want one of {_MODES})")
        nth = int(nth_s) if nth_s else 1
        if nth < 0:
            raise ValueError(
                f"failpoint nth must be >= 0 (0 = every hit), got {nth}")
        out[site.strip()] = _Armed(mode, nth)
    return out


def configure(spec: Optional[str]) -> None:
    """Arm sites from a spec string (tests); ``None``/"" disarms all."""
    global _active
    with _lock:
        _armed.clear()
        if spec:
            _armed.update(parse_spec(spec))
        _active = bool(_armed)


def reset() -> None:
    """Disarm everything and zero hit counters (test isolation)."""
    global _active
    with _lock:
        _armed.clear()
        for site in _declared:
            _declared[site] = 0
        _active = False


def _load_env() -> None:
    # Local import: config is the single home of LO_TPU_* reads
    # (lolint env-discipline), and importing it lazily keeps this
    # module free of package imports at its own import time.
    from learningorchestra_tpu.config import failpoint_spec

    spec = failpoint_spec()
    if spec:
        configure(spec)


def _corrupt_torn(path: str) -> None:
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(max(size // 2, 1))
        f.flush()
        os.fsync(f.fileno())


def _corrupt_bitflip(path: str) -> None:
    size = os.path.getsize(path)
    pos = size // 2
    with open(path, "r+b") as f:
        f.seek(pos)
        byte = f.read(1)
        flipped = bytes([(byte[0] ^ 0x01) if byte else 0x01])
        f.seek(pos)
        f.write(flipped)
        f.flush()
        os.fsync(f.fileno())


def fire(site: str, path: Optional[str] = None) -> None:
    """Hit a failpoint site. No-op (one flag test) unless armed.

    ``path`` names the in-flight file ``torn``/``bitflip`` corrupt; an
    armed file mode at a site that passes no path fires as ``raise``
    instead (a misconfiguration should fail the test loudly, not no-op).
    """
    if not _active:
        return
    with _lock:
        if site in _declared:
            _declared[site] += 1
        armed = _armed.get(site)
        if armed is None or armed.fired:
            return
        armed.hits += 1
        if armed.hits < armed.nth:
            return
        if armed.nth > 0:                 # nth=0 = persistent: every hit
            armed.fired = True
        mode = armed.mode
    if mode == "crash":
        # Skip interpreter teardown entirely — the point is the state
        # the OS sees at this exact syscall boundary.
        os._exit(CRASH_EXIT_CODE)
    if mode == "hang":
        time.sleep(3600.0)
        return
    if mode == "slow":
        time.sleep(SLOW_S)
        return
    if mode in ("torn", "bitflip") and path is not None \
            and os.path.isfile(path):
        (_corrupt_torn if mode == "torn" else _corrupt_bitflip)(path)
        return
    raise FailpointError(f"failpoint fired: {site} ({mode})")


_load_env()
