"""Structured logging — every line stamped with its trace/span ids.

The third leg of the observability plane (docs/observability.md): traces
answer "where did this request spend its time", metrics answer "how is
the fleet doing", and logs carry the narrative — but only if the three
cross-reference. This module makes every log line emitted inside a
traced operation carry that operation's ``trace_id``/``span_id``, so
``grep <trace_id> server.log`` reconstructs a request's story and a log
line's trace is one ``GET /trace/{id}`` away.

Usage: package modules take ``log = structlog.get_logger("spmd")``
(a stdlib logger under the ``lo_tpu`` tree — all the stdlib machinery,
levels, and test caplog integration keep working); entry points call
:func:`configure` once, which installs a single stream handler whose
format follows ``LO_TPU_LOG_FORMAT``:

- ``text`` (default): classic one-liner with `` trace=<id> span=<id>``
  appended when ambient;
- ``json``: one JSON doc per line — ``ts``, ``level``, ``logger``,
  ``msg``, ``trace_id``/``span_id``, ``process``, and ``exc`` on
  exception records — the machine-parseable form log shippers want.

lolint's ``log-discipline`` rule (docs/static_analysis.md) bans bare
``print(`` and root-logger ``logging.*`` calls in package code so
nothing bypasses this funnel.
"""

from __future__ import annotations

import json
import logging
import sys
import time
from typing import IO, Optional

from learningorchestra_tpu.config import Settings, settings as global_settings
from learningorchestra_tpu.utils import tracing

#: Root of the framework's logger tree; every get_logger() name nests
#: under it so one handler + level governs the whole package.
ROOT = "lo_tpu"


def get_logger(name: str) -> logging.Logger:
    """The framework logger for one component: ``get_logger("spmd")`` →
    ``lo_tpu.spmd``. Idempotent with stdlib semantics (same object per
    name)."""
    if name == ROOT or name.startswith(ROOT + "."):
        return logging.getLogger(name)
    return logging.getLogger(f"{ROOT}.{name}")


class JsonFormatter(logging.Formatter):
    """One JSON doc per line; trace ids from the ambient tracing context
    at EMIT time (the log site needs no plumbing)."""

    def format(self, record: logging.LogRecord) -> str:
        doc = {
            "ts": round(record.created, 3),
            "level": record.levelname.lower(),
            "logger": record.name,
            "msg": record.getMessage(),
        }
        ctx = tracing.current()
        if ctx is not None:
            doc["trace_id"] = ctx.trace_id
            doc["span_id"] = ctx.span_id
        if record.exc_info:
            doc["exc"] = self.formatException(record.exc_info)
        return json.dumps(doc, default=str)


class TextFormatter(logging.Formatter):
    """Human-readable one-liner, trace ids appended when ambient so an
    operator can paste the id straight into ``GET /trace/{id}``."""

    def __init__(self):
        super().__init__("%(asctime)s %(name)s %(levelname)s %(message)s")
        self.converter = time.localtime

    def format(self, record: logging.LogRecord) -> str:
        line = super().format(record)
        ctx = tracing.current()
        if ctx is not None:
            line += f" trace={ctx.trace_id} span={ctx.span_id}"
        return line


def configure(cfg: Optional[Settings] = None,
              stream: Optional[IO[str]] = None) -> logging.Logger:
    """Install the ``lo_tpu`` tree's single handler per
    ``LO_TPU_LOG_FORMAT`` / ``LO_TPU_LOG_LEVEL``. Idempotent: re-calls
    replace the handler (tests reconfigure against a StringIO), never
    stack duplicates. Returns the tree root logger."""
    cfg = cfg or global_settings
    root = logging.getLogger(ROOT)
    for h in list(root.handlers):
        root.removeHandler(h)
    handler = logging.StreamHandler(stream if stream is not None
                                    else sys.stderr)
    handler.setFormatter(JsonFormatter()
                         if str(cfg.log_format).lower() == "json"
                         else TextFormatter())
    root.addHandler(handler)
    level = getattr(logging, str(cfg.log_level).upper(), None)
    root.setLevel(level if isinstance(level, int) else logging.INFO)
    #: One funnel: the tree must not double-emit through the stdlib root
    #: logger's handlers (pytest installs its own there).
    root.propagate = False
    return root
