"""Flight recorder — bounded post-incident evidence bundles.

When something goes wrong on a live server, the evidence is spread over
volatile surfaces: the trace ring is evicting, the alert engine's state
moves on, the process may be about to die. The flight recorder freezes
that evidence AT the incident: on an alert transitioning to firing, a
``/healthz`` flip to 503, a dispatcher quarantine, or a supervisor-
observed child death, it dumps a bundle to
``<store_root>/_flightrec/<bundle-id>/``:

- ``manifest.json`` — reason, detail, wall time, versions (python /
  jax / numpy), the full ``Settings`` snapshot, and the alert engine's
  state at the instant of the dump;
- ``spans.json`` — the trace ring's recent spans (the failing request's
  trace included, since the incident just happened);
- ``history.json`` — the surrounding telemetry window
  (``LO_TPU_FLIGHTREC_WINDOW_S`` of utils/timeseries.py series);
- ``resources.json`` — the resource/compile snapshot;
- ``metrics.json`` — the metrics registry document that triggered the
  dump, when the trigger had one in hand.

Retention is bounded (``LO_TPU_FLIGHTREC_KEEP`` newest bundles) and
automatic dumps are rate-limited (``LO_TPU_FLIGHTREC_MIN_INTERVAL_S``)
so a flapping alert records its first transition instead of filling the
disk. ``POST /debug/flightrec`` forces a bundle on demand; ``GET
/debug/flightrec`` lists them. Dumping is best-effort by construction:
a recorder failure logs and returns None — it must never turn an
incident into a second incident.

The module-level :func:`incident` hook lets components that cannot see
the App (the predict batcher's quarantine path, deep library code)
trigger the process's recorder; the supervisor — a separate process
with no App at all — writes manifest-only bundles via
:func:`dump_minimal`.
"""

from __future__ import annotations

import dataclasses
import json
import os
import platform
import re
import shutil
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from learningorchestra_tpu.config import Settings
from learningorchestra_tpu.utils.structlog import get_logger

log = get_logger("flightrec")

_SLUG_RE = re.compile(r"[^a-z0-9._-]+")


def _slug(reason: str) -> str:
    return _SLUG_RE.sub("-", reason.lower()).strip("-")[:48] or "incident"


def _versions() -> Dict[str, Any]:
    doc: Dict[str, Any] = {"python": platform.python_version(),
                           "platform": platform.platform()}
    for mod in ("jax", "numpy"):
        m = sys.modules.get(mod)
        if m is not None:
            doc[mod] = getattr(m, "__version__", "?")
    return doc


def _config_doc(cfg: Settings) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for f in dataclasses.fields(cfg):
        val = getattr(cfg, f.name)
        if isinstance(val, (str, int, float, bool)) or val is None:
            out[f.name] = val
    return out


def bundle_root(store_root: str) -> str:
    return os.path.join(store_root, "_flightrec")


class FlightRecorder:
    """One server process's recorder. ``gather`` maps artifact names to
    thunks producing their JSON payloads (spans, history, resources,
    alerts) — the App wires these so the recorder never imports the
    serving layer."""

    def __init__(self, cfg: Settings,
                 gather: Optional[Dict[str, Callable[[], Any]]] = None):
        self.cfg = cfg
        self.gather = dict(gather or {})
        self._lock = threading.Lock()
        self._last_auto: Optional[float] = None
        self._seq = 0
        self._counters = {"dumped": 0, "suppressed": 0, "errors": 0,
                          "pruned": 0}
        #: Staging dirs of dumps currently being written: two triggers
        #: can dump concurrently (the batcher's quarantine incident and
        #: the alert engine's firing transition race on real servers),
        #: and the completing dump's prune must sweep only ORPHANED
        #: ``.tmp-`` debris, never a live sibling's staging dir.
        self._inflight: set = set()

    @property
    def root(self) -> str:
        return bundle_root(self.cfg.store_root)

    @property
    def enabled(self) -> bool:
        return int(self.cfg.flightrec_keep) > 0

    # -- dumping -------------------------------------------------------------

    def dump(self, reason: str, detail: Any = None,
             doc: Optional[Dict[str, Any]] = None,
             force: bool = False) -> Optional[str]:
        """Write one bundle; returns its id, or None when disabled,
        rate-limited (automatic triggers only), or failed. Never
        raises."""
        if not self.enabled:
            return None
        now = time.time()
        with self._lock:
            if not force and self._last_auto is not None and (
                    now - self._last_auto
                    < float(self.cfg.flightrec_min_interval_s)):
                self._counters["suppressed"] += 1
                return None
            if not force:
                self._last_auto = now
            self._seq += 1
            seq = self._seq
        bundle_id = (time.strftime("%Y%m%d-%H%M%S", time.localtime(now))
                     + f"-{seq:03d}-{_slug(reason)}")
        with self._lock:
            self._inflight.add(f".tmp-{bundle_id}")
        try:
            return self._write(bundle_id, reason, detail, doc, now)
        except Exception as exc:  # noqa: BLE001 — never a second incident
            with self._lock:
                self._counters["errors"] += 1
            log.error("flight-recorder dump failed (%s): %s", reason, exc)
            return None
        finally:
            with self._lock:
                self._inflight.discard(f".tmp-{bundle_id}")

    def _write(self, bundle_id: str, reason: str, detail: Any,
               doc: Optional[Dict[str, Any]], now: float) -> str:
        tmp = os.path.join(self.root, f".tmp-{bundle_id}")
        final = os.path.join(self.root, bundle_id)
        os.makedirs(tmp, exist_ok=True)
        manifest: Dict[str, Any] = {
            "bundle": bundle_id,
            "reason": reason,
            "detail": detail,
            "at": round(now, 3),
            "at_iso": time.strftime("%Y-%m-%dT%H:%M:%S",
                                    time.localtime(now)),
            "versions": _versions(),
            "config": _config_doc(self.cfg),
        }
        artifacts = {"manifest.json": manifest}
        if doc is not None:
            artifacts["metrics.json"] = doc
        for name, thunk in self.gather.items():
            try:
                artifacts[f"{name}.json"] = thunk()
            except Exception as exc:  # noqa: BLE001 — partial bundles win
                artifacts[f"{name}.json"] = {"error": str(exc)}
        for fname, payload in artifacts.items():
            with open(os.path.join(tmp, fname), "w",
                      encoding="utf-8") as f:
                json.dump(payload, f, indent=1, default=str)
        # Staged rename: a bundle either exists completely or not at all
        # (a crash mid-dump leaves only a .tmp- dir the next prune
        # sweeps away).
        os.replace(tmp, final)
        with self._lock:
            self._counters["dumped"] += 1
        log.warning("flight-recorder bundle %s dumped (%s)",
                    bundle_id, reason)
        self._prune()
        return bundle_id

    def _prune(self) -> None:
        try:
            entries = sorted(
                e for e in os.listdir(self.root)
                if os.path.isdir(os.path.join(self.root, e)))
        except OSError:
            return
        keep = max(1, int(self.cfg.flightrec_keep))
        with self._lock:
            inflight = set(self._inflight)
        stale = [e for e in entries
                 if e.startswith(".tmp-") and e not in inflight]
        live = [e for e in entries if not e.startswith(".tmp-")]
        doomed = stale + live[:-keep] if len(live) > keep else stale
        for e in doomed:
            shutil.rmtree(os.path.join(self.root, e), ignore_errors=True)
        if doomed:
            with self._lock:
                self._counters["pruned"] += len(doomed)

    # -- views ---------------------------------------------------------------

    def list(self) -> List[Dict[str, Any]]:
        """Bundle summaries, newest first — the ``GET /debug/flightrec``
        body and the client's ``flight_recordings()``."""
        out: List[Dict[str, Any]] = []
        try:
            entries = sorted(os.listdir(self.root), reverse=True)
        except OSError:
            return out
        for e in entries:
            path = os.path.join(self.root, e)
            if e.startswith(".tmp-") or not os.path.isdir(path):
                continue
            summary: Dict[str, Any] = {"bundle": e, "path": path}
            try:
                with open(os.path.join(path, "manifest.json"),
                          encoding="utf-8") as f:
                    man = json.load(f)
                summary.update({k: man.get(k) for k in
                                ("reason", "at", "at_iso", "detail")})
                summary["files"] = sorted(os.listdir(path))
            except (OSError, ValueError):
                summary["error"] = "unreadable manifest"
            out.append(summary)
        return out

    def _bundle_ids(self) -> List[str]:
        """Bundle ids, newest first (no manifest reads — ids sort by
        their timestamp prefix)."""
        try:
            return sorted(
                (e for e in os.listdir(self.root)
                 if not e.startswith(".tmp-")
                 and os.path.isdir(os.path.join(self.root, e))),
                reverse=True)
        except OSError:
            return []

    def latest(self) -> Optional[str]:
        """Freshest bundle id (the one client error messages quote)."""
        ids = self._bundle_ids()
        return ids[0] if ids else None

    def snapshot(self) -> Dict[str, Any]:
        """The ``flightrec`` section of ``/metrics`` — cheap by design
        (one directory listing, no manifest reads: it runs per
        scrape)."""
        with self._lock:
            doc: Dict[str, Any] = dict(self._counters)
        ids = self._bundle_ids()
        doc["bundles"] = len(ids)
        doc["latest"] = ids[0] if ids else None
        return doc


# -- process-global incident hook ---------------------------------------------

#: The serving process's recorder (set by App). Components below the
#: serving layer (the predict batcher's quarantine path) report
#: incidents through :func:`incident` without importing the App.
_recorder: Optional[FlightRecorder] = None
_recorder_lock = threading.Lock()


def set_recorder(rec: Optional[FlightRecorder]) -> None:
    global _recorder
    with _recorder_lock:
        _recorder = rec


def incident(reason: str, detail: Any = None) -> Optional[str]:
    """Trigger the process recorder (no-op without one). Best-effort
    like every recorder path — callers never guard it."""
    with _recorder_lock:
        rec = _recorder
    if rec is None:
        return None
    return rec.dump(reason, detail=detail)


def dump_minimal(store_root: str, reason: str,
                 detail: Any = None, keep: int = 8) -> Optional[str]:
    """A manifest-only bundle for processes without a recorder (the
    supervisor observing a child death): reason + detail + versions,
    same bundle layout and retention, no in-process telemetry to
    capture."""
    cfg = Settings()
    cfg.store_root = store_root
    cfg.flightrec_keep = keep
    cfg.flightrec_min_interval_s = 0.0
    return FlightRecorder(cfg).dump(reason, detail=detail, force=True)
