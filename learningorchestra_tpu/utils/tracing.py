"""End-to-end request tracing — correlated spans from HTTP to device.

The aggregate tier (``OpTimer`` means/maxes on ``/metrics``) answers
"how slow is this operation on average"; it cannot answer "where did
THIS request/job spend its time" — the blind spot that made the r04/r05
sweep regression a human archaeology job, and exactly the per-stage
attribution tf.data's authors used to find input-pipeline stalls
(PAPERS 2101.12127). The Spark study (PAPERS 1612.01437) shows aggregate
stage timers mis-attribute scheduler/queue time to compute; spans with
parent links are the fix.

Design (stdlib-only, like lolint):

- every HTTP request and async job mints a **trace id** (honoring an
  inbound ``X-Request-Id``); the id flows through contextvars on one
  process, explicitly captured contexts across thread pools
  (``attach``), and the SPMD job-channel spec across processes
  (``to_wire``/``from_wire``) — workers ship their spans back over the
  channel and :func:`ingest` merges them, so ``GET /trace/{id}`` on the
  coordinator shows the whole pod;
- **spans** record name, parent link, monotonic-clock duration, wall
  start, attributes (dataset, model, rows, ...), status, and the
  recording process;
- spans land in a bounded **ring buffer** (``LO_TPU_TRACE_BUFFER_SPANS``,
  FIFO eviction — a long-lived server holds a recent window, never
  leaks); ``GET /traces`` lists recent root spans, ``GET /trace/{id}``
  returns one trace's span tree;
- **sampling** (``LO_TPU_TRACE_SAMPLE``): the record/skip decision is
  made once per trace; unsampled traces still mint + propagate ids (the
  response's ``X-Request-Id`` must always be quotable) but record
  nothing and skip all child-span bookkeeping — the bench's overhead
  A/B flips exactly this knob.

Recording is cheap by construction: one ``os.urandom`` id + a dict and
a deque-append under a short lock per span, no I/O, no serialization
until a ``/traces`` read. The serving hot path adds ~4 spans per traced
request; see bench.py's ``tracing_overhead`` section for the measured
cost.
"""

from __future__ import annotations

import os
import random
import threading
import time
from collections import deque
from contextlib import contextmanager
from contextvars import ContextVar
from typing import Any, Dict, Iterator, List, Optional

__all__ = [
    "TraceContext", "current", "new_id", "trace", "span", "job_trace",
    "attach", "record_span", "to_wire", "from_wire", "ingest",
    "spans_for", "pop_spans", "trace_tree", "recent_traces",
    "counters_snapshot", "attribution_snapshot", "recent_span_docs",
    "reset", "set_sample", "set_capacity", "set_process",
]


class TraceContext:
    """The ambient trace position of the current logical operation:
    which trace, which span is the would-be parent, and whether this
    trace records at all."""

    __slots__ = ("trace_id", "span_id", "sampled")

    def __init__(self, trace_id: str, span_id: str, sampled: bool):
        self.trace_id = trace_id
        self.span_id = span_id
        self.sampled = sampled


class Span:
    __slots__ = ("trace_id", "span_id", "parent_id", "name", "start",
                 "duration_s", "attrs", "status", "error", "process")

    def __init__(self, trace_id: str, span_id: str,
                 parent_id: Optional[str], name: str, start: float,
                 duration_s: float, attrs: Optional[Dict[str, Any]],
                 status: str = "ok", error: Optional[str] = None,
                 process: Optional[int] = None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.start = start
        self.duration_s = duration_s
        self.attrs = attrs
        self.status = status
        self.error = error
        self.process = _process() if process is None else process

    def to_doc(self) -> Dict[str, Any]:
        doc: Dict[str, Any] = {
            "trace_id": self.trace_id, "span_id": self.span_id,
            "parent_id": self.parent_id, "name": self.name,
            "start": round(self.start, 6),
            "duration_ms": round(self.duration_s * 1e3, 3),
            "process": self.process, "status": self.status,
        }
        if self.attrs:
            doc["attrs"] = dict(self.attrs)
        if self.error:
            doc["error"] = self.error
        return doc

    @classmethod
    def from_doc(cls, doc: Dict[str, Any]) -> "Span":
        return cls(str(doc["trace_id"]), str(doc["span_id"]),
                   doc.get("parent_id"), str(doc.get("name", "?")),
                   float(doc.get("start", 0.0)),
                   float(doc.get("duration_ms", 0.0)) / 1e3,
                   doc.get("attrs"), str(doc.get("status", "ok")),
                   doc.get("error"), int(doc.get("process", 0)))


_ctx: "ContextVar[Optional[TraceContext]]" = ContextVar(
    "lo_trace_ctx", default=None)

_lock = threading.Lock()
_spans: "deque[Span]" = deque()
_counters = {"spans_recorded": 0, "spans_dropped": 0, "spans_ingested": 0,
             "traces_started": 0, "traces_unsampled": 0}
#: None = read the knob from config.settings on use; tests/bench pin via
#: set_sample / set_capacity (the readpipe set_cache_budget pattern).
_sample_override: Optional[float] = None
_capacity_override: Optional[int] = None
#: This process's pod rank on recorded spans; workers set it from
#: jax.process_index() at worker-loop entry (env LO_TPU_PROCESS_ID is
#: not required to be set on test rigs).
_process_override: Optional[int] = None


def new_id() -> str:
    """A fresh 64-bit hex id (trace or span)."""
    return os.urandom(8).hex()


def _process() -> int:
    if _process_override is not None:
        return _process_override
    from learningorchestra_tpu import config

    return config.process_id() or 0


def set_process(index: int) -> None:
    """Pin the process rank stamped on this process's spans (worker
    loops call this with ``jax.process_index()``)."""
    global _process_override
    _process_override = int(index)


def _sample_rate() -> float:
    if _sample_override is not None:
        return _sample_override
    from learningorchestra_tpu.config import settings

    return float(settings.trace_sample)


def set_sample(rate: Optional[float]) -> None:
    """Pin the sampling rate (tests, bench A/B); None restores the
    ``LO_TPU_TRACE_SAMPLE`` process default."""
    global _sample_override
    _sample_override = rate


def _capacity() -> int:
    if _capacity_override is not None:
        return _capacity_override
    from learningorchestra_tpu.config import settings

    return int(settings.trace_buffer_spans)


def set_capacity(spans: Optional[int]) -> None:
    """Pin the ring-buffer capacity (tests); None restores the
    ``LO_TPU_TRACE_BUFFER_SPANS`` process default. Shrinking evicts."""
    global _capacity_override
    with _lock:
        _capacity_override = spans
        cap = _capacity()
        while len(_spans) > max(0, cap):
            _spans.popleft()
            _counters["spans_dropped"] += 1


def current() -> Optional[TraceContext]:
    return _ctx.get()


# -- latency attribution ------------------------------------------------------

#: Span names aggregated into the per-model/per-phase histogram table,
#: mapped to the attribute carrying their label. ``fit.<family>.<sub>``
#: names are handled structurally (phase ``fit.<sub>``, label family).
_ATTR_PHASES = {"queue.wait": "model", "dispatch.device": "model",
                "design.build": "model", "batch.coalesce": "model",
                "http.handle": "route"}
#: Cardinality bound on (phase, label) entries: past it, new labels are
#: dropped (counted) instead of letting a scanner of made-up model
#: names grow /metrics without bound — the PR 6 _stats lesson.
_ATTR_MAX_ENTRIES = 512
#: (phase, label) -> {count, total_s, max_s, buckets}. The seam that
#: turns the span taxonomy into "where did the p99 go" without grepping
#: /traces: every recorded span whose name is in the taxonomy ALSO
#: lands in a log-bucketed histogram keyed by phase and model/family.
_attrib: Dict[tuple, Dict[str, Any]] = {}


def _attrib_key(name: str,
                attrs: Optional[Dict[str, Any]]) -> Optional[tuple]:
    label_attr = _ATTR_PHASES.get(name)
    if label_attr is not None:
        label = (attrs or {}).get(label_attr)
        if label:
            return (name, str(label))
        # Only http.handle collapses label-less spans into "-"
        # (unmatched 404s carry no route by design). Model-labeled
        # phases SKIP instead: SPMD workers' job-path dispatch.device
        # spans carry no model, and folding multi-second sweep programs
        # into a "serving" phase would wildly inflate its percentiles.
        return (name, "-") if name == "http.handle" else None
    if name.startswith("fit."):
        parts = name.split(".")
        if len(parts) == 3:                 # fit.<family>.<sub-phase>
            return (f"fit.{parts[2]}", parts[1])
        if len(parts) == 2:                 # fit.<family>
            return ("fit", parts[1])
    return None


def _attrib_observe(span_obj: Span) -> None:
    """Fold one span into the attribution table (caller holds _lock).
    Deliberately independent of ring capacity: a server with span
    retention off still answers the aggregate question."""
    key = _attrib_key(span_obj.name, span_obj.attrs)
    if key is None:
        return
    ent = _attrib.get(key)
    if ent is None:
        if len(_attrib) >= _ATTR_MAX_ENTRIES:
            _counters["attribution_dropped"] = \
                _counters.get("attribution_dropped", 0) + 1
            return
        from learningorchestra_tpu.utils import profiling

        ent = _attrib[key] = {"count": 0, "total_s": 0.0, "max_s": 0.0,
                              "buckets": profiling.new_histogram()}
    from learningorchestra_tpu.utils import profiling

    ent["count"] += 1
    ent["total_s"] += span_obj.duration_s
    ent["max_s"] = max(ent["max_s"], span_obj.duration_s)
    profiling.observe(ent["buckets"], span_obj.duration_s)


def attribution_snapshot() -> Dict[str, Dict[str, Any]]:
    """The ``latency_attribution`` section of ``/metrics``: per-phase,
    per-model (or per-family, per-route) latency histograms aggregated
    from the span taxonomy — ``queue.wait`` / ``dispatch.device`` /
    ``design.build`` / ``batch.coalesce`` by model, ``fit.*`` by
    family, ``http.handle`` by route. Derived from SAMPLED spans, so
    under ``LO_TPU_TRACE_SAMPLE<1`` it attributes the sampled subset."""
    from learningorchestra_tpu.utils import profiling

    with _lock:
        items = [(k, dict(v, buckets=list(v["buckets"])))
                 for k, v in _attrib.items()]
    out: Dict[str, Dict[str, Any]] = {}
    for (phase, label), ent in sorted(items):
        p50 = profiling.quantile_from_buckets(ent["buckets"], 0.50)
        p99 = profiling.quantile_from_buckets(ent["buckets"], 0.99)
        out.setdefault(phase, {})[label] = {
            "count": ent["count"],
            "total_s": round(ent["total_s"], 6),
            "max_s": round(ent["max_s"], 6),
            "mean_ms": round(ent["total_s"] / ent["count"] * 1e3, 3),
            "p50_ms": None if p50 is None else round(p50 * 1e3, 3),
            "p99_ms": None if p99 is None else round(p99 * 1e3, 3),
            "buckets": ent["buckets"],
        }
    return out


def _record(span_obj: Span, ingested: bool = False) -> None:
    with _lock:
        cap = _capacity()
        _counters["spans_ingested" if ingested else "spans_recorded"] += 1
        _attrib_observe(span_obj)
        if cap <= 0:
            _counters["spans_dropped"] += 1
            return
        while len(_spans) >= cap:
            _spans.popleft()
            _counters["spans_dropped"] += 1
        _spans.append(span_obj)


@contextmanager
def trace(name: str, trace_id: Optional[str] = None,
          attrs: Optional[Dict[str, Any]] = None,
          sampled: Optional[bool] = None) -> Iterator[TraceContext]:
    """Open a ROOT span and make its trace the ambient context. The
    ``attrs`` dict is recorded by reference at exit, so callers may keep
    mutating it inside the block (e.g. stamping the HTTP status late).
    An exception escaping the block records the span with
    ``status="error"`` and re-raises."""
    if sampled is None:
        rate = _sample_rate()
        sampled = rate >= 1.0 or (rate > 0.0 and random.random() < rate)
    ctx = TraceContext(trace_id or new_id(), new_id(), sampled)
    with _lock:
        _counters["traces_started"] += 1
        if not sampled:
            _counters["traces_unsampled"] += 1
    token = _ctx.set(ctx)
    t0 = time.monotonic()
    t_wall = time.time()
    status, err = "ok", None
    try:
        yield ctx
    except BaseException as exc:
        status, err = "error", f"{type(exc).__name__}: {exc}"
        raise
    finally:
        _ctx.reset(token)
        if sampled:
            _record(Span(ctx.trace_id, ctx.span_id, None, name, t_wall,
                         time.monotonic() - t0, attrs, status, err))


@contextmanager
def span(name: str, attrs: Optional[Dict[str, Any]] = None,
         **kw: Any) -> Iterator[Optional[TraceContext]]:
    """Open a child span under the ambient trace. No ambient trace (or
    an unsampled one) ⇒ near-zero-cost no-op — instrumented code needs
    no guards. ``attrs``/keyword attrs merge; the dict is recorded by
    reference so the block may keep filling it in."""
    parent = _ctx.get()
    if parent is None or not parent.sampled:
        yield parent
        return
    if kw:
        attrs = {**(attrs or {}), **kw}
    ctx = TraceContext(parent.trace_id, new_id(), True)
    token = _ctx.set(ctx)
    t0 = time.monotonic()
    t_wall = time.time()
    status, err = "ok", None
    try:
        yield ctx
    except BaseException as exc:
        status, err = "error", f"{type(exc).__name__}: {exc}"
        raise
    finally:
        _ctx.reset(token)
        _record(Span(ctx.trace_id, ctx.span_id, parent.span_id, name,
                     t_wall, time.monotonic() - t0, attrs, status, err))


@contextmanager
def job_trace(name: str, trace_id: Optional[str] = None,
              parent: Optional[TraceContext] = None,
              attrs: Optional[Dict[str, Any]] = None
              ) -> Iterator[Optional[TraceContext]]:
    """An async job's root scope: when the submitting request's context
    was captured, the job's span joins THAT trace (one trace spans HTTP
    accept → job completion); otherwise the job becomes a trace of its
    own under ``trace_id`` (internal submissions: retries, resumed
    ingests)."""
    if parent is not None:
        with attach(parent), span(name, attrs=attrs) as ctx:
            yield ctx
    else:
        with trace(name, trace_id=trace_id, attrs=attrs) as ctx:
            yield ctx


@contextmanager
def attach(ctx: Optional[TraceContext]) -> Iterator[Optional[TraceContext]]:
    """Make an explicitly captured context ambient on this thread — how
    trace position crosses thread pools (builder fit threads, job
    workers) and, via the wire form, processes."""
    if ctx is None:
        yield None
        return
    token = _ctx.set(ctx)
    try:
        yield ctx
    finally:
        _ctx.reset(token)


def record_span(name: str, duration_s: float, *,
                ctx: Optional[TraceContext] = None,
                parent_id: Optional[str] = None,
                span_id: Optional[str] = None,
                t_wall: Optional[float] = None,
                attrs: Optional[Dict[str, Any]] = None,
                status: str = "ok",
                error: Optional[str] = None) -> Optional[str]:
    """Record a span with an EXACT externally measured duration — how
    instrumentation points that already time themselves (``device_span``,
    the batcher's queue-wait bookkeeping) emit spans that agree with
    their metrics to the digit. Returns the span id, or None when the
    (explicit or ambient) context is absent/unsampled.

    ``parent_id=""`` records a ROOT span (parent None) — how the
    front-end worker's event loop emits its ``http.handle`` root after
    the fact (an async request has no enclosing ``with trace(...)``
    frame to root it)."""
    c = ctx if ctx is not None else _ctx.get()
    if c is None or not c.sampled:
        return None
    sid = span_id or new_id()
    pid: Optional[str] = (parent_id if parent_id is not None
                          else c.span_id)
    if pid == "":
        pid = None
    _record(Span(c.trace_id, sid,
                 pid,
                 name,
                 t_wall if t_wall is not None else time.time() - duration_s,
                 duration_s, attrs, status, error))
    return sid


# -- cross-process propagation ------------------------------------------------

def to_wire(ctx: Optional[TraceContext] = None) -> Optional[Dict[str, Any]]:
    """The JSON-safe carrier stamped onto SPMD job specs."""
    c = ctx if ctx is not None else _ctx.get()
    if c is None:
        return None
    return {"trace_id": c.trace_id, "span_id": c.span_id,
            "sampled": bool(c.sampled)}


def from_wire(doc: Optional[Dict[str, Any]]) -> Optional[TraceContext]:
    if not isinstance(doc, dict) or "trace_id" not in doc:
        return None
    return TraceContext(str(doc["trace_id"]),
                        str(doc.get("span_id") or new_id()),
                        bool(doc.get("sampled", True)))


def ingest(docs: List[Dict[str, Any]]) -> int:
    """Merge span docs recorded by ANOTHER process (workers ship theirs
    over the job channel after each dispatched job) into this buffer, so
    the coordinator's ``GET /trace/{id}`` covers the whole pod. Returns
    how many were accepted."""
    n = 0
    for doc in docs:
        try:
            s = Span.from_doc(doc)
        except (KeyError, TypeError, ValueError):
            continue
        _record(s, ingested=True)
        n += 1
    return n


# -- queries ------------------------------------------------------------------

def _snapshot() -> List[Span]:
    with _lock:
        return list(_spans)


def spans_for(trace_id: str) -> List[Dict[str, Any]]:
    """All buffered spans of one trace, as docs, sorted by start time —
    the flat list ``/trace/{id}`` serves."""
    spans = [s for s in _snapshot() if s.trace_id == trace_id]
    spans.sort(key=lambda s: s.start)
    return [s.to_doc() for s in spans]


def pop_spans(trace_id: str) -> List[Dict[str, Any]]:
    """Remove and return one trace's spans (start-ordered docs) — the
    wire form SPMD workers ship to the coordinator. Popping (not
    copying) means a trace that dispatches several jobs never re-ships
    an earlier job's spans, and worker buffers stay lean."""
    with _lock:
        keep, out = deque(), []
        for s in _spans:
            (out if s.trace_id == trace_id else keep).append(s)
        _spans.clear()
        _spans.extend(keep)
    out.sort(key=lambda s: s.start)
    return [s.to_doc() for s in out]


def trace_tree(trace_id: str) -> Optional[Dict[str, Any]]:
    """One trace's span tree: flat ``spans`` (start-ordered) plus nested
    ``roots`` where each span doc carries its ``children``. Spans whose
    parent was evicted (or lives only on a process whose spans never
    merged) surface as roots rather than disappearing."""
    docs = spans_for(trace_id)
    if not docs:
        return None
    # Dedupe by span id (a worker shipment that merged twice — late
    # drain + next-round ack path — must not double nodes).
    seen_ids: set = set()
    docs = [d for d in docs
            if d["span_id"] not in seen_ids
            and not seen_ids.add(d["span_id"])]
    by_id = {d["span_id"]: dict(d, children=[]) for d in docs}
    roots = []
    for d in docs:
        node = by_id[d["span_id"]]
        parent = d.get("parent_id")
        if parent and parent in by_id and parent != d["span_id"]:
            by_id[parent]["children"].append(node)
        else:
            roots.append(node)
    start = min(d["start"] for d in docs)
    end = max(d["start"] + d["duration_ms"] / 1e3 for d in docs)
    return {
        "trace_id": trace_id,
        "span_count": len(docs),
        "processes": sorted({d["process"] for d in docs}),
        "start": round(start, 6),
        "duration_ms": round((end - start) * 1e3, 3),
        "spans": docs,
        "roots": roots,
    }


def recent_traces(route: Optional[str] = None, kind: Optional[str] = None,
                  min_ms: Optional[float] = None,
                  limit: int = 50) -> List[Dict[str, Any]]:
    """Recent traces (newest first), one summary per trace id. The
    summary is the trace's root span (parent-less; earliest span when
    the root was evicted) plus the trace's span count, full wall extent
    (``duration_ms`` — an async job trace is as long as its job, not its
    201 response), and the ``kinds`` of any job spans it contains.

    ``route`` filters on the root's ``route`` attribute (HTTP traces);
    ``kind`` matches the trace's job kinds — async jobs JOIN their
    submitting request's trace, so the sweep you're hunting is a child
    span, not a root; ``min_ms`` filters on the trace extent — the
    "show me every slow sweep" query."""
    groups: Dict[str, List[Span]] = {}
    for s in _snapshot():
        groups.setdefault(s.trace_id, []).append(s)
    out: List[Dict[str, Any]] = []
    for _tid, spans in sorted(groups.items(),
                              key=lambda kv: -max(s.start
                                                  for s in kv[1])):
        root = next((s for s in spans if s.parent_id is None),
                    min(spans, key=lambda s: s.start))
        attrs = root.attrs or {}
        kinds = sorted({str((s.attrs or {}).get("kind", ""))
                        for s in spans if s.name.startswith("job.")} - {""})
        extent_ms = (max(s.start + s.duration_s for s in spans)
                     - min(s.start for s in spans)) * 1e3
        if route is not None and route not in str(attrs.get("route", "")) \
                and route not in str(attrs.get("path", "")):
            # "route" is the matched route PATTERN on HTTP spans (one
            # label per route); "path" keeps the concrete URL, so both
            # "/files/{name}" and "/files/my_dataset" filters work.
            continue
        if kind is not None and kind not in kinds \
                and kind not in root.name:
            continue
        if min_ms is not None and extent_ms < min_ms:
            continue
        doc = root.to_doc()
        doc["spans"] = len(spans)
        doc["duration_ms"] = round(extent_ms, 3)
        if kinds:
            doc["kinds"] = kinds
        out.append(doc)
        if len(out) >= max(1, limit):
            break
    return out


def recent_span_docs(limit: Optional[int] = None) -> List[Dict[str, Any]]:
    """The newest ``limit`` buffered spans as docs (buffer order =
    completion order) — what the flight recorder freezes into a
    bundle's ``spans.json``."""
    spans = _snapshot()
    if limit is not None and len(spans) > limit:
        spans = spans[-limit:]
    return [s.to_doc() for s in spans]


def counters_snapshot() -> Dict[str, Any]:
    """Tracing's own health counters for ``/metrics``."""
    with _lock:
        out: Dict[str, Any] = dict(_counters)
        out["buffer_spans"] = len(_spans)
        out["buffer_capacity"] = _capacity()
        return out


def reset() -> None:
    """Drop every span, the attribution table, and zero counters (test
    isolation)."""
    with _lock:
        _spans.clear()
        _attrib.clear()
        for k in _counters:
            _counters[k] = 0
