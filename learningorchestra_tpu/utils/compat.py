"""JAX version compatibility shims.

The framework targets the modern ``jax.shard_map`` API (top-level export,
``check_vma`` kwarg). Older runtimes (jax < 0.5) ship the same machinery
as ``jax.experimental.shard_map.shard_map`` with the ``check_rep`` kwarg.
Rather than pinning a floor version (the container environment is fixed —
see the no-new-deps constraint), this module adapts at import time so
every kernel and mesh op runs unchanged on either runtime. Imported for
its side effect by ``parallel/__init__`` — the gateway every compute
module loads through — so jax-free entry points (the pod supervisor, the
client SDK) never pay the jax import.
"""

from __future__ import annotations

import jax

if not hasattr(jax, "shard_map"):
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, mesh=None, in_specs=None, out_specs=None,
                  check_vma=None, **kw):
        if check_vma is not None and "check_rep" not in kw:
            kw["check_rep"] = check_vma
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, **kw)

    jax.shard_map = shard_map
