"""Prometheus text exposition (version 0.0.4) for ``GET /metrics``.

One registry, two formats: the JSON ``/metrics`` document (op timer,
jobs, read pipeline, serving, integrity, tracing) is ALSO rendered as
Prometheus exposition text when the scrape asks for
``?format=prometheus`` — generated from the identical snapshot, so the
two views can never disagree. stdlib-only renderer; no client library.

Mapping conventions:

- ``ops`` entries → ``lo_op_seconds`` histograms labeled ``op=...``
  (cumulative ``_bucket`` series over the shared
  :data:`~learningorchestra_tpu.utils.profiling.BUCKETS_S` ladder, plus
  ``_sum``/``_count``) and a ``lo_op_max_seconds`` gauge;
- ``jobs`` → ``lo_jobs{status=...}`` gauge;
- ``read_pipeline`` / ``integrity`` / ``tracing`` counters →
  ``lo_read_pipeline_*`` / ``lo_integrity_*`` / ``lo_trace_*``;
- ``serving`` per-model counters → ``lo_serving_*_total{model=...}``,
  live gauges (``queue_rows``, ``qps``), and the request-latency
  histogram ``lo_serving_latency_seconds{model=...}`` — the log-bucketed
  histogram that replaced the old rolling-sample p50/p99 (the JSON
  view's ``p50_ms``/``p99_ms`` are estimated from the same buckets);
- ``resources`` → ``lo_resource_*`` gauges: host RSS/fds/threads,
  per-device HBM (``{device=...}`` where the backend reports it, plus
  process totals), and chunk-store disk usage/free (``{root=...}``);
- ``compile`` → ``lo_compile_*`` counters (backend compiles = cache
  misses, cumulative compile seconds, cache hits);
- ``alerts`` → ``lo_alert_firing{alert=...}`` 0/1 gauges with
  ``lo_alert_value``/``lo_alert_threshold`` next to them, plus engine
  counters; ``pod`` → ``lo_pod_degraded``;
- ``latency_attribution`` (the span-taxonomy aggregation,
  utils/tracing.py) → ``lo_phase_seconds{phase=...,label=...}``
  histograms — queue wait / device dispatch / design build per model,
  fit sub-phases per family, handling per route;
- ``telemetry`` (utils/timeseries.py) → ``lo_telemetry_*`` gauges;
  ``flightrec`` (utils/flightrec.py) → ``lo_flightrec_*`` counters.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

from learningorchestra_tpu.utils.profiling import BUCKETS_S

_COUNTER = "counter"
_GAUGE = "gauge"
_HISTOGRAM = "histogram"


def _esc(value: Any) -> str:
    """Escape a label value per the exposition format."""
    return (str(value).replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _fmt(value: Any) -> str:
    """Render a sample value; integers stay integral for readability."""
    f = float(value)
    return str(int(f)) if f.is_integer() and abs(f) < 1e15 else repr(f)


def _labels(labels: Optional[Dict[str, Any]]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_esc(v)}"' for k, v in labels.items())
    return "{" + inner + "}"


class _Writer:
    def __init__(self):
        self.lines: List[str] = []
        self._typed: set = set()

    def header(self, name: str, mtype: str, help_text: str) -> None:
        if name in self._typed:
            return
        self._typed.add(name)
        self.lines.append(f"# HELP {name} {help_text}")
        self.lines.append(f"# TYPE {name} {mtype}")

    def sample(self, name: str, labels: Optional[Dict[str, Any]],
               value: Any) -> None:
        self.lines.append(f"{name}{_labels(labels)} {_fmt(value)}")

    def histogram(self, name: str, labels: Dict[str, Any],
                  buckets: Sequence[int], total_s: float,
                  count: int) -> None:
        """Cumulative ``_bucket`` series from non-cumulative counts."""
        cum = 0
        for bound, c in zip(BUCKETS_S, buckets):
            cum += c
            self.sample(f"{name}_bucket", {**labels, "le": repr(bound)},
                        cum)
        self.sample(f"{name}_bucket", {**labels, "le": "+Inf"}, count)
        self.sample(f"{name}_sum", labels, total_s)
        self.sample(f"{name}_count", labels, count)

    def text(self) -> str:
        return "\n".join(self.lines) + "\n"


def _flat_counters(w: _Writer, prefix: str, doc: Dict[str, Any],
                   mtype: str, help_text: str) -> None:
    for key, val in sorted(doc.items()):
        if not isinstance(val, (int, float)) or isinstance(val, bool):
            continue
        name = f"{prefix}_{key}"
        w.header(name, mtype, f"{help_text} ({key})")
        w.sample(name, None, val)


def render(doc: Dict[str, Any]) -> str:
    """The exposition text for one ``/metrics`` JSON document."""
    w = _Writer()

    ops = doc.get("ops") or {}
    if ops:
        w.header("lo_op_seconds", _HISTOGRAM,
                 "Wall-clock of framework operations by op name")
        for op, s in sorted(ops.items()):
            buckets = s.get("buckets")
            if buckets is None:
                continue
            w.histogram("lo_op_seconds", {"op": op}, buckets,
                        s.get("total_s", 0.0), s.get("count", 0))
        w.header("lo_op_max_seconds", _GAUGE,
                 "Max observed wall-clock per op name")
        for op, s in sorted(ops.items()):
            w.sample("lo_op_max_seconds", {"op": op}, s.get("max_s", 0.0))

    jobs = doc.get("jobs") or {}
    if jobs:
        w.header("lo_jobs", _GAUGE, "Job records by status")
        for status, n in sorted(jobs.items()):
            w.sample("lo_jobs", {"status": status}, n)

    fault = doc.get("job_fault") or {}
    if fault:
        w.header("lo_job_watchdog_fired_total", _COUNTER,
                 "Jobs killed by the liveness watchdog (no progress "
                 "past LO_TPU_JOB_DEADLINE_S — hung device program)")
        w.sample("lo_job_watchdog_fired_total", None,
                 fault.get("watchdog_fired_total", 0))
        w.header("lo_jobs_resumed_total", _COUNTER,
                 "Fits resumed from a mid-fit checkpoint instead of "
                 "restarting from scratch")
        w.sample("lo_jobs_resumed_total", None,
                 fault.get("jobs_resumed_total", 0))

    fck = doc.get("fit_checkpoints") or {}
    if fck:
        w.header("lo_fit_checkpoint_bytes", _GAUGE,
                 "Bytes of fit-progress checkpoints under "
                 "<store_root>/_fitckpt")
        w.sample("lo_fit_checkpoint_bytes", None, fck.get("bytes", 0))
        w.header("lo_fit_checkpoint_files", _GAUGE,
                 "Checkpoint payload/sidecar files on disk")
        w.sample("lo_fit_checkpoint_files", None, fck.get("files", 0))
        for key in ("writes", "resumes", "discarded"):
            name = f"lo_fit_checkpoint_{key}_total"
            w.header(name, _COUNTER,
                     f"Fit-checkpoint store {key} this process")
            w.sample(name, None, fck.get(key, 0))

    for section, prefix, mtype, help_text in (
            ("read_pipeline", "lo_read_pipeline", _COUNTER,
             "Chunk-read pipeline counter"),
            ("tune", "lo_tune", _COUNTER,
             "Hyperparameter-search plane counter"),
            ("integrity", "lo_integrity", _COUNTER,
             "Data-plane integrity counter"),
            ("ingest", "lo_ingest", _COUNTER,
             "Range-partitioned ingest plane counter"),
            # Mixed live values (buffer occupancy) and monotone totals:
            # gauge is the honest common type.
            ("tracing", "lo_trace", _GAUGE, "Tracing subsystem metric")):
        sec = doc.get(section) or {}
        if sec:
            _flat_counters(w, prefix, sec, mtype, help_text)

    shard = doc.get("shard") or {}
    if shard:
        for key in ("local_reads", "remote_reads"):
            name = f"lo_shard_{key}_total"
            w.header(name, _COUNTER,
                     f"Shard-placement planner {key.replace('_', ' ')} "
                     "(rows of shard_chunked feed classified against the "
                     "dataset shard map)")
            w.sample(name, None, shard.get(key, 0))

    rep = doc.get("replication") or {}
    if rep.get("enabled"):
        for key in ("pushes", "push_bytes", "fetches", "repairs",
                    "errors"):
            name = f"lo_replica_{key}_total"
            w.header(name, _COUNTER,
                     f"Peer replication plane {key} this process")
            w.sample(name, None, (rep.get("counters") or {}).get(key, 0))
        w.header("lo_replica_lag_bytes", _GAUGE,
                 "Journal bytes committed locally but not yet acked by "
                 "the worst-lagging peer, per dataset")
        for dname, d in sorted((rep.get("datasets") or {}).items()):
            w.sample("lo_replica_lag_bytes", {"dataset": dname},
                     d.get("lag_bytes", 0))
        w.header("lo_replica_under_replicated", _GAUGE,
                 "(dataset, peer) pairs with replication lag and a "
                 "failed last push")
        w.sample("lo_replica_under_replicated", None,
                 len(rep.get("under_replicated") or []))
        w.header("lo_replica_peers", _GAUGE,
                 "Configured peer replica targets")
        w.sample("lo_replica_peers", None, len(rep.get("peers") or []))

    serving = doc.get("serving") or {}
    models = serving.get("models") or {}
    if models:
        for key in ("requests", "rows", "batches", "batched_rows",
                    "rejected", "timeouts", "errors", "deadline_exceeded",
                    "dispatcher_restarts"):
            name = f"lo_serving_{key}_total"
            w.header(name, _COUNTER,
                     f"Online predict tier {key} per model")
            for model, m in sorted(models.items()):
                w.sample(name, {"model": model}, m.get(key, 0))
        # quarantined is a LEVEL (0/1 per model), not a monotone count.
        w.header("lo_serving_quarantined", _GAUGE,
                 "1 while the model is quarantined (dispatcher crashed "
                 "past its threshold; predicts answer a terminal 503)")
        for model, m in sorted(models.items()):
            w.sample("lo_serving_quarantined", {"model": model},
                     m.get("quarantined", 0))
        for key in ("queue_rows", "qps", "mean_batch_rows"):
            name = f"lo_serving_{key}"
            w.header(name, _GAUGE,
                     f"Online predict tier live {key} per model")
            for model, m in sorted(models.items()):
                w.sample(name, {"model": model}, m.get(key) or 0)
        w.header("lo_serving_latency_seconds", _HISTOGRAM,
                 "End-to-end online predict latency per model")
        for model, m in sorted(models.items()):
            hist = m.get("latency") or {}
            buckets = hist.get("buckets")
            if buckets is None:
                continue
            w.histogram("lo_serving_latency_seconds", {"model": model},
                        buckets, hist.get("sum_s", 0.0),
                        m.get("requests", 0))
        # Per-replica plane (serve_replicas): each replica's dispatcher
        # occupancy, routing inputs, and health, labeled
        # {model=...,replica=...}. Rendered for every topology — at
        # replicas=1 the single replica-0 row equals the model row.
        for key in ("batches", "batched_rows", "dispatcher_restarts"):
            name = f"lo_serving_replica_{key}_total"
            w.header(name, _COUNTER,
                     f"Online predict tier {key} per device replica")
            for model, m in sorted(models.items()):
                for r in m.get("replicas") or []:
                    w.sample(name,
                             {"model": model, "replica": r["replica"]},
                             r.get(key, 0))
        for key in ("queue_rows", "qps", "service_us_per_row",
                    "mean_batch_rows"):
            name = f"lo_serving_replica_{key}"
            w.header(name, _GAUGE,
                     f"Online predict tier live {key} per device replica "
                     "(the router's cost inputs)")
            for model, m in sorted(models.items()):
                for r in m.get("replicas") or []:
                    w.sample(name,
                             {"model": model, "replica": r["replica"]},
                             r.get(key) or 0)
        w.header("lo_serving_replica_quarantined", _GAUGE,
                 "1 while this device replica is quarantined (its "
                 "siblings keep serving; the model-level gauge only "
                 "rises when every replica is down)")
        for model, m in sorted(models.items()):
            for r in m.get("replicas") or []:
                w.sample("lo_serving_replica_quarantined",
                         {"model": model, "replica": r["replica"]},
                         r.get("quarantined", 0))
    aot = serving.get("aot") or {}
    if aot:
        _flat_counters(w, "lo_serving_aot", aot, _COUNTER,
                       "AOT predict-program cache counter")

    frontend = doc.get("frontend") or {}
    if frontend:
        # Multi-worker front end (LO_TPU_HTTP_WORKERS > 1): accept-
        # process liveness + respawns and row-channel frame counters.
        # Gauge is the honest common type — live worker counts sit next
        # to monotone frame totals.
        _flat_counters(w, "lo_frontend", frontend, _GAUGE,
                       "Multi-worker serving front end metric")

    res = doc.get("resources") or {}
    host = res.get("host") or {}
    if host:
        _flat_counters(w, "lo_resource_host", host, _GAUGE,
                       "Host process resource gauge")
    devices = res.get("devices") or {}
    if devices:
        for key in ("total_bytes_in_use", "peak_bytes_in_use"):
            val = devices.get(key)
            if isinstance(val, (int, float)):
                name = f"lo_resource_device_{key}"
                w.header(name, _GAUGE,
                         f"Device memory across local devices ({key})")
                w.sample(name, None, val)
        for dev in devices.get("devices") or []:
            for key in ("bytes_in_use", "peak_bytes_in_use",
                        "bytes_limit"):
                val = dev.get(key)
                if isinstance(val, (int, float)):
                    name = f"lo_resource_device_{key}_by_device"
                    w.header(name, _GAUGE,
                             f"Per-device memory gauge ({key})")
                    w.sample(name, {"device": dev.get("id", "?")}, val)
    disk = res.get("disk") or {}
    if disk:
        for key in ("total_bytes", "free_bytes", "used_bytes",
                    "store_bytes"):
            val = disk.get(key)
            if isinstance(val, (int, float)):
                name = f"lo_resource_disk_{key}"
                w.header(name, _GAUGE,
                         f"Chunk-store filesystem gauge ({key})")
                w.sample(name, {"root": disk.get("root", "?")}, val)

    comp = doc.get("compile") or {}
    if comp:
        _flat_counters(w, "lo_compile", comp, _COUNTER,
                       "XLA compile accounting counter")

    attrib = doc.get("latency_attribution") or {}
    if attrib:
        w.header("lo_phase_seconds", _HISTOGRAM,
                 "Latency attributed per phase of the span taxonomy "
                 "(queue wait / device dispatch / design build per "
                 "model, fit sub-phases per family, handling per route)")
        for phase, labels in sorted(attrib.items()):
            for label, ent in sorted(labels.items()):
                buckets = ent.get("buckets")
                if buckets is None:
                    continue
                w.histogram("lo_phase_seconds",
                            {"phase": phase, "label": label}, buckets,
                            ent.get("total_s", 0.0), ent.get("count", 0))

    tele = doc.get("telemetry") or {}
    if tele:
        # Mixed live values (ring occupancy) and monotone totals:
        # gauge is the honest common type, like lo_trace_*.
        _flat_counters(w, "lo_telemetry", tele, _GAUGE,
                       "Telemetry history store metric")
    rec = doc.get("flightrec") or {}
    if rec:
        _flat_counters(w, "lo_flightrec", rec, _GAUGE,
                       "Flight recorder metric")

    pod = doc.get("pod") or {}
    if pod:
        w.header("lo_pod_degraded", _GAUGE,
                 "1 while the pod is degraded (worker death pending "
                 "supervisor restart)")
        w.sample("lo_pod_degraded", None,
                 1 if pod.get("degraded") else 0)

    al = doc.get("alerts") or {}
    rules = al.get("rules") or {}
    if rules:
        w.header("lo_alert_firing", _GAUGE,
                 "1 while the named alert rule is firing")
        for name, r in sorted(rules.items()):
            w.sample("lo_alert_firing", {"alert": name},
                     1 if r.get("firing") else 0)
        w.header("lo_alert_value", _GAUGE,
                 "Last evaluated value of the named alert rule")
        for name, r in sorted(rules.items()):
            if isinstance(r.get("value"), (int, float)):
                w.sample("lo_alert_value", {"alert": name}, r["value"])
        w.header("lo_alert_threshold", _GAUGE,
                 "Configured threshold of the named alert rule")
        for name, r in sorted(rules.items()):
            w.sample("lo_alert_threshold", {"alert": name},
                     r.get("threshold", 0))
        _flat_counters(
            w, "lo_alert", {k: al[k] for k in
                            ("evaluations", "fired_total",
                             "resolved_total") if k in al},
            _COUNTER, "Alert engine counter")

    return w.text()
