"""Resource accounting — the capacity leg of the observability plane.

The tracing plane (PR 9) answers *where time went*; nothing answered
*what resources a job consumed* or *how much headroom the host has* —
yet cost-based packing (ROADMAP 5) needs per-job device-memory and
compile-time profiles as its cost inputs, and multi-host shard placement
(ROADMAP 3) needs disk/host capacity signals. This module is the one
sampling seam every surface reads from:

- **Device HBM**: per-device ``Device.memory_stats()`` where the backend
  provides it (TPU/GPU: ``bytes_in_use`` / ``peak_bytes_in_use``), with
  a live-buffer fallback (sum of ``jax.live_arrays()`` byte sizes) on
  backends that return nothing (the CPU test rig) — so ``source`` in the
  snapshot says which number you are reading.
- **Host**: RSS/VMS from ``/proc/self/statm``, open-fd and thread counts
  from ``/proc/self`` — the signals that catch fd leaks and host-RAM
  creep before the OOM killer does.
- **Disk**: filesystem totals via ``shutil.disk_usage(store_root)`` plus
  a per-dataset byte breakdown of the chunk store (TTL-cached — walking
  a terabyte store per scrape would be its own regression).
- **XLA compile time**: a ``jax.monitoring`` duration listener
  accumulates every real backend compile in this process
  (``backend_compile_duration`` fires only on actual compiles — a warm
  program fires nothing), so ``compile_s`` / ``compiles`` are exact
  without wrapping every jit call site. Cache *hits* are counted at the
  seams that know them: the AOT predict-program cache
  (models/aot.py) and device phases that complete without a single new
  compile (a warm fit program).

Job watermarks: :class:`job_phase` (wrapped around every managed job's
body by jobs.JobManager) and :class:`family_phase` / the ``device_span``
hook (models/builder.py, utils/profiling.py) sample compile-seconds,
RSS, and device bytes around compute phases and merge them into the
current job's profile — ``peak_hbm_bytes`` (max), ``compile_s`` (the
job window's compile total), ``host_rss_delta``, and per-family
``fit_resources`` on sweeps. SPMD workers sample the same way around
their dispatched device ops and ship the watermarks back over the job
channel with their spans (parallel/spmd.py), so the coordinator's job
profile covers the pod and ``GET /cluster`` can show every process's
last-known snapshot.

Counters are process-global (one server process = one metrics surface,
the OpTimer convention); concurrent jobs' compile windows overlap, so a
job's ``compile_s`` reads "compile seconds this process spent during the
job's window" — exact when jobs serialize (the bench, the SPMD dispatch
guard), an honest upper bound when they overlap.
"""

from __future__ import annotations

import os
import shutil
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, Optional

from learningorchestra_tpu.config import Settings, settings as global_settings
from learningorchestra_tpu.utils.structlog import get_logger

log = get_logger("resources")

_lock = threading.Lock()

# -- XLA compile accounting ---------------------------------------------------

#: Cumulative compile counters, fed by the jax.monitoring listener
#: (misses = real backend compiles) and by the cache seams that know
#: their hits (AotCache, warm device phases).
_compile = {"compiles": 0, "compile_s": 0.0, "cache_hits": 0,
            "persistent_cache_hits": 0}
#: One registration attempt per process (claimed under _lock); _listener_ok
#: records whether it succeeded — a concurrent caller racing the attempt
#: reads False until the registering thread publishes the outcome.
_listener_installed = False
_listener_ok = False


def _on_duration(event: str, duration: float, **_kw: Any) -> None:
    if event.endswith("backend_compile_duration"):
        with _lock:
            _compile["compiles"] += 1
            _compile["compile_s"] += float(duration)


def _on_event(event: str, **_kw: Any) -> None:
    if "cache_hit" in event:
        with _lock:
            _compile["persistent_cache_hits"] += 1


def ensure_listener() -> bool:
    """Install the jax.monitoring compile listener once per process.
    Returns False (and accounts nothing) on jax builds without the
    monitoring API — every reader treats the counters as best-effort.

    Exactly ONE registration attempt per process, decided under the
    lock: jax.monitoring has no unregister, so two concurrent first
    callers must not both register (every compile would count twice
    forever), and a failed attempt must not be retried by a later
    caller (a partial registration would double the half that
    succeeded)."""
    global _listener_installed, _listener_ok
    with _lock:
        if _listener_installed:
            return _listener_ok
        _listener_installed = True     # claim the one attempt
    ok = True
    try:
        import jax.monitoring as monitoring

        monitoring.register_event_duration_secs_listener(_on_duration)
        monitoring.register_event_listener(_on_event)
    except Exception as exc:  # noqa: BLE001 — degrade, don't break fits
        log.warning("compile accounting unavailable: %s", exc)
        ok = False
    with _lock:
        _listener_ok = ok
    return ok


def compile_seconds() -> float:
    ensure_listener()
    with _lock:
        return _compile["compile_s"]


def note_cache_hit(n: int = 1) -> None:
    """Count a compilation-cache hit observed at a seam that knows one:
    an AOT predict-program served from cache, or a device phase that
    completed without a single new backend compile (warm program)."""
    with _lock:
        _compile["cache_hits"] += int(n)


def compile_snapshot() -> Dict[str, Any]:
    """The ``compile`` section of ``/metrics``: real backend compiles
    (= cache misses), their cumulative seconds, and cache hits."""
    ensure_listener()
    with _lock:
        out = dict(_compile)
    out["compile_s"] = round(out["compile_s"], 6)
    out["cache_misses"] = out["compiles"]
    return out


# -- host (/proc/self) --------------------------------------------------------

def host_rss_bytes() -> int:
    try:
        with open("/proc/self/statm") as f:
            parts = f.read().split()
        return int(parts[1]) * (os.sysconf("SC_PAGE_SIZE")
                                if hasattr(os, "sysconf") else 4096)
    except (OSError, IndexError, ValueError):
        return 0


def host_snapshot() -> Dict[str, Any]:
    """RSS/VMS, open fds, thread count from ``/proc/self`` (zeros on
    platforms without procfs — keys stay present so dashboards never
    branch)."""
    rss = vms = 0
    try:
        page = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096
        with open("/proc/self/statm") as f:
            parts = f.read().split()
        vms, rss = int(parts[0]) * page, int(parts[1]) * page
    except (OSError, IndexError, ValueError):
        pass
    try:
        open_fds = len(os.listdir("/proc/self/fd"))
    except OSError:
        open_fds = 0
    return {"rss_bytes": rss, "vms_bytes": vms, "open_fds": open_fds,
            "threads": threading.active_count()}


# -- device HBM ---------------------------------------------------------------

def device_snapshot() -> Dict[str, Any]:
    """Per-local-device memory accounting. ``source`` says where the
    numbers came from: ``memory_stats`` (backend-reported, with true
    peaks — TPU/GPU) or ``live_buffers`` (sum of live jax array bytes —
    the CPU rig's fallback, attributed to the process, not per device)."""
    try:
        import jax

        devices = jax.local_devices()
    except Exception as exc:  # noqa: BLE001 — pre-init callers
        return {"devices": [], "source": "unavailable", "error": str(exc),
                "total_bytes_in_use": 0, "peak_bytes_in_use": None}
    docs, total, peak_total, have_stats = [], 0, 0, False
    for d in devices:
        stats = None
        try:
            stats = d.memory_stats()
        except Exception:  # noqa: BLE001 — backend without the API
            stats = None
        doc: Dict[str, Any] = {"id": str(d), "platform": d.platform}
        if stats:
            have_stats = True
            doc["bytes_in_use"] = int(stats.get("bytes_in_use", 0))
            doc["peak_bytes_in_use"] = int(
                stats.get("peak_bytes_in_use", doc["bytes_in_use"]))
            if "bytes_limit" in stats:
                doc["bytes_limit"] = int(stats["bytes_limit"])
            total += doc["bytes_in_use"]
            peak_total += doc["peak_bytes_in_use"]
        docs.append(doc)
    if not have_stats:
        # Live-buffer fallback: exact for what jax holds, attributed
        # per device via each array's committed placement so the
        # per-device rows (and lo_resource_device_bytes_in_use_by_device)
        # show every replica's params residency even on the CPU rig —
        # the old process-wide sum left every device but 0 reading as
        # empty once the serve plane replicated params across devices.
        per_dev: Dict[str, int] = {}
        total = 0
        try:
            for a in jax.live_arrays():
                nbytes = int(a.nbytes)
                total += nbytes
                try:
                    devs = list(a.devices())
                except Exception:  # noqa: BLE001 — deleted/donated array
                    continue
                if not devs:
                    continue
                share = nbytes // len(devs)
                for d in devs:
                    per_dev[str(d)] = per_dev.get(str(d), 0) + share
        except Exception:  # noqa: BLE001 — best-effort
            total = 0
        for doc in docs:
            if doc["id"] in per_dev:
                doc["bytes_in_use"] = per_dev[doc["id"]]
        return {"devices": docs, "source": "live_buffers",
                "total_bytes_in_use": total, "peak_bytes_in_use": None}
    return {"devices": docs, "source": "memory_stats",
            "total_bytes_in_use": total, "peak_bytes_in_use": peak_total}


def hbm_bytes_in_use() -> int:
    """One number for watermark sampling: CURRENT device bytes in use
    (summed across local devices; live-buffer bytes on backends without
    memory_stats). Deliberately not the backend's ``peak_bytes_in_use``
    — that peak is process-lifetime and never resets, so sampling it
    would stamp every job after the hungriest one with the hungriest
    one's footprint. Per-job peaks come from max-merging this current
    reading at each device phase end, when the phase's arrays are still
    live."""
    snap = device_snapshot()
    return int(snap.get("total_bytes_in_use") or 0)


# -- disk (chunk store) -------------------------------------------------------

#: Disk-walk TTL cache: (root) -> (expires_monotonic, doc). Walking the
#: store per scrape is O(store size); 5 s staleness is invisible to a
#: 15 s alert window.
_DISK_TTL_S = 5.0
_disk_cache: Dict[str, tuple] = {}


def disk_snapshot(cfg: Optional[Settings] = None,
                  ttl_s: float = _DISK_TTL_S) -> Dict[str, Any]:
    """Filesystem totals for the chunk-store root plus per-dataset byte
    usage (top-level directories under ``store_root``, including
    ``_models``). ``free_bytes`` is what the disk-headroom alert and
    ``/healthz`` judge against."""
    cfg = cfg or global_settings
    root = cfg.store_root
    now = time.monotonic()
    with _lock:
        hit = _disk_cache.get(root)
        if hit is not None and hit[0] > now:
            return dict(hit[1])
    doc: Dict[str, Any] = {"root": root}
    try:
        usage = shutil.disk_usage(root if os.path.isdir(root) else
                                  os.path.dirname(root) or "/")
        doc.update(total_bytes=usage.total, free_bytes=usage.free,
                   used_bytes=usage.used)
    except OSError as exc:
        doc.update(total_bytes=0, free_bytes=0, used_bytes=0,
                   error=str(exc))
    datasets: Dict[str, int] = {}
    store_bytes = 0
    if os.path.isdir(root):
        for entry in sorted(os.listdir(root)):
            path = os.path.join(root, entry)
            if not os.path.isdir(path):
                try:
                    store_bytes += os.path.getsize(path)
                except OSError:
                    pass
                continue
            size = 0
            for dirpath, _dirs, files in os.walk(path):
                for fname in files:
                    try:
                        size += os.path.getsize(
                            os.path.join(dirpath, fname))
                    except OSError:
                        pass
            datasets[entry] = size
            store_bytes += size
    doc["store_bytes"] = store_bytes
    doc["datasets"] = datasets
    with _lock:
        _disk_cache[root] = (now + max(0.0, ttl_s), dict(doc))
    return doc


# -- full snapshots -----------------------------------------------------------

def process_snapshot(cfg: Optional[Settings] = None,
                     lite: bool = False) -> Dict[str, Any]:
    """Everything ``GET /resources`` serves for this process. ``lite``
    drops the per-dataset disk walk — the form workers ship over the
    SPMD job channel and ``/cluster`` displays per process."""
    from learningorchestra_tpu import config

    doc: Dict[str, Any] = {
        "process": config.process_id() or 0,
        "host": host_snapshot(),
        "devices": device_snapshot(),
        "compile": compile_snapshot(),
    }
    if not lite:
        doc["disk"] = disk_snapshot(cfg)
    return doc


#: Last-known snapshots of OTHER pod processes, keyed by pod rank —
#: shipped over the SPMD job channel (hello handshake + per-job span
#: shipments) so ``GET /cluster`` compares the whole pod at a glance.
_remote: Dict[int, Dict[str, Any]] = {}


def note_remote(process: Any, doc: Any) -> None:
    """Record a worker process's shipped resource snapshot (coordinator
    side of the job channel). Malformed shipments are dropped — the
    channel peer is trusted code, but a half-dead worker must never
    corrupt the pod view."""
    if not isinstance(doc, dict):
        return
    try:
        idx = int(process)
    except (TypeError, ValueError):
        return
    with _lock:
        _remote[idx] = {"at": time.time(), **doc}


def remote_snapshots() -> Dict[int, Dict[str, Any]]:
    with _lock:
        return {k: dict(v) for k, v in _remote.items()}


# -- phase sampling (the seam jobs/builder/spmd/profiling hook into) ----------

#: Per-family watermark table accumulated across sweeps since the last
#: reset — what bench.py reads for its ``resources`` block (builds run
#: outside a managed job there, so the job profile can't carry them).
_families: Dict[str, Dict[str, Any]] = {}


def reset_watermarks() -> None:
    with _lock:
        _families.clear()


def family_watermarks() -> Dict[str, Dict[str, Any]]:
    with _lock:
        return {k: dict(v) for k, v in _families.items()}


def _merge_family(family: str, compile_s: float, peak_hbm: int) -> None:
    with _lock:
        ent = _families.setdefault(
            family, {"compile_s": 0.0, "peak_hbm_bytes": 0, "phases": 0})
        ent["compile_s"] = round(ent["compile_s"] + compile_s, 6)
        ent["peak_hbm_bytes"] = max(ent["peak_hbm_bytes"], int(peak_hbm))
        ent["phases"] += 1


def observe_device_phase(name: Optional[str],
                         compile_delta_s: Optional[float],
                         peak_hbm: int) -> None:
    """Merge one device phase's watermarks into the module table and the
    current job's profile. ``name`` follows the span taxonomy —
    ``fit.<family>.device`` attributes the phase to its family.
    ``compile_delta_s`` None means the phase's compile window OVERLAPPED
    another phase's (the process-global counter can't attribute the
    seconds to one family) — the peak still merges, compile attribution
    is skipped rather than double-counted."""
    from learningorchestra_tpu import jobs

    family = None
    if name:
        parts = name.split(".")
        if len(parts) >= 2 and parts[0] == "fit":
            family = parts[1]
    if compile_delta_s is not None and compile_delta_s <= 0.0:
        note_cache_hit()        # warm program: the phase compiled nothing
    if family is not None:
        _merge_family(family, compile_delta_s or 0.0, peak_hbm)
        stats = {"peak_hbm_bytes": int(peak_hbm)}
        if compile_delta_s is not None:
            stats["compile_s"] = round(compile_delta_s, 6)
        jobs.record_job_watermarks(family=family, family_stats=stats)
    jobs.record_job_watermarks(peak_hbm_bytes=peak_hbm)


#: Currently-open device-phase tokens and the subset that overlapped
#: another phase at any point of their window. Compile seconds are a
#: process-global counter, so only a phase that was the SOLE open window
#: for its whole duration can attribute its delta to one family — the
#: serialized instrumented sweep and dispatched pod rounds qualify; a
#: pipelined sweep's concurrent phases record peaks only.
_open_phases: set = set()
_overlapped_phases: set = set()


@contextmanager
def device_phase(name: Optional[str]):
    """The one device-phase sampling window, shared by ``family_phase``
    and ``profiling.device_span``: compile-seconds delta (None when the
    window overlapped another phase — attribution would double-count)
    and a current-device-bytes sample at exit, merged via
    :func:`observe_device_phase`. Exception-transparent — a failing
    phase still records what it consumed before dying."""
    ensure_listener()
    token = object()
    with _lock:
        if _open_phases:
            _overlapped_phases.update(_open_phases)
            _overlapped_phases.add(token)
        _open_phases.add(token)
    c0 = compile_seconds()
    try:
        yield
    finally:
        delta = compile_seconds() - c0
        with _lock:
            _open_phases.discard(token)
            overlapped = token in _overlapped_phases
            _overlapped_phases.discard(token)
        try:
            observe_device_phase(name, None if overlapped else delta,
                                 hbm_bytes_in_use())
        except Exception:  # noqa: BLE001 — sampling must never fail a fit
            pass


def family_phase(family: str):
    """Wrap one classifier family's dispatch region (models/builder.py);
    see :func:`device_phase` for the attribution rules."""
    return device_phase(f"fit.{family}.device")


@contextmanager
def job_phase():
    """Wrap a managed job's whole body (jobs.JobManager): at exit, the
    job's profile carries ``peak_hbm_bytes`` (max of the end sample and
    whatever device phases recorded mid-job), ``compile_s`` (the job
    window's process compile total), and ``host_rss_delta``."""
    from learningorchestra_tpu import jobs

    ensure_listener()
    c0 = compile_seconds()
    rss0 = host_rss_bytes()
    jobs.record_job_watermarks(peak_hbm_bytes=hbm_bytes_in_use())
    try:
        yield
    finally:
        jobs.record_job_watermarks(
            peak_hbm_bytes=hbm_bytes_in_use(),
            compile_s=compile_seconds() - c0,
            host_rss_delta=host_rss_bytes() - rss0)


# -- on-demand device profile (POST /debug/profile) ---------------------------

#: Hard cap on one capture — /debug/profile is an operator tool, not a
#: way to leave the profiler running forever.
PROFILE_MAX_SECONDS = 60.0


def capture_profile(out_dir: str, seconds: float) -> str:
    """Capture a ``jax.profiler`` trace of this process for ``seconds``
    into ``out_dir`` (TensorBoard-loadable). Serializes on the same lock
    as ``device_trace`` — JAX allows one active trace per process."""
    import jax

    from learningorchestra_tpu.utils import profiling

    seconds = min(max(0.0, float(seconds)), PROFILE_MAX_SECONDS)
    os.makedirs(out_dir, exist_ok=True)
    with profiling._trace_lock:
        jax.profiler.start_trace(out_dir)
        try:
            time.sleep(seconds)
        finally:
            jax.profiler.stop_trace()
    log.info("device profile captured: %s (%.1fs)", out_dir, seconds)
    return out_dir


def reset() -> None:
    """Test isolation: clear remote snapshots, family watermarks, and
    the disk cache (compile counters are monotonic by design — tests
    read deltas)."""
    with _lock:
        _remote.clear()
        _families.clear()
        _disk_cache.clear()
