"""Profiling + op timing — the observability tier SURVEY.md §5 calls for.

The reference's only performance instrumentation is the per-model
``fit_time`` wall clock persisted with results (reference
model_builder.py:199-204); everything else was delegated to Spark's web
UIs. Here:

- every framework operation (ingest, projection, histogram, each model
  fit, each embedding) records its wall-clock into a process-wide
  ``OpTimer`` — count/total/mean/max PLUS a log-bucketed latency
  histogram per op, which is what ``GET /metrics?format=prometheus``
  exposes as real histogram series and what the p50/p99 estimates
  derive from (a rolling sample window keeps only recent shape; the
  histogram is exact over the op's whole life at O(#buckets) memory);
- ``timed``/``device_span`` are span-emitting: under an ambient trace
  (utils/tracing.py) each timed region also records a span with the
  exact measured duration, so per-request traces and aggregate metrics
  can never disagree about the same measurement;
- setting ``LO_TPU_PROFILE_DIR`` wraps compute jobs in
  ``jax.profiler.trace`` so every XLA op, transfer, and collective lands
  in a TensorBoard-loadable trace — the device-level view Spark's stage UI
  approximated.
"""

from __future__ import annotations

import bisect
import threading
import time
from contextlib import contextmanager
from typing import Dict, List, Optional, Sequence

from learningorchestra_tpu.config import Settings
from learningorchestra_tpu.utils import tracing

#: Log-spaced histogram bucket upper bounds, seconds (Prometheus-style
#: 1-2.5-5 ladder from 1 ms to 60 s; one implicit +Inf bucket past the
#: end). Shared by OpTimer and the serving tier's latency stats so every
#: histogram on /metrics speaks the same ladder.
BUCKETS_S: Sequence[float] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


def new_histogram() -> List[int]:
    """Zeroed per-bucket counts (len(BUCKETS_S) + 1: last = +Inf)."""
    return [0] * (len(BUCKETS_S) + 1)


def observe(buckets: List[int], seconds: float) -> None:
    """Count one observation into its (non-cumulative) bucket."""
    buckets[bisect.bisect_left(BUCKETS_S, seconds)] += 1


def quantile_from_buckets(buckets: Sequence[int],
                          q: float) -> Optional[float]:
    """Estimate the q-quantile (seconds) from non-cumulative bucket
    counts by linear interpolation within the containing bucket — the
    standard Prometheus ``histogram_quantile`` scheme. The +Inf bucket
    clamps to the last finite bound (an estimate can't exceed what the
    ladder resolves). None when empty."""
    total = sum(buckets)
    if total <= 0:
        return None
    target = q * total
    cum = 0.0
    for i, c in enumerate(buckets):
        if c == 0:
            continue
        prev = cum
        cum += c
        if cum >= target:
            if i >= len(BUCKETS_S):
                return BUCKETS_S[-1]
            lo = BUCKETS_S[i - 1] if i > 0 else 0.0
            hi = BUCKETS_S[i]
            return lo + (hi - lo) * max(0.0, min(1.0, (target - prev) / c))
    return BUCKETS_S[-1]


class OpTimer:
    """Thread-safe aggregate wall-clock stats per operation name.

    An entry exists only once something was recorded into it, so every
    snapshot entry has ``count >= 1`` by construction — ``mean_s`` is a
    plain division, never a guarded one that silently reads 0.0 for an
    empty entry (the old ``max(count, 1)`` bug class)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._stats: Dict[str, Dict] = {}

    def record(self, name: str, seconds: float) -> None:
        with self._lock:
            s = self._stats.get(name)
            if s is None:
                s = self._stats[name] = {
                    "count": 0, "total_s": 0.0, "max_s": 0.0,
                    "buckets": new_histogram()}
            s["count"] += 1
            s["total_s"] += seconds
            s["max_s"] = max(s["max_s"], seconds)
            observe(s["buckets"], seconds)

    def snapshot(self) -> Dict[str, Dict]:
        with self._lock:
            out = {}
            for name, s in self._stats.items():
                out[name] = {
                    "count": s["count"],
                    "total_s": s["total_s"],
                    "max_s": s["max_s"],
                    # count >= 1 always: entries are created by record().
                    "mean_s": s["total_s"] / s["count"],
                    "p50_s": quantile_from_buckets(s["buckets"], 0.50),
                    "p99_s": quantile_from_buckets(s["buckets"], 0.99),
                    "buckets": list(s["buckets"]),
                }
            return out


#: Process-global timer (one server process = one metrics surface).
op_timer = OpTimer()


@contextmanager
def timed(name: str, timer: Optional[OpTimer] = None):
    """Time a region into the op timer AND, under an ambient trace,
    record a span of the same name with the identical duration."""
    t0 = time.time()
    try:
        yield
    finally:
        dur = time.time() - t0
        (timer or op_timer).record(name, dur)
        tracing.record_span(name, dur)


def device_span(fn, name: Optional[str] = None):
    """Run ``fn`` (a thunk whose result is a pytree of jax arrays or a
    value derived from them) and return ``(result, seconds)`` where the
    span covers program dispatch *through blocked completion* — JAX
    dispatch is asynchronous, so an unblocked wall-clock around a jitted
    call measures enqueue time, not compute. ``jax.block_until_ready``
    walks pytrees, so trainer param dicts work as-is.

    When the caller serializes device work (one fit in its device phase
    at a time), the span is the fit's device occupancy plus its transfer
    tail — the ``device_s`` figure that separates tunnel/host jitter from
    device compute in the bench. Under overlapped dispatch it includes
    queue waits behind other programs and is reported as such.

    ``name`` additionally records a trace span (ambient context) with
    the exact same measured duration — the builder passes
    ``fit.<family>.device`` so a job's trace and its ``fit_device_s``
    profile figure agree to the digit.

    Every device phase is also a resource sample point
    (``resources.device_phase``): the compile-seconds delta across the
    span (attributed only when the window overlapped no other phase —
    the counter is process-global) and a device-bytes reading at its
    end merge into the current job's watermarks (``peak_hbm_bytes``)
    and — for ``fit.<family>.device`` names — the per-family table
    bench.py and the job profile's ``fit_resources`` read. Best-effort:
    a sampling failure degrades to an unprofiled span, never a failed
    fit.
    """
    import jax

    from learningorchestra_tpu.utils import resources

    with resources.device_phase(name):
        # Timed INSIDE the sampling window so the measured duration
        # stays the pure dispatch-to-completion figure (the sampling
        # reads at window exit never inflate device_s).
        t0 = time.time()
        out = jax.block_until_ready(fn())
        dur = time.time() - t0
    if name is not None:
        tracing.record_span(name, dur)
    return out, dur


#: JAX allows one active profiler trace per process; concurrent jobs that
#: both request tracing serialize on this lock instead of crashing.
_trace_lock = threading.Lock()


@contextmanager
def device_trace(cfg: Settings):
    """jax.profiler trace around a compute job when profile_dir is set.

    Wrap whole jobs (a full multi-classifier build, one predict call) —
    not per-thread work items — so a trace covers a meaningful span.
    """
    if not cfg.profile_dir:
        yield
        return
    import jax

    with _trace_lock, jax.profiler.trace(cfg.profile_dir):
        yield
