"""Profiling + op timing — the observability tier SURVEY.md §5 calls for.

The reference's only performance instrumentation is the per-model
``fit_time`` wall clock persisted with results (reference
model_builder.py:199-204); everything else was delegated to Spark's web
UIs. Here:

- every framework operation (ingest, projection, histogram, each model
  fit, each embedding) records its wall-clock into a process-wide
  ``OpTimer``; aggregates are served at GET /metrics alongside job stats;
- setting ``LO_TPU_PROFILE_DIR`` wraps compute jobs in
  ``jax.profiler.trace`` so every XLA op, transfer, and collective lands
  in a TensorBoard-loadable trace — the device-level view Spark's stage UI
  approximated.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Dict, Optional

from learningorchestra_tpu.config import Settings


class OpTimer:
    """Thread-safe aggregate wall-clock stats per operation name."""

    def __init__(self):
        self._lock = threading.Lock()
        self._stats: Dict[str, Dict[str, float]] = {}

    def record(self, name: str, seconds: float) -> None:
        with self._lock:
            s = self._stats.setdefault(
                name, {"count": 0, "total_s": 0.0, "max_s": 0.0})
            s["count"] += 1
            s["total_s"] += seconds
            s["max_s"] = max(s["max_s"], seconds)

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {
                name: {**s, "mean_s": s["total_s"] / max(s["count"], 1)}
                for name, s in self._stats.items()
            }


#: Process-global timer (one server process = one metrics surface).
op_timer = OpTimer()


@contextmanager
def timed(name: str, timer: Optional[OpTimer] = None):
    t0 = time.time()
    try:
        yield
    finally:
        (timer or op_timer).record(name, time.time() - t0)


def device_span(fn):
    """Run ``fn`` (a thunk whose result is a pytree of jax arrays or a
    value derived from them) and return ``(result, seconds)`` where the
    span covers program dispatch *through blocked completion* — JAX
    dispatch is asynchronous, so an unblocked wall-clock around a jitted
    call measures enqueue time, not compute. ``jax.block_until_ready``
    walks pytrees, so trainer param dicts work as-is.

    When the caller serializes device work (one fit in its device phase
    at a time), the span is the fit's device occupancy plus its transfer
    tail — the ``device_s`` figure that separates tunnel/host jitter from
    device compute in the bench. Under overlapped dispatch it includes
    queue waits behind other programs and is reported as such.
    """
    import jax

    t0 = time.time()
    out = jax.block_until_ready(fn())
    return out, time.time() - t0


#: JAX allows one active profiler trace per process; concurrent jobs that
#: both request tracing serialize on this lock instead of crashing.
_trace_lock = threading.Lock()


@contextmanager
def device_trace(cfg: Settings):
    """jax.profiler trace around a compute job when profile_dir is set.

    Wrap whole jobs (a full multi-classifier build, one predict call) —
    not per-thread work items — so a trace covers a meaningful span.
    """
    if not cfg.profile_dir:
        yield
        return
    import jax

    with _trace_lock, jax.profiler.trace(cfg.profile_dir):
        yield
