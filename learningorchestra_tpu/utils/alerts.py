"""Declarative SLO alert engine over the metrics registry snapshot.

Metrics answer questions when someone asks; alerts ask the questions
continuously. This engine evaluates a small set of declarative rules
against the SAME registry snapshot both ``/metrics`` formats render
(serving/app.py ``_metrics_doc``) — so an alert can never fire on a
number the operator cannot see — and keeps firing/resolved state with
hysteresis on both edges:

- a **threshold rule** (serving p99 over its SLO, queue rejection rate)
  must be bad for ``for_windows`` consecutive evaluation windows before
  it fires — one jittery scrape pages nobody;
- an **event rule** (pod degraded, disk under its watermark, corruption
  or read-worker-error counter increments) fires on a single window —
  these are never jitter;
- a firing alert resolves only after ``clear_windows`` consecutive clean
  windows — a flapping condition stays visibly FIRING instead of
  strobing.

Evaluation is *read-driven*, the Prometheus model: each ``/metrics`` /
``/alerts`` / ``/healthz`` / status-page read advances at most one
window (``LO_TPU_ALERT_WINDOW_S``), so scrape cadence is evaluation
cadence and an unwatched server burns zero cycles on rules. Transitions
log through structlog (WARNING on fire, INFO on resolve) with the rule
name, value, and threshold — greppable next to the traces.

Rules read the snapshot, never mutate it, and keep their cross-window
state (previous counter values, streak counts) inside the engine — a
rule evaluated against two different App instances' snapshots never
bleeds state between them because each App owns its engine.

**Multi-window burn rates** (PR 13): with a telemetry history store
attached (utils/timeseries.py), the serving SLO rules
(``serving_p99_slo``, ``serving_reject_rate``,
``serving_deadline_exceeded_rate``) stop judging one instantaneous
snapshot and judge the HISTORY instead, over two windows at once:

- the **slow window** (``LO_TPU_SLO_BURN_SLOW_S``, default 1 h) owns
  the error budget (``LO_TPU_SLO_BURN_BUDGET``): a spike that consumed
  almost none of it reads a burn rate < 1 and pages nobody, however
  dramatic its instantaneous value was;
- the **fast window** (``LO_TPU_SLO_BURN_FAST_S``, default 5 min)
  guards recency: a burn that already stopped reads < 1 there and
  resolves promptly instead of paging for an hour-old incident.

A rule's value is ``min(burn_fast, burn_slow)`` and it fires above 1.0
— so a sustained burn fires within the fast window (its slow-window
budget is consumed quickly at a high burn rate) while brief spikes and
stale incidents both stay silent. The history store, not scrape
cadence, is the evaluation substrate: the background telemetry sampler
keeps feeding it even when nothing scrapes ``/metrics``. Without a
history store (or with a burn window knob at 0) the legacy
single-window samplers above apply unchanged.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from learningorchestra_tpu.config import Settings
from learningorchestra_tpu.utils.structlog import get_logger

log = get_logger("alerts")


def _path(snapshot: Dict[str, Any], *keys: str) -> Optional[float]:
    """Numeric value at a nested path, or None when absent."""
    cur: Any = snapshot
    for k in keys:
        if not isinstance(cur, dict) or k not in cur:
            return None
        cur = cur[k]
    if isinstance(cur, bool) or not isinstance(cur, (int, float)):
        return None
    return float(cur)


def counter_delta(*keys: str) -> Callable:
    """Sample fn: per-window increase of a cumulative counter at
    ``keys``. The previous value lives in the per-rule ``state`` dict
    the engine owns. First observation establishes the baseline (delta
    None — a server restarting with a nonzero counter must not fire)."""

    def sample(snapshot: Dict[str, Any],
               state: Dict[str, Any]) -> Optional[float]:
        cur = _path(snapshot, *keys)
        if cur is None:
            return None
        prev = state.get("prev")
        state["prev"] = cur
        if prev is None:
            return None
        return max(0.0, cur - prev)

    return sample


@dataclass
class AlertRule:
    """One declarative rule: ``sample(snapshot, state)`` produces the
    measured value (None = no data this window → streaks hold), which
    fires when ``value <op> threshold``."""

    name: str
    severity: str                 # "critical" degrades /healthz; "warning"
    summary: str
    sample: Callable[[Dict[str, Any], Dict[str, Any]], Optional[float]]
    threshold: float
    op: str = ">"                 # ">" or "<"
    #: None = engine default (cfg.alert_for_windows); event rules pin 1.
    for_windows: Optional[int] = None

    def bad(self, value: float) -> bool:
        return value < self.threshold if self.op == "<" \
            else value > self.threshold


@dataclass
class _RuleState:
    firing: bool = False
    bad_streak: int = 0
    ok_streak: int = 0
    since: Optional[float] = None       # wall time of the last transition
    last_value: Optional[float] = None
    fired_count: int = 0
    state: Dict[str, Any] = field(default_factory=dict)


class AlertEngine:
    """Firing/resolved state machine over a rule list. One instance per
    App — rule state (counter baselines, streaks) is App-scoped."""

    def __init__(self, rules: List[AlertRule], window_s: float = 15.0,
                 for_windows: int = 2, clear_windows: int = 2):
        self.rules = list(rules)
        self.window_s = max(0.0, float(window_s))
        self.for_windows = max(1, int(for_windows))
        self.clear_windows = max(1, int(clear_windows))
        self._lock = threading.Lock()
        self._states: Dict[str, _RuleState] = {
            r.name: _RuleState() for r in self.rules}
        self._last_eval: Optional[float] = None
        self._counters = {"evaluations": 0, "fired_total": 0,
                          "resolved_total": 0}

    # -- evaluation ----------------------------------------------------------

    def observe(self, snapshot: Dict[str, Any],
                now: Optional[float] = None) -> List[Dict[str, Any]]:
        """Window-gated evaluation: advances one window when at least
        ``window_s`` elapsed since the last one (0 = every call).
        Returns the transitions of this window ([] when gated out)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            if (self._last_eval is not None
                    and now - self._last_eval < self.window_s):
                return []
            self._last_eval = now
        return self.evaluate(snapshot)

    def evaluate(self, snapshot: Dict[str, Any]) -> List[Dict[str, Any]]:
        """One evaluation window, unconditionally (tests drive this
        directly). Returns fired/resolved transition docs."""
        transitions: List[Dict[str, Any]] = []
        with self._lock:
            self._counters["evaluations"] += 1
            for rule in self.rules:
                st = self._states[rule.name]
                value = rule.sample(snapshot, st.state)
                if value is None:
                    continue                  # no data: streaks hold
                st.last_value = value
                need = rule.for_windows or self.for_windows
                if rule.bad(value):
                    st.bad_streak += 1
                    st.ok_streak = 0
                    if not st.firing and st.bad_streak >= need:
                        st.firing = True
                        st.since = time.time()
                        st.fired_count += 1
                        self._counters["fired_total"] += 1
                        transitions.append(
                            {"alert": rule.name, "to": "firing",
                             "value": value,
                             "threshold": rule.threshold})
                else:
                    st.ok_streak += 1
                    st.bad_streak = 0
                    if st.firing and st.ok_streak >= self.clear_windows:
                        st.firing = False
                        st.since = time.time()
                        self._counters["resolved_total"] += 1
                        transitions.append(
                            {"alert": rule.name, "to": "resolved",
                             "value": value,
                             "threshold": rule.threshold})
        for t in transitions:
            if t["to"] == "firing":
                log.warning(
                    "alert %s FIRING: value %.6g vs threshold %.6g",
                    t["alert"], t["value"], t["threshold"])
            else:
                log.info(
                    "alert %s resolved: value %.6g vs threshold %.6g",
                    t["alert"], t["value"], t["threshold"])
        return transitions

    # -- views ---------------------------------------------------------------

    def firing(self, severity: Optional[str] = None) -> List[str]:
        by_rule = {r.name: r for r in self.rules}
        with self._lock:
            return [name for name, st in self._states.items()
                    if st.firing and (severity is None
                                      or by_rule[name].severity == severity)]

    def snapshot(self) -> Dict[str, Any]:
        """The ``alerts`` section of ``/metrics`` and the ``GET /alerts``
        body: per-rule state plus engine counters."""
        rules: Dict[str, Any] = {}
        with self._lock:
            for rule in self.rules:
                st = self._states[rule.name]
                rules[rule.name] = {
                    "severity": rule.severity,
                    "summary": rule.summary,
                    "threshold": rule.threshold,
                    "op": rule.op,
                    "for_windows": rule.for_windows or self.for_windows,
                    "firing": st.firing,
                    "value": st.last_value,
                    "since": st.since,
                    "fired_count": st.fired_count,
                }
                # Burn-rate rules stash their per-window detail: the
                # operator sees WHICH window is (not) burning.
                if "burn" in st.state:
                    rules[rule.name]["burn"] = dict(st.state["burn"])
            counters = dict(self._counters)
        return {
            "firing": sorted(n for n, doc in rules.items()
                             if doc["firing"]),
            "rules": rules,
            "window_s": self.window_s,
            "clear_windows": self.clear_windows,
            **counters,
        }


# -- multi-window burn-rate samplers (over the telemetry history) -------------

def _expected_samples(samples, window_s: float) -> float:
    """How many samples the window WOULD hold at the observed cadence —
    the denominator that makes absent history count as in-SLO. A young
    server (or one whose history only spans minutes of a 1 h window)
    must not read its few samples as the whole window: a 1-minute blip
    on a 2-minute-old process is still a blip, not a 50% burn."""
    n = len(samples)
    if n < 2:
        return float(n)
    span = samples[-1][0] - samples[0][0]
    if span <= 0:
        return float(n)
    gap = span / (n - 1)
    return max(float(n), float(window_s) / gap)


def _p99_bad_fraction(history, window_s: float, slo_ms: float) -> \
        Optional[float]:
    """Fraction of the trailing window where ANY model with recent
    traffic ran its p99 above the SLO — judged against the sample count
    the FULL window would hold (missing history counts as in-SLO). None
    without samples (no data: streaks hold, like every sampler)."""
    samples = history.window(window_s)
    if not samples:
        return None
    bad = 0
    for _t, values in samples:
        for name, val in values.items():
            if not (name.startswith("serving.models.")
                    and name.endswith(".p99_ms")):
                continue
            qps = values.get(name[: -len(".p99_ms")] + ".qps") or 0.0
            if qps > 0 and val > slo_ms:
                bad += 1
                break
    return bad / _expected_samples(samples, window_s)


def _ratio_bad_fraction(history, window_s: float, bad_key: str,
                        ok_key: str, threshold: float) -> Optional[float]:
    """Fraction of the window's sample-to-sample intervals whose
    ``Δbad / (Δbad + Δok)`` ratio exceeded ``threshold`` — the same
    "how much of this window was out of SLO" unit the p99 rule
    measures, so every burn rule divides by one budget. Counters that
    moved backwards (process restart) clamp to 0 for that interval;
    traffic-free intervals count as in-SLO. None without at least two
    samples carrying both counters."""
    samples = history.window(window_s)
    points = [(t, v) for t, v in samples
              if bad_key in v and ok_key in v]
    if len(points) < 2:
        return None
    bad_intervals = 0
    for (_t0, prev), (_t1, cur) in zip(points, points[1:]):
        d_bad = max(0.0, cur[bad_key] - prev[bad_key])
        d_ok = max(0.0, cur[ok_key] - prev[ok_key])
        offered = d_bad + d_ok
        if offered > 0 and (d_bad / offered) > threshold:
            bad_intervals += 1
    # Same missing-history-is-in-SLO denominator as the p99 rule.
    return bad_intervals / max(len(points) - 1.0,
                               _expected_samples(points, window_s) - 1.0)


def burn_rate_sample(history, cfg: Settings,
                     bad_fraction_fn: Callable) -> Callable:
    """Build a multi-window burn-rate sampler. ``bad_fraction_fn(history,
    window_s)`` measures the out-of-SLO fraction of one window; the
    sample is ``min(fast, slow) / budget`` — both windows must be
    burning for the rule to read above its 1.0 firing line. The last
    per-window burns land in the rule's state dict, which the snapshot
    surfaces for operators."""
    fast_s = float(cfg.slo_burn_fast_s)
    slow_s = float(cfg.slo_burn_slow_s)
    budget = max(1e-9, float(cfg.slo_burn_budget))

    def sample(_snapshot: Dict[str, Any],
               state: Dict[str, Any]) -> Optional[float]:
        fast = bad_fraction_fn(history, fast_s)
        slow = bad_fraction_fn(history, slow_s)
        if fast is None or slow is None:
            return None
        burn_fast, burn_slow = fast / budget, slow / budget
        state["burn"] = {"fast": round(burn_fast, 4),
                         "slow": round(burn_slow, 4),
                         "fast_window_s": fast_s, "slow_window_s": slow_s}
        return min(burn_fast, burn_slow)

    return sample


def _burn_windows_enabled(cfg: Settings, history) -> bool:
    # A DISABLED history store (LO_TPU_TELEMETRY_SAMPLE_S < 0: window()
    # forever empty) must fall back to the legacy instantaneous
    # samplers — burn rules over it would return None every window and
    # silently never fire any serving SLO alert.
    return (history is not None and getattr(history, "enabled", True)
            and cfg.slo_burn_fast_s > 0 and cfg.slo_burn_slow_s > 0)


# -- the default rule set -----------------------------------------------------

def _serving_worst_p99(snapshot: Dict[str, Any],
                       _state: Dict[str, Any]) -> Optional[float]:
    """Worst per-model recent-window p99 (ms) — the SLO is per model, so
    one degraded model fires even while healthy ones dilute the mean.
    Only models with recent traffic count: an idle model's ``p99_ms``
    falls back to its LIFETIME histogram shape (batcher._Stats), and a
    cold-load spike in there would otherwise keep the alert lit forever
    on a healthy, idle server. No model serving ⇒ 0.0 (no breach — and
    a firing alert resolves when traffic stops instead of latching)."""
    models = ((snapshot.get("serving") or {}).get("models") or {})
    worst = None
    for m in models.values():
        p99 = m.get("p99_ms")
        if not (m.get("qps") or 0) > 0:
            continue
        if isinstance(p99, (int, float)) and (worst is None or p99 > worst):
            worst = float(p99)
    return 0.0 if worst is None else worst


def _reject_rate(snapshot: Dict[str, Any],
                 state: Dict[str, Any]) -> Optional[float]:
    """Per-window rejected / offered ratio for the online predict tier.
    A window with no offered traffic reads 0.0 (no data ≠ bad)."""
    serving = snapshot.get("serving") or {}
    rej = serving.get("rejected")
    req = serving.get("requests")
    if not isinstance(rej, (int, float)) or not isinstance(
            req, (int, float)):
        return None
    prev = state.get("prev")
    state["prev"] = (float(rej), float(req))
    if prev is None:
        return None
    d_rej = max(0.0, float(rej) - prev[0])
    d_req = max(0.0, float(req) - prev[1])
    offered = d_rej + d_req
    return (d_rej / offered) if offered > 0 else 0.0


def _deadline_rate(snapshot: Dict[str, Any],
                   state: Dict[str, Any]) -> Optional[float]:
    """Per-window deadline-expired / offered ratio for the online
    predict tier — sustained misses mean callers are abandoning answers
    faster than the tier can produce them. Offered = completed +
    expired this window; an idle window reads 0.0 (no data ≠ bad)."""
    serving = snapshot.get("serving") or {}
    ded = serving.get("deadline_exceeded")
    req = serving.get("requests")
    if not isinstance(ded, (int, float)) or not isinstance(
            req, (int, float)):
        return None
    prev = state.get("prev")
    state["prev"] = (float(ded), float(req))
    if prev is None:
        return None
    d_ded = max(0.0, float(ded) - prev[0])
    d_req = max(0.0, float(req) - prev[1])
    offered = d_ded + d_req
    return (d_ded / offered) if offered > 0 else 0.0


def _quarantined_models(snapshot: Dict[str, Any],
                        _state: Dict[str, Any]) -> Optional[float]:
    """How many models are currently quarantined (dispatcher crashed
    past its threshold and predicts answer the terminal 503). Level, not
    delta: the alert stays FIRING for as long as any quarantine stands,
    and resolves when a DELETE/re-save lifts the last one."""
    serving = snapshot.get("serving") or {}
    q = serving.get("quarantined")
    if not isinstance(q, (int, float)) or isinstance(q, bool):
        return None
    return float(q)


def _pod_degraded(snapshot: Dict[str, Any],
                  _state: Dict[str, Any]) -> Optional[float]:
    pod = snapshot.get("pod") or {}
    return 1.0 if pod.get("error") else 0.0


def _disk_free(snapshot: Dict[str, Any],
               _state: Dict[str, Any]) -> Optional[float]:
    return _path(snapshot, "resources", "disk", "free_bytes")


def _under_replicated(snapshot: Dict[str, Any],
                      _state: Dict[str, Any]) -> Optional[float]:
    """(dataset, peer) pairs with committed-but-unacked journal bytes
    whose last push FAILED — the store does not flag transient lag from
    an in-flight push, so this level is burn-rate friendly: it holds
    through a real outage and drops to zero the moment re-replication
    catches up. None (rule skips the window) when no peers are
    configured."""
    rep = snapshot.get("replication") or {}
    if not rep.get("enabled"):
        return None
    return float(len(rep.get("under_replicated") or []))


def default_rules(cfg: Settings, history=None) -> List[AlertRule]:
    """The shipped rule table (docs/observability.md). Thresholds come
    from Settings; a 0 threshold knob drops its rule entirely. With a
    telemetry ``history`` store attached (and burn windows enabled),
    the three serving SLO rules evaluate as multi-window burn rates
    over it — value ``min(burn_fast, burn_slow)``, firing line 1.0 —
    instead of the legacy instantaneous single-window samplers."""
    burn = _burn_windows_enabled(cfg, history)
    rules: List[AlertRule] = []
    if cfg.slo_p99_ms > 0:
        slo_ms = float(cfg.slo_p99_ms)
        if burn:
            rules.append(AlertRule(
                name="serving_p99_slo", severity="warning",
                summary="online predict p99 burning its error budget: "
                        f"out-of-SLO (> {slo_ms:g}ms) fraction of both "
                        "the fast and the slow history window exceeds "
                        "the budget (brief spikes stay silent; "
                        "sustained burns fire within the fast window)",
                sample=burn_rate_sample(
                    history, cfg,
                    lambda h, w, slo=slo_ms: _p99_bad_fraction(h, w, slo)),
                threshold=1.0, for_windows=1))
        else:
            rules.append(AlertRule(
                name="serving_p99_slo", severity="warning",
                summary="online predict recent-window p99 above its SLO "
                        "for the worst model",
                sample=_serving_worst_p99, threshold=slo_ms))
    if cfg.slo_reject_rate > 0:
        if burn:
            rate = float(cfg.slo_reject_rate)
            rules.append(AlertRule(
                name="serving_reject_rate", severity="warning",
                summary="predict-queue rejection rate burning its error "
                        "budget over both history windows (capacity, "
                        "not a blip)",
                sample=burn_rate_sample(
                    history, cfg,
                    lambda h, w, r=rate: _ratio_bad_fraction(
                        h, w, "serving.rejected", "serving.requests", r)),
                threshold=1.0, for_windows=1))
        else:
            rules.append(AlertRule(
                name="serving_reject_rate", severity="warning",
                summary="predict queue rejecting a sustained fraction of "
                        "offered requests (capacity, not a blip)",
                sample=_reject_rate, threshold=float(cfg.slo_reject_rate)))
    if cfg.slo_deadline_rate > 0:
        if burn:
            rate = float(cfg.slo_deadline_rate)
            rules.append(AlertRule(
                name="serving_deadline_exceeded_rate", severity="warning",
                summary="deadline-miss rate burning its error budget "
                        "over both history windows — callers abandon "
                        "answers faster than the tier produces them",
                sample=burn_rate_sample(
                    history, cfg,
                    lambda h, w, r=rate: _ratio_bad_fraction(
                        h, w, "serving.deadline_exceeded",
                        "serving.requests", r)),
                threshold=1.0, for_windows=1))
        else:
            rules.append(AlertRule(
                name="serving_deadline_exceeded_rate", severity="warning",
                summary="a sustained fraction of predict requests is "
                        "dying at its deadline (admission or in-queue "
                        "expiry) — callers abandon answers faster than "
                        "the tier produces them",
                sample=_deadline_rate,
                threshold=float(cfg.slo_deadline_rate)))
    rules.append(AlertRule(
        name="serving_quarantined", severity="warning",
        summary="a model's dispatcher crashed past its quarantine "
                "threshold; its predicts answer a terminal 503 until "
                "the model is re-saved or deleted",
        sample=_quarantined_models, threshold=0.5, for_windows=1))
    rules.append(AlertRule(
        name="job_watchdog_fired", severity="critical",
        summary="the job watchdog killed a hung device program this "
                "window (no progress past LO_TPU_JOB_DEADLINE_S); the "
                "pod is poisoned pending a supervisor restart and the "
                "retried job will resume from its fit checkpoint",
        sample=counter_delta("job_fault", "watchdog_fired_total"),
        threshold=0.0, for_windows=1))
    rules.append(AlertRule(
        name="pod_degraded", severity="critical",
        summary="a pod worker died mid-job; mesh jobs fail fast until "
                "the supervisor restarts the pod",
        sample=_pod_degraded, threshold=0.5, for_windows=1))
    if cfg.disk_free_watermark_mb > 0:
        rules.append(AlertRule(
            name="disk_free_low", severity="critical",
            summary="chunk-store filesystem below its free-space "
                    "watermark; ingest/journal writes are about to fail",
            sample=_disk_free,
            threshold=float(cfg.disk_free_watermark_mb) * (1 << 20),
            op="<", for_windows=1))
    rules.append(AlertRule(
        name="integrity_corrupt", severity="critical",
        summary="chunk corruption detected this window (CRC mismatch "
                "on read or scrub)",
        sample=counter_delta("integrity", "chunks_corrupt"),
        threshold=0.0, for_windows=1))
    rules.append(AlertRule(
        name="data_under_replicated", severity="critical",
        summary="committed journal bytes are not replicated to every "
                "peer and the last push failed — a host loss right now "
                "loses the unacked suffix; check peer liveness, lag "
                "drains automatically once a push succeeds "
                "(docs/fault_tolerance.md §9)",
        sample=_under_replicated, threshold=0.0, for_windows=1))
    rules.append(AlertRule(
        name="readpipe_worker_errors", severity="warning",
        summary="chunk-read pipeline workers raised this window "
                "(failures re-raise consumer-side; investigate disk)",
        sample=counter_delta("read_pipeline", "worker_errors"),
        threshold=0.0, for_windows=1))
    return rules


def default_engine(cfg: Settings, history=None) -> AlertEngine:
    return AlertEngine(default_rules(cfg, history=history),
                       window_s=cfg.alert_window_s,
                       for_windows=cfg.alert_for_windows,
                       clear_windows=cfg.alert_clear_windows)
