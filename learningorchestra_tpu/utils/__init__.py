"""Cross-cutting utilities: profiling, op timing."""
