"""Fit-progress checkpoints — interrupted fits resume instead of restart.

The elastic-recovery loop (supervisor restart → mesh-epoch bump → job
rescan, docs/fault_tolerance.md) re-executes a lost job FROM SCRATCH: at
the HIGGS-11M scale the ROADMAP targets, losing tens of minutes of gb
boost rounds to one worker blip is the dominant MTTR cost. This module
is the missing half: trainers (and the streamed-design state fit)
persist per-family progress at natural boundaries — gb boost-round
batches, rf vmapped tree batches, mlp iteration segments, fitting-pass
boundaries — and a retried job resumes from the newest valid checkpoint,
producing **bit-identical** final params/metrics to an uninterrupted
fit (parity-pinned per family in tests/test_fitckpt.py).

Disk discipline mirrors the chunk store's (PR 4): every checkpoint is an
immutable ``ckpt-<progress>.npz`` payload committed via tmp+fsync+rename
with a sidecar ``ckpt-<progress>.json`` carrying the payload's CRC32 —
written strictly AFTER the payload lands, so a crash at any byte leaves
either a fully-valid pair or an ignorable orphan, never a torn
checkpoint that could be trusted (the crash sweep in
tests/test_failpoints.py covers the ``fit.ckpt.pre_rename`` window).
Older checkpoints are pruned only after a newer pair is fully durable.

Validity is KEYED, never assumed: the sidecar records
``(dataset, family, config, snapshot, mesh_epoch)`` — the config hash
covers hparams/steps/mesh shape (a different mesh shape changes psum
summation grouping, so its partial sums must not be resumed), the
snapshot token pins the row prefix the fit read (PR 2's ``pin_snapshot``
discipline), and the mesh epoch records the writing incarnation. A
checkpoint whose key mismatches, whose epoch is FROM THE FUTURE (a
concurrent newer incarnation wrote it), or whose payload fails its CRC
is discarded with a structlog warning — stale or corrupt progress is
never trusted. ``LO_TPU_FIT_CKPT_ROUNDS=0`` (default) disables the
whole tier and keeps the single-program fit path as the oracle.
"""

from __future__ import annotations

import io
import json
import os
import threading
import zlib
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

import numpy as np

from learningorchestra_tpu.config import Settings, mesh_epoch
from learningorchestra_tpu.utils import failpoints
from learningorchestra_tpu.utils.structlog import get_logger

log = get_logger("fitckpt")

#: Deterministic fault-injection sites (utils/failpoints.py): the
#: payload is written+fsynced but not yet renamed into place (the torn
#: /crash window the sweep drives), and the read side's entry (corrupt
#: checkpoints must be discarded, never trusted).
FP_CKPT_PRE_RENAME = failpoints.declare("fit.ckpt.pre_rename")
FP_CKPT_PRE_READ = failpoints.declare("fit.ckpt.pre_read")

#: Families whose fits carry natural mid-fit checkpoint boundaries (the
#: builder only mints contexts for these; lr/nb/dt fits are single
#: closed-form/one-batch programs whose only boundary is the start).
SEGMENTED_FAMILIES = ("gb", "rf", "mlp")

_counter_lock = threading.Lock()
_counters = {"writes": 0, "resumes": 0, "discarded": 0}


def _bump(key: str) -> None:
    with _counter_lock:
        _counters[key] += 1


def counters_snapshot() -> Dict[str, int]:
    with _counter_lock:
        return dict(_counters)


def count_resume() -> None:
    """Count one ACTUAL resume — called by the segmented fit drivers at
    the moment they accept a loaded checkpoint (not by ``load`` itself:
    a caller may still reject a key-valid checkpoint whose progress
    doesn't fit its shape, and the series documents successful
    resumes)."""
    _bump("resumes")


def root_dir(cfg: Settings) -> str:
    return os.path.join(cfg.store_root, "_fitckpt")


def disk_snapshot(cfg: Settings) -> Dict[str, Any]:
    """The ``fit_checkpoints`` section of ``/metrics``: live bytes/files
    under ``<store_root>/_fitckpt`` plus the process counters. One
    directory walk per scrape — the dir holds at most a handful of
    (payload, sidecar) pairs per in-flight family."""
    files = 0
    nbytes = 0
    root = root_dir(cfg)
    for dirpath, _dirs, names in os.walk(root):
        for name in names:
            try:
                nbytes += os.path.getsize(os.path.join(dirpath, name))
                files += 1
            except OSError:
                continue
    doc: Dict[str, Any] = {"files": files, "bytes": nbytes}
    doc.update(counters_snapshot())
    return doc


def config_hash(doc: Any) -> str:
    """Stable short hash of a JSON-able config document (hparams, steps,
    mesh shape, ...) — the checkpoint-validity component that makes a
    resume under ANY changed fit configuration start fresh."""
    blob = json.dumps(doc, sort_keys=True, default=str).encode("utf-8")
    return f"{zlib.crc32(blob):08x}-{len(blob)}"


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


@dataclass
class FitContext:
    """One (dataset, family) checkpoint stream: ``save`` commits
    progress, ``load`` returns the newest valid checkpoint, ``clear``
    drops the stream once the fit completed. ``every`` is the cadence in
    the family's natural unit (gb rounds / mlp iters); ``0`` disables —
    callers should then never consult the context at all."""

    cfg: Settings
    dataset: str
    family: str
    config: str                      # config_hash() of the fit's knobs
    snapshot: str                    # pinned row-prefix token
    every: int = 0
    #: Serializes this stream's save/load/clear: fan-out family threads
    #: each own their context, so this is cheap insurance against a
    #: future caller sharing one — never a hot lock.
    _lock: threading.Lock = field(default_factory=threading.Lock,
                                  repr=False)

    @property
    def enabled(self) -> bool:
        return self.every > 0

    def _dir(self) -> str:
        # Dataset/family names are validated route-side (store
        # validate_name); the join stays flat by construction.
        return os.path.join(root_dir(self.cfg),
                            f"{self.dataset}__{self.family}")

    def _key_doc(self) -> Dict[str, Any]:
        return {"dataset": self.dataset, "family": self.family,
                "config": self.config, "snapshot": self.snapshot}

    # -- write ---------------------------------------------------------------

    def save(self, progress: int, arrays: Dict[str, np.ndarray],
             meta: Optional[Dict[str, Any]] = None) -> None:
        """Commit one checkpoint at ``progress`` (a monotone count in the
        family's natural unit). Best-effort by contract: a checkpoint
        write failure must never fail the fit it exists to protect —
        except an armed failpoint, which must stay injectable."""
        if not self.enabled:
            return
        try:
            with self._lock:
                self._save(progress, arrays, meta)
            _bump("writes")
        except failpoints.FailpointError:
            raise
        except OSError as exc:
            log.warning("fit checkpoint write failed for %s/%s@%d: %s",
                        self.dataset, self.family, progress, exc)

    def _save(self, progress: int, arrays: Dict[str, np.ndarray],
              meta: Optional[Dict[str, Any]]) -> None:
        d = self._dir()
        os.makedirs(d, exist_ok=True)
        payload = os.path.join(d, f"ckpt-{progress:08d}.npz")
        sidecar = os.path.join(d, f"ckpt-{progress:08d}.json")
        buf = io.BytesIO()
        np.savez(buf, **{k: np.asarray(v) for k, v in arrays.items()})
        blob = buf.getvalue()
        tmp = payload + ".tmp"
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        # The torn/crash window under sweep test: payload durable in its
        # tmp name, nothing committed yet — the previous checkpoint pair
        # must stay the one a resume trusts.
        failpoints.fire(FP_CKPT_PRE_RENAME, path=tmp)
        os.replace(tmp, payload)
        doc = dict(self._key_doc(),
                   progress=int(progress),
                   crc32=zlib.crc32(blob),
                   nbytes=len(blob),
                   mesh_epoch=mesh_epoch(),
                   meta=dict(meta or {}))
        stmp = sidecar + ".tmp"
        with open(stmp, "w") as f:
            json.dump(doc, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(stmp, sidecar)
        _fsync_dir(d)
        # Prune strictly-older pairs only now that the newer pair is
        # fully durable (a crash anywhere above leaves the previous one).
        for name in os.listdir(d):
            if not name.startswith("ckpt-"):
                continue
            try:
                p = int(name[5:13])
            except ValueError:
                continue
            if p < progress:
                try:
                    os.remove(os.path.join(d, name))
                except OSError:
                    pass

    # -- read ----------------------------------------------------------------

    def load(self) -> Optional[Tuple[int, Dict[str, np.ndarray],
                                     Dict[str, Any]]]:
        """Newest valid checkpoint as ``(progress, arrays, meta)``, or
        None. Anything stale, corrupt, or config-mismatched is DISCARDED
        with a warning — a resume never trusts it, and the files are
        unlinked so the next write starts clean."""
        if not self.enabled:
            return None
        d = self._dir()
        with self._lock:
            try:
                names = sorted((n for n in os.listdir(d)
                                if n.startswith("ckpt-")
                                and n.endswith(".json")), reverse=True)
            except OSError:
                return None
            failpoints.fire(FP_CKPT_PRE_READ)
            for name in names:
                sidecar = os.path.join(d, name)
                payload = sidecar[:-5] + ".npz"
                got = self._load_one(sidecar, payload)
                if got is not None:
                    return got
        return None

    def _load_one(self, sidecar: str, payload: str):
        def discard(why: str) -> None:
            log.warning("discarding fit checkpoint %s: %s", sidecar, why)
            _bump("discarded")
            for p in (sidecar, payload):
                try:
                    os.remove(p)
                except OSError:
                    pass

        try:
            with open(sidecar) as f:
                doc = json.load(f)
        except (OSError, ValueError) as exc:
            discard(f"unreadable sidecar ({exc})")
            return None
        want = self._key_doc()
        got = {k: doc.get(k) for k in want}
        if got != want:
            discard(f"key mismatch (have {got}, want {want})")
            return None
        epoch = int(doc.get("mesh_epoch", 0) or 0)
        if epoch > mesh_epoch():
            # Written by an incarnation newer than this process's epoch:
            # a concurrent pod owns this stream — never resume its
            # partial progress from here.
            discard(f"mesh epoch {epoch} is newer than ours "
                    f"({mesh_epoch()})")
            return None
        try:
            with open(payload, "rb") as f:
                blob = f.read()
        except OSError as exc:
            discard(f"payload unreadable ({exc})")
            return None
        if zlib.crc32(blob) != int(doc.get("crc32", -1)):
            discard("payload CRC32 mismatch (torn or rotten)")
            return None
        try:
            with np.load(io.BytesIO(blob), allow_pickle=False) as npz:
                arrays = {k: npz[k] for k in npz.files}
        except Exception as exc:  # noqa: BLE001 — any decode failure = torn
            discard(f"payload decode failed ({exc})")
            return None
        meta = dict(doc.get("meta") or {})
        meta["mesh_epoch"] = epoch
        return int(doc["progress"]), arrays, meta

    def clear(self) -> None:
        """Drop the stream (fit completed — its progress is now fully
        represented by the persisted model / prediction dataset)."""
        d = self._dir()
        with self._lock:
            try:
                for name in os.listdir(d):
                    try:
                        os.remove(os.path.join(d, name))
                    except OSError:
                        pass
                os.rmdir(d)
            except OSError:
                pass


def context(cfg: Settings, *, dataset: str, family: str, config: Any,
            snapshot: str, every: Optional[int] = None) -> FitContext:
    """Build a checkpoint context; ``config`` may be any JSON-able doc
    (hashed here). ``every`` defaults to ``cfg.fit_ckpt_rounds``."""
    return FitContext(
        cfg=cfg, dataset=dataset, family=family,
        config=config if isinstance(config, str) else config_hash(config),
        snapshot=str(snapshot),
        every=int(cfg.fit_ckpt_rounds if every is None else every))
