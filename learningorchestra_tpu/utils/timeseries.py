"""Telemetry history — persistent time-series over the metrics registry.

Every signal PRs 9–10 added is point-in-time and process-lifetime: a
restart, a crash, or simply not being scraped at the right second erases
the evidence. Production pipelines are debugged from *retained*
telemetry (PAPERS 1909.10389's pipeline monitoring; 1612.01437's
post-hoc bottleneck analysis), and the upcoming multi-tenant scheduler
needs historical queue/latency series as its cost signal. This module
is that memory:

- :class:`TelemetryHistory` flattens the ``/metrics`` registry document
  into named numeric series (``serving.models.<m>.p99_ms``,
  ``resources.host.rss_bytes``, ...) and appends one sample per
  ``LO_TPU_TELEMETRY_SAMPLE_S`` into a bounded in-memory ring — fed by
  a background sampler thread, so history accrues whether or not
  anything scrapes the server (registry reads also contribute, gated to
  the same cadence, so the two feeds never double-sample);
- every ``LO_TPU_TELEMETRY_SEGMENT_SAMPLES`` samples the ring rotates a
  **delta-encoded segment** (first record full, subsequent records only
  the keys whose value changed) to ``<store_root>/_telemetry/``, with
  bounded retention — history survives restarts without ever growing
  unboundedly;
- :meth:`TelemetryHistory.query` merges disk segments with the live
  ring and serves ``GET /metrics/history?series=&window=``, the burn-
  rate alert rules (utils/alerts.py), the status-page sparklines, and
  the flight recorder's "surrounding window" capture.

Samples are wall-clock stamped (``time.time()``) because they must be
comparable across restarts; the monotonic clock resets with the
process.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from learningorchestra_tpu.config import Settings
from learningorchestra_tpu.utils.structlog import get_logger

log = get_logger("timeseries")

#: Series-name paths excluded from flattening: per-rule alert state is
#: bookkeeping about evaluation, not a signal worth a series each, and
#: per-dataset disk byte walks would mint one series per dataset name.
_EXCLUDE_PREFIXES = ("alerts.rules.", "resources.disk.datasets.",
                     "ops.")


def flatten_doc(doc: Dict[str, Any], prefix: str = "",
                out: Optional[Dict[str, float]] = None) -> Dict[str, float]:
    """Flatten nested numeric leaves of a metrics document into
    ``{"a.b.c": value}`` series samples. Lists (histogram buckets),
    strings and booleans are skipped — series are scalars by
    construction."""
    if out is None:
        out = {}
    for key, val in doc.items():
        name = f"{prefix}{key}"
        if any(name.startswith(p) for p in _EXCLUDE_PREFIXES):
            continue
        if isinstance(val, dict):
            flatten_doc(val, f"{name}.", out)
        elif isinstance(val, (int, float)) and not isinstance(val, bool):
            out[name] = float(val)
    return out


def _encode_segment(samples: List[Tuple[float, Dict[str, float]]]) -> str:
    """Delta-encode one segment: the first record carries the full
    sample (``v``), later records only the keys whose value changed
    (``d``) plus the keys that disappeared (``x``) — counters mostly
    move a few keys per tick, so segments stay small without a binary
    format."""
    lines: List[str] = []
    prev: Optional[Dict[str, float]] = None
    for t, values in samples:
        if prev is None:
            lines.append(json.dumps({"t": round(t, 3), "v": values},
                                    sort_keys=True))
        else:
            delta = {k: v for k, v in values.items() if prev.get(k) != v}
            gone = sorted(k for k in prev if k not in values)
            rec: Dict[str, Any] = {"t": round(t, 3), "d": delta}
            if gone:
                rec["x"] = gone
            lines.append(json.dumps(rec, sort_keys=True))
        prev = values
    return "\n".join(lines) + "\n"


def _decode_segment(text: str) -> List[Tuple[float, Dict[str, float]]]:
    """Inverse of :func:`_encode_segment`. A torn tail line (crash mid
    write) is dropped rather than poisoning the whole segment."""
    out: List[Tuple[float, Dict[str, float]]] = []
    current: Dict[str, float] = {}
    for line in text.splitlines():
        if not line.strip():
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            break                       # torn tail: keep the good prefix
        if "v" in rec:
            current = dict(rec["v"])
        else:
            current = dict(current)
            current.update(rec.get("d") or {})
            for k in rec.get("x") or ():
                current.pop(k, None)
        out.append((float(rec["t"]), current))
    return out


class TelemetryHistory:
    """Bounded metric time-series: in-memory ring + rotating on-disk
    delta segments under ``<store_root>/_telemetry/``.

    ``source`` is the snapshot thunk the background sampler invokes
    (the App's ``_metrics_doc`` — whose body calls :meth:`observe`, so
    thread ticks and operator scrapes feed one gated recording path).
    """

    def __init__(self, cfg: Settings,
                 source: Optional[Callable[[], Any]] = None):
        self.cfg = cfg
        self._source = source
        self._lock = threading.Lock()
        self._ring: "deque[Tuple[float, Dict[str, float]]]" = deque(
            maxlen=max(1, int(cfg.telemetry_ring_samples)))
        #: Samples recorded since the last segment rotation (suffix of
        #: the ring — kept separately so rotation never re-writes what a
        #: previous segment already persisted).
        self._pending: List[Tuple[float, Dict[str, float]]] = []
        self._last_sample: Optional[float] = None
        self._counters = {"samples": 0, "segments_written": 0,
                          "segments_loaded": 0, "sampler_errors": 0}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._segments: List[str] = []
        if self.enabled:
            self._load_segments()

    # -- properties ----------------------------------------------------------

    @property
    def enabled(self) -> bool:
        return float(self.cfg.telemetry_sample_s) >= 0

    @property
    def root(self) -> str:
        return os.path.join(self.cfg.store_root, "_telemetry")

    # -- recording -----------------------------------------------------------

    def observe(self, doc: Dict[str, Any],
                now: Optional[float] = None) -> bool:
        """Record one flattened sample of ``doc``, gated to at most one
        per ``telemetry_sample_s`` (0 = every call — how tests drive
        history deterministically). Returns whether a sample landed."""
        if not self.enabled:
            return False
        # Millisecond-rounded at the source so the ring and the disk
        # encoding carry the IDENTICAL timestamp — the window() merge
        # dedupes rotated samples by exact t.
        now = round(time.time() if now is None else now, 3)
        gate = float(self.cfg.telemetry_sample_s)
        with self._lock:
            # Cheap pre-check BEFORE flattening: under frequent
            # scraping nearly every read is gated out, and walking
            # hundreds of doc leaves just to discard the result would
            # tax the scrape path for nothing.
            if (self._last_sample is not None
                    and now - self._last_sample < gate):
                return False
        values = flatten_doc(doc)
        rotate: Optional[List[Tuple[float, Dict[str, float]]]] = None
        with self._lock:
            if (self._last_sample is not None
                    and now - self._last_sample < gate):
                return False              # raced another recorder
            self._last_sample = now
            self._ring.append((now, values))
            self._pending.append((now, values))
            self._counters["samples"] += 1
            if len(self._pending) >= max(
                    1, int(self.cfg.telemetry_segment_samples)):
                rotate, self._pending = self._pending, []
        if rotate:
            self._write_segment(rotate)
        return True

    def flush(self) -> None:
        """Persist the partial pending segment (graceful shutdown — the
        restarted process serves this window from disk)."""
        with self._lock:
            rotate, self._pending = self._pending, []
        if rotate:
            self._write_segment(rotate)

    # -- disk segments -------------------------------------------------------

    def _write_segment(self, samples: List[Tuple[float, Dict[str, float]]]
                       ) -> None:
        try:
            os.makedirs(self.root, exist_ok=True)
            t0 = samples[0][0]
            path = os.path.join(self.root, f"seg-{int(t0 * 1000):015d}.jsonl")
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                f.write(_encode_segment(samples))
            os.replace(tmp, path)
            with self._lock:
                self._counters["segments_written"] += 1
                self._segments.append(path)
                self._segments.sort()
                doomed = self._segments[:-max(
                    1, int(self.cfg.telemetry_retention_segments))]
                self._segments = self._segments[len(doomed):]
            for old in doomed:
                try:
                    os.unlink(old)
                except OSError:
                    pass
        except OSError as exc:
            # History is best-effort: a full disk must degrade telemetry,
            # never the serving path that happened to trigger a rotation.
            log.warning("telemetry segment write failed: %s", exc)

    def _load_segments(self) -> None:
        """Index existing segments at startup — queries decode them on
        demand, so ``/metrics/history`` serves the pre-restart window
        immediately without reading every file up front."""
        try:
            if not os.path.isdir(self.root):
                return
            self._segments = sorted(
                os.path.join(self.root, fn)
                for fn in os.listdir(self.root)
                if fn.startswith("seg-") and fn.endswith(".jsonl"))
            self._counters["segments_loaded"] = len(self._segments)
        except OSError as exc:
            log.warning("telemetry segment scan failed: %s", exc)

    @staticmethod
    def _segment_t0(path: str) -> float:
        try:
            return int(os.path.basename(path)[4:-6]) / 1000.0
        except ValueError:
            return 0.0

    def _disk_samples(self, since: float, until: float
                      ) -> List[Tuple[float, Dict[str, float]]]:
        out: List[Tuple[float, Dict[str, float]]] = []
        with self._lock:
            segments = list(self._segments)
        starts = [self._segment_t0(p) for p in segments]
        for i, path in enumerate(segments):
            if starts[i] > until:
                continue
            # Segments are chronological: everything in this one
            # precedes the NEXT segment's first sample, so a segment
            # entirely before the window is skipped WITHOUT decoding —
            # the hot paths (burn windows, sparklines, bundles) must
            # not re-parse hours of dead history per call. The newest
            # segment has no upper bound and always decodes.
            if i + 1 < len(segments) and starts[i + 1] <= since:
                continue
            try:
                with open(path, encoding="utf-8") as f:
                    samples = _decode_segment(f.read())
            except OSError:
                continue
            out.extend(s for s in samples if since <= s[0] <= until)
        return out

    # -- queries -------------------------------------------------------------

    def window(self, window_s: Optional[float] = None,
               now: Optional[float] = None
               ) -> List[Tuple[float, Dict[str, float]]]:
        """Samples within the trailing window (disk + ring, start-
        ordered, deduplicated by timestamp — rotated samples exist in
        both)."""
        now = time.time() if now is None else now
        since = now - float(window_s) if window_s else 0.0
        with self._lock:
            ring = [s for s in self._ring if since <= s[0] <= now]
        ring_start = ring[0][0] if ring else now
        disk = self._disk_samples(since, min(now, ring_start))
        seen = {t for t, _ in ring}
        merged = [s for s in disk if s[0] not in seen] + ring
        merged.sort(key=lambda s: s[0])
        return merged

    def query(self, series: Optional[List[str]] = None,
              window_s: Optional[float] = None,
              now: Optional[float] = None) -> Dict[str, Any]:
        """The ``GET /metrics/history`` body: per-series ``[t, value]``
        points. ``series`` entries match exactly or as dotted prefixes
        (``serving`` matches every ``serving.*`` series)."""
        samples = self.window(window_s, now)

        def match(name: str) -> bool:
            if not series:
                return True
            return any(name == s or name.startswith(s.rstrip(".") + ".")
                       for s in series)

        out: Dict[str, List[List[float]]] = {}
        for t, values in samples:
            for name, val in values.items():
                if match(name):
                    out.setdefault(name, []).append([round(t, 3), val])
        return {
            "window_s": window_s,
            "samples": len(samples),
            "from": round(samples[0][0], 3) if samples else None,
            "to": round(samples[-1][0], 3) if samples else None,
            "series": out,
        }

    def series_names(self) -> List[str]:
        with self._lock:
            newest = self._ring[-1][1] if self._ring else {}
        return sorted(newest)

    def snapshot(self) -> Dict[str, Any]:
        """The ``telemetry`` section of ``/metrics``."""
        with self._lock:
            doc = dict(self._counters)
            doc["ring_samples"] = len(self._ring)
            doc["pending_samples"] = len(self._pending)
            doc["segments"] = len(self._segments)
            doc["series"] = len(self._ring[-1][1]) if self._ring else 0
        doc["sample_s"] = float(self.cfg.telemetry_sample_s)
        return doc

    # -- the sampler thread --------------------------------------------------

    def start(self) -> None:
        """Start the background sampler (idempotent; no-op when the
        cadence knob is 0 — read-driven mode — or negative)."""
        if self._source is None or float(self.cfg.telemetry_sample_s) <= 0:
            return
        with self._lock:
            if self._thread is not None:
                return
            # A previous stop() latched the event; a serve→stop→serve
            # cycle must get a live sampler again, not a thread that
            # exits on its first wait.
            self._stop.clear()
            # thread-lifecycle: owner=TelemetryHistory; exits when
            # stop() sets the _stop event (joined there, bounded);
            # daemon so an App that never serves cannot hang interpreter
            # exit behind a sleeping sampler.
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="lo-telemetry")
            self._thread.start()

    def _run(self) -> None:
        period = float(self.cfg.telemetry_sample_s)
        while not self._stop.wait(period):
            try:
                # The source (App._metrics_doc) calls observe() itself —
                # one recording seam whether the tick or a scrape fires.
                self._source()
            except Exception as exc:  # noqa: BLE001 — sampling never kills
                with self._lock:
                    self._counters["sampler_errors"] += 1
                log.warning("telemetry sampler tick failed: %s", exc)

    def stop(self) -> None:
        """Stop the sampler and flush the partial segment so a restart
        serves this window from disk."""
        self._stop.set()
        with self._lock:
            thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=5.0)
        if self.enabled:
            self.flush()
