from learningorchestra_tpu.viz.service import (  # noqa: F401
    ImageService, create_embedding_image)
