"""Scatter-plot PNG rendering for the embedding services.

Mirrors the reference's seaborn scatterplot with optional label hue and
``savefig`` to the images volume (reference tsne.py:90-102, pca.py:90-98).
Headless matplotlib (Agg backend) — no display in TPU-VM containers.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

import matplotlib

matplotlib.use("Agg")

import matplotlib.pyplot as plt  # noqa: E402
import seaborn as sns  # noqa: E402


def save_scatter(embedding: np.ndarray, path: str,
                 labels: Optional[np.ndarray] = None,
                 title: str = "") -> str:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    fig, ax = plt.subplots(figsize=(8, 8))
    hue = None
    if labels is not None:
        hue = np.asarray(labels).astype(str)
    sns.scatterplot(x=embedding[:, 0], y=embedding[:, 1], hue=hue,
                    s=12, linewidth=0, ax=ax,
                    palette="deep" if hue is not None else None)
    if title:
        ax.set_title(title)
    fig.savefig(path, dpi=120, bbox_inches="tight")
    plt.close(fig)
    return path
