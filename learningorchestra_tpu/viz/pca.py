"""PCA embedding — device-side, mesh-scalable.

The reference's pca microservice Spark-loads the collection then runs
single-node ``sklearn.decomposition.PCA(n_components=2)`` on the driver
(reference pca.py:74-98) — the gather-to-driver cliff SURVEY.md §3.4 calls
out. TPU-native design: the d×d Gram matrix is one MXU contraction over the
row-sharded design matrix (XLA all-reduces the sharded row axis over ICI),
and the eigendecomposition of that tiny matrix runs on device — no row data
ever leaves the devices, so HIGGS-11M (11M × 28) costs one pass of
streaming matmul instead of an 11M-row driver collect.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from learningorchestra_tpu.parallel.mesh import MeshRuntime


@partial(jax.jit, static_argnames=("k",))
def _pca_project(X, n_valid, *, k):
    n, d = X.shape
    mask = (jnp.arange(n) < n_valid)[:, None].astype(jnp.float32)
    Xm = X * mask
    count = jnp.maximum(n_valid.astype(jnp.float32), 1.0)
    mean = Xm.sum(axis=0) / count
    Xc = (X - mean) * mask
    cov = (Xc.T @ Xc) / count                  # (d, d) — MXU + ICI psum
    evals, evecs = jnp.linalg.eigh(cov)        # ascending
    comps = evecs[:, ::-1][:, :k]              # top-k components (d, k)
    var = evals[::-1][:k]
    return Xc @ comps, var


def pca_embed(runtime: MeshRuntime, X: np.ndarray,
              k: int = 2) -> np.ndarray:
    """(n, d) host matrix → (n, k) principal-component embedding."""
    from learningorchestra_tpu.parallel import spmd

    spmd.require_single_process("pca")
    X_dev, n = runtime.shard_rows(np.asarray(X, np.float32))
    emb, _ = _pca_project(X_dev, runtime.replicate(np.int32(n)), k=k)
    return np.asarray(emb)[:n]
