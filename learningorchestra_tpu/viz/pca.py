"""PCA embedding — device-side, mesh-scalable.

The reference's pca microservice Spark-loads the collection then runs
single-node ``sklearn.decomposition.PCA(n_components=2)`` on the driver
(reference pca.py:74-98) — the gather-to-driver cliff SURVEY.md §3.4 calls
out. TPU-native design: the d×d Gram matrix is one MXU contraction over the
row-sharded design matrix (XLA all-reduces the sharded row axis over ICI),
and the eigendecomposition of that tiny matrix runs on device — no row data
ever leaves the devices, so HIGGS-11M (11M × 28) costs one pass of
streaming matmul instead of an 11M-row driver collect.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from learningorchestra_tpu.parallel.mesh import MeshRuntime, host_rows


@partial(jax.jit, static_argnames=("k",))
def _pca_project(X, n_valid, *, k):
    n, d = X.shape
    mask = (jnp.arange(n) < n_valid)[:, None].astype(jnp.float32)
    Xm = X * mask
    count = jnp.maximum(n_valid.astype(jnp.float32), 1.0)
    mean = Xm.sum(axis=0) / count
    Xc = (X - mean) * mask
    cov = (Xc.T @ Xc) / count                  # (d, d) — MXU + ICI psum
    evals, evecs = jnp.linalg.eigh(cov)        # ascending
    comps = evecs[:, ::-1][:, :k]              # top-k components (d, k)
    var = evals[::-1][:k]
    return Xc @ comps, var


def pca_embed(runtime: MeshRuntime, X: np.ndarray,
              k: int = 2) -> np.ndarray:
    """(n, d) host matrix → (n, k) principal-component embedding.

    Runs on multi-process pods too (every process calls this through the
    SPMD dispatch protocol): the embedding is row-sharded, so the
    host-side gather is ``host_rows`` (process_allgather when shards span
    processes), not a plain copy."""
    X = np.asarray(X, np.float32)
    if X.ndim != 2 or X.shape[1] < k:
        # Matches sklearn's n_components <= n_features contract (the
        # reference's PCA(2) likewise rejects 1-column data) but as a
        # clean client error instead of an IndexError mid-plot.
        raise ValueError(
            f"pca with {k} components needs at least {k} numeric feature "
            f"columns; dataset has {X.shape[1] if X.ndim == 2 else 0}")
    X_dev, n = runtime.shard_rows(X)
    emb, _ = _pca_project(X_dev, runtime.replicate(np.int32(n)), k=k)
    return host_rows(emb)[:n]
