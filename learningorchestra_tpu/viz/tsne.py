"""t-SNE embedding — TPU-native kNN-affinity + exact-repulsion KL descent.

The reference's tsne microservice Spark-loads the collection, gathers every
row to the driver, and runs single-core sklearn ``TSNE().fit_transform``
(reference tsne.py:74-102) — the headline capability SURVEY.md §3.4 says the
rebuild must actually improve. sklearn's Barnes-Hut tree is irregular and
hostile to XLA, so this is a re-design around what the MXU does well:

- **Affinities**: squared distances computed in (tile × n) blocks as one
  matmul per tile; ``lax.top_k`` keeps the 3·perplexity nearest neighbours
  (Barnes-Hut's sparse-attraction approximation); per-row bandwidths are
  bisected to the target perplexity *vectorized over all rows at once*.
- **Symmetrized sparse attraction, scatter-free**: TPU scatter-adds
  serialize (~94 ms for the 5.5M-edge transpose term at n=60k, vs 17 ms
  for the matching gather), so the directed kNN edge set is flipped ONCE
  on the host into a padded incoming-edge table. Per iteration the
  attraction is then a single dense gather + weighted reduction over
  ``k + max_in_degree`` columns — every directed edge still acts on both
  endpoints (exact symmetrization), but nothing scatters.
- **Exact repulsion**: the full n² q-sum, tiled as a ``lax.scan`` over row
  blocks of the (n, 2) embedding — dense, regular, VPU-friendly flops in
  place of Barnes-Hut's quadtree (≈6 flops/pair in 2-D: ~22 GFLOP/iter at
  n=60k, seconds/thousand-iters territory on one chip).
- Standard Kullback-Leibler descent schedule: early exaggeration ×12, then
  momentum 0.8 with per-coordinate gains, as in van der Maaten's reference
  implementation.

Multi-chip: the repulsion — the embed's entire asymptotic cost — row-shards
over the mesh data axis (each shard computes its row range against the
replicated (n, 2) embedding; Z partials psum over ICI and force rows
all-gather back), so a v5e-8 splits the O(n²) term 8 ways. The kNN/
calibration front-end stays replicated (it runs once, not per iteration).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as Pspec

from learningorchestra_tpu.ops import pallas_kernels
from learningorchestra_tpu.parallel.mesh import DATA_AXIS, MeshRuntime
from learningorchestra_tpu.viz.pca import pca_embed

_TILE = 1024


def _pad_rows(X: np.ndarray, multiple: int):
    n = len(X)
    pad = (-n) % multiple
    if pad:
        X = np.concatenate([X, np.full((pad,) + X.shape[1:], 1e7,
                                       X.dtype)])
    return X, n


@partial(jax.jit, static_argnames=("k", "tile"))
def _knn(X, *, k, tile):
    """Blocked kNN: per row, indices + squared distances of k nearest
    (excluding self). X: (n, d) padded to tile multiple."""
    n = X.shape[0]
    sq = (X * X).sum(axis=1)

    def block(carry, i):
        rows = jax.lax.dynamic_slice_in_dim(X, i * tile, tile)
        rsq = jax.lax.dynamic_slice_in_dim(sq, i * tile, tile)
        d2 = rsq[:, None] + sq[None, :] - 2.0 * (rows @ X.T)
        row_ids = i * tile + jnp.arange(tile)
        d2 = jnp.where(jnp.arange(n)[None, :] == row_ids[:, None],
                       jnp.inf, d2)                      # mask self
        neg, idx = jax.lax.top_k(-d2, k)
        return carry, (-neg, idx)

    _, (d2k, idxk) = jax.lax.scan(block, None, jnp.arange(n // tile))
    return d2k.reshape(n, k), idxk.reshape(n, k)


@jax.jit
def _calibrate(d2k, perplexity):
    """Bisect per-row precision beta to hit the target perplexity, all rows
    at once. d2k: (n, k) squared distances to neighbours."""
    target = jnp.log(perplexity)
    d2 = d2k - d2k[:, :1]                         # stabilize exponent

    def entropy(beta):
        w = jnp.exp(-d2 * beta[:, None])
        s = w.sum(axis=1)
        h = jnp.log(s) + beta * (d2 * w).sum(axis=1) / s
        return h, w / s[:, None]

    def body(carry, _):
        lo, hi, beta = carry
        h, _ = entropy(beta)
        too_high = h > target                     # entropy too high → raise beta
        lo = jnp.where(too_high, beta, lo)
        hi = jnp.where(too_high, hi, beta)
        beta = jnp.where(jnp.isinf(hi), lo * 2.0, (lo + hi) / 2.0)
        return (lo, hi, beta), None

    n = d2.shape[0]
    init = (jnp.zeros(n), jnp.full(n, jnp.inf), jnp.ones(n))
    (lo, hi, beta), _ = jax.lax.scan(body, init, None, length=50)
    _, P = entropy(beta)
    return P                                       # (n, k) row-normalized


def _rep_rows_scan(Yq, vq, Y, valid, offset, *, tile):
    """Pure-XLA repulsion for global rows [offset, offset+len(Yq)) against
    all columns — the scan twin of the Pallas ``tsne_repulsion_rows``."""
    n = Y.shape[0]
    nq = Yq.shape[0]
    ysq = (Y * Y).sum(axis=1)

    def rep_block(carry, i):
        Z_acc, F = carry
        rows = jax.lax.dynamic_slice_in_dim(Yq, i * tile, tile)
        vrows = jax.lax.dynamic_slice_in_dim(vq, i * tile, tile)
        rsq = (rows * rows).sum(axis=1)
        d2 = rsq[:, None] + ysq[None, :] - 2.0 * (rows @ Y.T)
        q = 1.0 / (1.0 + d2)
        row_ids = offset + i * tile + jnp.arange(tile)
        pair_valid = (valid[None, :] * vrows[:, None]
                      * (jnp.arange(n)[None, :] != row_ids[:, None]))
        q = q * pair_valid
        Z_acc = Z_acc + q.sum()
        # repulsive force numerator: sum_j q² (yi − yj)
        q2 = q * q
        f = rows * q2.sum(axis=1, keepdims=True) - q2 @ Y
        F = jax.lax.dynamic_update_slice_in_dim(F, f, i * tile, axis=0)
        return (Z_acc, F), None

    (Z, F), _ = jax.lax.scan(
        rep_block, (jnp.float32(0.0), jnp.zeros((nq, 2), Y.dtype)),
        jnp.arange(nq // tile))
    return Z, F


def _repulsion(Y, valid, *, tile, use_pallas, mesh):
    """(Z, F) over all pairs; row-sharded across the mesh data axis when
    it has >1 device: each shard computes its row range against the full
    (replicated, n×2 — tiny) embedding, Z partials psum over ICI, and the
    force rows all-gather back to replicated. This distributes the O(n²)
    term, the embed's entire asymptotic cost (the reference's tsne is
    single-core sklearn, reference tsne.py:74-102)."""
    n = Y.shape[0]
    ktile = min(tile, pallas_kernels.TILE)
    P_data = 1 if mesh is None else mesh.shape[DATA_AXIS]
    if P_data == 1:
        if use_pallas:
            return pallas_kernels.tsne_repulsion(Y, valid, tile=ktile)
        return _rep_rows_scan(Y, valid, Y, valid, 0, tile=tile)

    nloc = n // P_data

    def shard_fn(Yr, vr):
        k = jax.lax.axis_index(DATA_AXIS)
        off = k * nloc
        Yq = jax.lax.dynamic_slice_in_dim(Yr, off, nloc)
        vq = jax.lax.dynamic_slice_in_dim(vr, off, nloc)
        if use_pallas:
            Zp, Fp = pallas_kernels.tsne_repulsion_rows(
                Yq, vq, Yr, vr, off, tile=ktile)
        else:
            Zp, Fp = _rep_rows_scan(Yq, vq, Yr, vr, off, tile=tile)
        return (jax.lax.psum(Zp, DATA_AXIS),
                jax.lax.all_gather(Fp, DATA_AXIS, axis=0, tiled=True))

    return jax.shard_map(
        shard_fn, mesh=mesh, in_specs=(Pspec(), Pspec()),
        out_specs=(Pspec(), Pspec()), check_vma=False,
    )(Y, valid)


def _edge_table(idx: np.ndarray, P: np.ndarray, n_pad: int,
                n_valid: int) -> tuple:
    """Flip the directed kNN edge set into one padded gather table
    (host-side, once per embed; the structure is static across all
    descent iterations).

    Every directed edge (i → j, p) exerts w·q·(y_i − y_j) on i and the
    opposite on j, with w = p / (2n) — the exact symmetrization the
    scatter-add expressed. Row i's table therefore holds its k outgoing
    neighbours followed by its incoming sources (padded with weight-0
    self edges), so the per-iteration attraction is one gather + dense
    reduction, no scatter.

    Incoming columns cap at 2k: kNN hubs (dense-cluster centers) can draw
    thousands of in-edges, and padding every row to the max in-degree
    explodes the table (observed 61k × 5.7k → OOM). Edges past the cap go
    to a COO overflow list handled by a small sorted scatter-add — exact
    same forces, just a different summation route for the hub tail.

    Returns (sym_idx (n_pad, K) int32, sym_w (n_pad, K) float32,
    ov_src (m,) int32, ov_dst (m,) int32, ov_w (m,) float32).
    """
    n, k = idx.shape
    cap = 2 * k
    wmat = (P / (2.0 * max(n_valid, 1))).astype(np.float32)
    # kNN should never select a padding row (they sit at distance ~1e14),
    # but a zero weight makes that a guarantee rather than an assumption.
    wmat[idx >= n_valid] = 0.0
    src = np.repeat(np.arange(n, dtype=np.int64), k)
    dst = idx.reshape(-1).astype(np.int64)
    w = wmat.reshape(-1)
    keep = dst < n_valid
    src, dst, w = src[keep], dst[keep], w[keep]
    order = np.argsort(dst, kind="stable")
    src, dst, w = src[order], dst[order], w[order]
    starts = np.searchsorted(dst, np.arange(n_pad))
    rank = np.arange(len(dst)) - starts[dst]
    counts = np.bincount(dst, minlength=n_pad) if len(dst) else \
        np.zeros(n_pad, np.int64)
    in_cols = int(min(counts.max(), cap)) if len(dst) else 0
    K = k + in_cols
    sym_idx = np.tile(np.arange(n_pad, dtype=np.int32)[:, None], (1, K)) \
        if K else np.zeros((n_pad, 0), np.int32)
    sym_w = np.zeros((n_pad, K), np.float32)
    sym_idx[:n, :k] = idx
    sym_w[:n, :k] = wmat
    dense = rank < in_cols
    sym_idx[dst[dense], k + rank[dense]] = src[dense].astype(np.int32)
    sym_w[dst[dense], k + rank[dense]] = w[dense]
    ov = ~dense
    return (sym_idx, sym_w, src[ov].astype(np.int32),
            dst[ov].astype(np.int32), w[ov])


@partial(jax.jit, static_argnames=("tile", "use_pallas", "mesh"),
         donate_argnums=(0,))
def _step(Y, vel, gains, sym_idx, sym_w, ov_src, ov_dst, ov_w, n_valid,
          exaggeration, eta, momentum, *, tile, use_pallas=False,
          mesh=None):
    n = Y.shape[0]
    valid = (jnp.arange(n) < n_valid).astype(jnp.float32)

    # --- exact repulsion: tiled full-pairwise over the 2-D embedding,
    # row-sharded over the mesh data axis when available ---------------------
    Z, Frep = _repulsion(Y, valid, tile=tile, use_pallas=use_pallas,
                         mesh=mesh)
    Z = jnp.maximum(Z, 1e-12)

    # --- sparse symmetric attraction over the precomputed edge table -------
    # (scatter-free: see _edge_table; padding entries are weight-0 self
    # edges whose diff is exactly zero.)
    Yn = Y[sym_idx]                                # (n, K, 2) one gather
    diff = Y[:, None, :] - Yn
    d2e = (diff * diff).sum(axis=-1)
    qe = 1.0 / (1.0 + d2e)
    w = (sym_w * exaggeration) * qe
    Fattr = (w[..., None] * diff).sum(axis=1)
    if ov_dst.shape[0]:
        # Hub-tail overflow edges (beyond the dense cap): dst-sorted COO,
        # so the scatter-add takes the cheap indices_are_sorted lowering.
        dov = Y[ov_dst] - Y[ov_src]
        qov = 1.0 / (1.0 + (dov * dov).sum(axis=-1))
        fov = (ov_w * exaggeration * qov)[:, None] * dov
        Fattr = Fattr.at[ov_dst].add(fov, indices_are_sorted=True)

    grad = 4.0 * (Fattr - Frep / Z)
    # van der Maaten gains + momentum
    same_sign = jnp.sign(grad) == jnp.sign(vel)
    gains = jnp.where(same_sign, gains * 0.8, gains + 0.2)
    gains = jnp.maximum(gains, 0.01)
    vel = momentum * vel - eta * gains * grad
    Y = (Y + vel) * valid[:, None]
    return Y, vel, gains


def tsne_embed(runtime: MeshRuntime, X: np.ndarray, *,
               perplexity: float = 30.0, iters: int = 750,
               exaggeration_iters: int = 250, eta: Optional[float] = None,
               seed: int = 0, pca_dims: int = 50,
               tile: int = _TILE) -> np.ndarray:
    """(n, d) host matrix → (n, 2) t-SNE embedding.

    Runs on multi-process pods too (every process calls this through the
    SPMD dispatch protocol). The kNN/calibration front end is computed
    per-process on local devices (deterministic — same input, same
    program) and handed to the descent loop as *host* arrays: jit treats
    numpy inputs as identical on every process and replicates them
    globally, so the sharded-repulsion ``shard_map`` over the global mesh
    sees consistent global arrays, and the iteration state it returns
    stays replicated across the loop."""
    X = np.asarray(X, np.float32)
    n, d = X.shape
    if d > pca_dims:
        X = pca_embed(runtime, X, k=pca_dims)  # standard PCA-50 front end
    tile = min(tile, 1 << max(3, (n - 1).bit_length() - 1))
    # Row-shard the O(n²) repulsion across the mesh data axis when each
    # shard still gets at least one full tile of rows; smaller problems
    # run single-device (they are sub-second anyway).
    mesh = runtime.mesh
    P_data = mesh.shape[DATA_AXIS]
    shard = P_data > 1 and n >= P_data * tile
    pad_to = tile * P_data if shard else tile
    Xp, n_valid = _pad_rows(X, pad_to)
    k = min(int(3 * perplexity), n - 1)

    # The fused kernel wants lane-width (≥128) tiles; tiny datasets use the
    # XLA scan path, which is compile-time-cheaper there anyway.
    use_pallas = bool(runtime.cfg.use_pallas) and tile >= 128
    step_mesh = mesh if shard else None
    # Sharded descent needs *global replicated* device inputs (a pod's
    # shard_map spans processes; per-process local arrays would not line
    # up). Unsharded small problems stay on one local device. Either way
    # everything is placed on device ONCE before the loop — per-iteration
    # host transfers would dominate at this problem size.
    put = runtime.replicate if step_mesh is not None else jnp.asarray

    d2k, idx_dev = _knn(jnp.asarray(Xp), k=k, tile=tile)
    P_cal = _calibrate(d2k[:n_valid], jnp.float32(perplexity))
    # kNN/calibration run per-process on local devices (deterministic);
    # the edge table is built on host (also deterministic) so `put` can
    # place it replicated globally.
    table = _edge_table(
        np.asarray(idx_dev)[:n_valid], np.asarray(P_cal), len(Xp), n_valid)
    sym_idx, sym_w, ov_src, ov_dst, ov_w = (put(a) for a in table)

    rng = np.random.default_rng(seed)
    Y = put(rng.normal(scale=1e-4, size=(len(Xp), 2)).astype(np.float32))
    vel = put(np.zeros((len(Xp), 2), np.float32))
    gains = put(np.ones((len(Xp), 2), np.float32))
    if eta is None:
        eta = max(float(n_valid) / 12.0 / 4.0, 50.0)  # learning rate n/48
    nv = put(np.float32(n_valid))
    eta_d = put(np.float32(eta))
    exag_d = {True: put(np.float32(12.0)), False: put(np.float32(1.0))}
    mom_d = {True: put(np.float32(0.5)), False: put(np.float32(0.8))}

    # XLA's CPU backend can deadlock when collective programs pipeline
    # deeply (in-flight runs share one thunk pool; a later run's
    # rendezvous threads can starve an earlier run's stragglers on
    # oversubscribed hosts). The CPU mesh is the multi-chip simulation
    # rig, so serialize steps there; TPU keeps the async dispatch queue.
    sync_steps = step_mesh is not None and jax.default_backend() == "cpu"
    for it in range(iters):
        early = it < exaggeration_iters
        Y, vel, gains = _step(Y, vel, gains, sym_idx, sym_w, ov_src,
                              ov_dst, ov_w, nv, exag_d[early], eta_d,
                              mom_d[early], tile=tile,
                              use_pallas=use_pallas, mesh=step_mesh)
        if sync_steps:
            jax.block_until_ready(Y)
    return np.asarray(Y)[:n_valid]
