"""Embedding-image service core: tsne/pca create + image CRUD.

The reference ships two near-identical microservices (tsne_image/,
pca_image/ — SURVEY.md components #6/#7): validate (PNG not already on
disk, parent exists, label ∈ fields), Spark-load, embed, save PNG to an
images volume, and full CRUD over the PNGs (server.py:57-155 in each).
Here one service hosts both methods; the embed runs on the mesh
(viz/pca.py, viz/tsne.py) instead of driver-side sklearn.
"""

from __future__ import annotations

import os
from typing import List, Optional

from learningorchestra_tpu.catalog.store import DatasetStore, validate_name
from learningorchestra_tpu.config import Settings, settings as global_settings
from learningorchestra_tpu.ops.preprocess import design_matrix
from learningorchestra_tpu.parallel.mesh import MeshRuntime
from learningorchestra_tpu.viz.pca import pca_embed
from learningorchestra_tpu.viz.plotting import save_scatter
from learningorchestra_tpu.viz.tsne import tsne_embed


class ImageExists(ValueError):
    pass


class ImageNotFound(KeyError):
    pass


def create_embedding_image(store: DatasetStore, runtime: MeshRuntime,
                           method: str, parent: str, image_name: str,
                           label: Optional[str] = None,
                           image_root: Optional[str] = None,
                           marker: Optional[str] = None,
                           **embed_kwargs) -> str:
    """Embed ``parent``'s numeric matrix with tsne|pca and save the PNG.

    Synchronous core; the serving layer runs it under JobManager (the
    reference's POST also returns before the PNG exists and clients GET
    until 200). Label-encoding of string columns before embedding matches
    the reference's LabelEncoder pass (tsne.py:82-86).

    Multi-process pods dispatch the embed to every worker first — the
    reference ran tsne/pca's data load through the shared Spark tier
    (reference tsne.py:74-80), so a pod deployment must serve them too.
    The spec pins the row count and fitted preprocessing state so workers
    rebuild bit-identical design matrices from the shared store; PNG
    rendering stays process-0 business.
    """
    from learningorchestra_tpu.parallel import spmd

    cfg_root = image_root or global_settings.image_root
    parent_ds = store.get(parent)
    if label is not None and label not in parent_ds.metadata.fields:
        raise ValueError(f"label field not in dataset: {label}")
    if method not in ("pca", "tsne"):
        raise ValueError(f"unknown embedding method: {method}")
    X, y, feature_fields, state = design_matrix(parent_ds,
                                                label or "__none__")

    def embed():
        if method == "pca":
            return pca_embed(runtime, X)
        return tsne_embed(runtime, X, **embed_kwargs)

    with spmd.dispatch_job(store, (parent,), {
            "op": "embed", "method": method, "parent": parent,
            "label": label, "n_rows": int(len(X)),
            "state": spmd.jsonable_state(state),
            "feature_fields": list(feature_fields),
            "embed_kwargs": embed_kwargs},
            outputs=(marker,) if marker else ()):
        emb = embed()
    labels = None
    if label is not None:
        labels = parent_ds.columns[label]
    path = os.path.join(cfg_root, method, f"{image_name}.png")
    return save_scatter(emb, path, labels=labels,
                        title=f"{method} of {parent}")


class ImageService:
    """CRUD over generated PNGs (reference tsne_image/server.py:57-155)."""

    def __init__(self, method: str, cfg: Optional[Settings] = None):
        self.method = method
        self.cfg = cfg or global_settings

    def _path(self, name: str) -> str:
        # Image names arrive from the REST API and become file paths.
        validate_name(name)
        return os.path.join(self.cfg.image_root, self.method, f"{name}.png")

    def exists(self, name: str) -> bool:
        return os.path.isfile(self._path(name))

    def validate_new(self, name: str) -> None:
        if self.exists(name):
            raise ImageExists(name)

    def get_path(self, name: str) -> str:
        p = self._path(name)
        if not os.path.isfile(p):
            raise ImageNotFound(name)
        return p

    def list_names(self) -> List[str]:
        root = os.path.join(self.cfg.image_root, self.method)
        if not os.path.isdir(root):
            return []
        return sorted(f[:-4] for f in os.listdir(root) if f.endswith(".png"))

    def delete(self, name: str) -> None:
        os.remove(self.get_path(name))
