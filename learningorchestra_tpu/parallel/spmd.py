"""SPMD job dispatch — how one HTTP process drives a multi-process mesh.

The reference scales out by sending Spark jobs from a driver service to a
standalone master that fans work across worker JVMs (reference
docker-compose.yml:123-163, model_builder.py:70-95). Under ``jax.distributed``
the equivalent constraint is SPMD: every process in the pod must execute the
same jitted computations in the same order, or the collectives XLA emits
(psum/all_gather over ICI/DCN) deadlock. But jobs arrive dynamically over
HTTP on one process only.

Design: **process 0 owns the catalog and the REST surface; every other
process runs a worker loop** (`worker_loop`). Before process 0 runs a mesh
computation for a job, it sends a job spec to every worker over a
persistent TCP channel (newline-delimited JSON — the minimal analogue of
the reference's Spark RPC control plane, ports 7077/41352 + py4j). A
device collective cannot play this role: workers idle between jobs, and
collective rendezvous carries initialization/barrier timeouts (Gloo's 30 s
handshake on CPU), so the control plane must tolerate unbounded idle —
TCP recv does. Workers decode the spec, rebuild identical host inputs
from the *shared dataset store* (the data plane replacing Mongo, which
played exactly this role for Spark executors), and execute the same
sequence of jitted calls. Results live replicated or are all-gathered;
process 0 persists them, workers discard.

The channel's address defaults to the jax.distributed coordinator host
(process 0) at ``LO_TPU_JOB_PORT`` (coordinator port + 1 when unset).

Single-process runs (and the CPU-mesh test rig) skip all of this: every
entry point no-ops when ``jax.process_count() == 1``.
"""

from __future__ import annotations

import json
import logging
import os
import socket
import threading
import time
from typing import Any, Dict, List, Optional

log = logging.getLogger("lo_tpu.spmd")


def is_multiprocess() -> bool:
    import jax

    return jax.process_count() > 1


def _job_addr() -> tuple:
    """(host, port) of the job channel — coordinator host, port + 1."""
    coord = os.environ.get("LO_TPU_COORDINATOR", "127.0.0.1:8476")
    host, _, port = coord.rpartition(":")
    job_port = int(os.environ.get("LO_TPU_JOB_PORT", int(port) + 1))
    return host or "127.0.0.1", job_port


class _JobChannel:
    """Process-0 end: accepts one connection per worker, fans job specs
    out as JSON lines. Worker connections are accepted lazily in the
    background so the server can start before (or after) its workers."""

    def __init__(self, n_workers: int):
        self.n_workers = n_workers
        self._lock = threading.Lock()
        self._conns: List[socket.socket] = []
        _, port = _job_addr()
        self._srv = socket.create_server(("", port))
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name="lo-spmd-accept")
        t.start()

    def _accept_loop(self) -> None:
        while True:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                self._conns.append(conn)

    def send(self, spec: Dict[str, Any]) -> None:
        """Block until every worker is connected, then fan out the spec."""
        deadline = time.time() + 120.0
        while True:
            with self._lock:
                if len(self._conns) >= self.n_workers:
                    break
            if time.time() > deadline:
                raise TimeoutError(
                    f"only {len(self._conns)}/{self.n_workers} workers "
                    "connected to the job channel")
            time.sleep(0.05)
        data = (json.dumps(spec) + "\n").encode("utf-8")
        with self._lock:
            for conn in self._conns:
                conn.sendall(data)


_channel: Optional[_JobChannel] = None
_channel_lock = threading.Lock()
_dispatch_lock = threading.Lock()


def _get_channel() -> _JobChannel:
    import jax

    global _channel
    with _channel_lock:
        if _channel is None:
            _channel = _JobChannel(jax.process_count() - 1)
        return _channel


def dispatch(spec: Dict[str, Any]) -> None:
    """Process-0 side: announce the next mesh job to every worker. No-op
    single-process. Caller must then execute exactly the device-op
    sequence `run_job` executes for this spec."""
    if not is_multiprocess():
        return
    _get_channel().send(spec)


class dispatch_guard:
    """Serializes mesh jobs under multi-process operation.

    Collective programs from concurrently dispatched jobs would interleave
    differently on each process and deadlock; the guard makes dispatch +
    compute atomic. Single-process mode is a no-op (concurrent fits stay
    overlapped, the FAIR-scheduler behavior)."""

    def __enter__(self):
        if is_multiprocess():
            _dispatch_lock.acquire()
        return self

    def __exit__(self, *exc):
        if is_multiprocess():
            _dispatch_lock.release()
        return False


# -- worker side -------------------------------------------------------------

def run_build_job(store, runtime, spec: Dict[str, Any]) -> None:
    """Execute a model-build job's device-op sequence, mirroring
    ``ModelBuilder.build``'s per-classifier compute exactly (fit →
    predict_proba with the same shapes, same order). Host-only work
    (persistence, prediction datasets, metrics) is process-0 business and
    is skipped here."""
    from learningorchestra_tpu.models.registry import get_trainer
    from learningorchestra_tpu.ops import preprocess

    train_ds = store.load(spec["train"])
    test_ds = store.load(spec["test"])
    steps = spec.get("steps") or ()
    label = spec["label"]
    hparams = spec.get("hparams") or {}
    X_train, y_train, ff, state = preprocess.design_matrix(
        train_ds, label, steps)
    X_test, y_test, _, _ = preprocess.design_matrix(
        test_ds, label, steps, state=state, feature_fields=ff)
    # The spec pins process 0's snapshot: an ingest commit between its
    # save and this load may have appended rows, and a shape mismatch
    # would wedge every collective. Rows only ever append, so truncating
    # reproduces the snapshot prefix.
    n_train, n_test = spec.get("n_train"), spec.get("n_test")
    if n_train is not None:
        if len(X_train) < n_train or len(X_test) < n_test:
            raise RuntimeError(
                f"worker sees fewer rows than the dispatched snapshot "
                f"({len(X_train)}/{n_train} train, {len(X_test)}/{n_test} "
                "test) — shared store out of sync")
        X_train, y_train = X_train[:n_train], y_train[:n_train]
        X_test = X_test[:n_test]
        y_test = y_test[:n_test] if y_test is not None else None
    num_classes = int(max(int(y_train.max()) + 1,
                          2 if y_test is None else int(y_test.max()) + 1))
    for c in spec["classifiers"]:
        try:
            trainer = get_trainer(c)
            model = trainer(runtime, X_train, y_train, num_classes,
                            **hparams.get(c, {}))
            model.predict_proba(runtime, X_test)
        except Exception:  # noqa: BLE001 — mirror process 0's per-model boundary
            log.exception("worker fit %s failed", c)


def run_predict_job(store, runtime, spec: Dict[str, Any]) -> None:
    """Mirror ``ModelBuilder.predict``'s device ops for a re-served model."""
    from learningorchestra_tpu.models.persistence import ModelRegistry
    from learningorchestra_tpu.ops import preprocess

    registry = ModelRegistry(store.cfg)
    man, model = registry.load(spec["model"])
    pp = man["preprocess"]
    ds = store.load(spec["dataset"])
    X, _, _, _ = preprocess.design_matrix(
        ds, pp["label"], pp["steps"], state=pp["state"],
        feature_fields=pp["feature_fields"])
    n = spec.get("n_rows")
    if n is not None:
        if len(X) < n:
            raise RuntimeError(
                f"worker sees fewer rows ({len(X)}) than the dispatched "
                f"snapshot ({n}) — shared store out of sync")
        X = X[:n]
    model.predict_proba(runtime, X)


def _connect_to_controller(timeout_s: float = 120.0) -> socket.socket:
    host, port = _job_addr()
    deadline = time.time() + timeout_s
    while True:
        try:
            sock = socket.create_connection((host, port), timeout=5.0)
            sock.settimeout(None)  # jobs may be hours apart
            return sock
        except OSError:
            if time.time() > deadline:
                raise
            time.sleep(0.2)


def worker_loop(store, runtime) -> None:
    """Non-zero processes: block on the next job spec, execute its device
    ops, repeat until shutdown. The store must point at the same (shared)
    store_root process 0 persists into — the data plane that replaces the
    reference's Mongo-as-shared-storage for Spark executors."""
    import jax

    log.info("worker %d/%d entering SPMD loop",
             jax.process_index(), jax.process_count())
    sock = _connect_to_controller()
    buf = b""
    while True:
        while b"\n" not in buf:
            data = sock.recv(1 << 16)
            if not data:
                log.info("controller closed the job channel; exiting")
                return
            buf += data
        line, buf = buf.split(b"\n", 1)
        spec = json.loads(line.decode("utf-8"))
        op = spec.get("op")
        if op == "shutdown":
            log.info("worker %d shutting down", jax.process_index())
            return
        try:
            if op == "build":
                run_build_job(store, runtime, spec)
            elif op == "predict":
                run_predict_job(store, runtime, spec)
            else:
                log.error("unknown job op: %r", op)
        except Exception:  # noqa: BLE001 — keep the loop alive
            log.exception("worker job %r failed", op)


def require_single_process(what: str) -> None:
    """Guard for mesh ops that are not yet SPMD-dispatched to workers:
    running their collectives on process 0 alone would wedge the pod.
    Raises a clean client error (406) instead."""
    if is_multiprocess():
        raise ValueError(
            f"{what} is not SPMD-dispatched yet and cannot run on a "
            "multi-process pod; run it on a single-process deployment")


def shutdown_workers() -> None:
    """Process 0: release every worker from its loop (server shutdown)."""
    if is_multiprocess():
        try:
            _get_channel().send({"op": "shutdown"})
        except TimeoutError:
            pass
