"""SPMD job dispatch — how one HTTP process drives a multi-process mesh.

The reference scales out by sending Spark jobs from a driver service to a
standalone master that fans work across worker JVMs (reference
docker-compose.yml:123-163, model_builder.py:70-95). Under ``jax.distributed``
the equivalent constraint is SPMD: every process in the pod must execute the
same jitted computations in the same order, or the collectives XLA emits
(psum/all_gather over ICI/DCN) deadlock. But jobs arrive dynamically over
HTTP on one process only.

Design: **process 0 owns the catalog and the REST surface; every other
process runs a worker loop** (`worker_loop`). Before process 0 runs a mesh
computation for a job, it sends a job spec to every worker over a
persistent TCP channel (newline-delimited JSON — the minimal analogue of
the reference's Spark RPC control plane, ports 7077/41352 + py4j). A
device collective cannot play this role: workers idle between jobs, and
collective rendezvous carries initialization/barrier timeouts (Gloo's 30 s
handshake on CPU), so the control plane must tolerate unbounded idle —
TCP recv does. Workers decode the spec, rebuild identical host inputs
from the *shared dataset store* (the data plane replacing Mongo, which
played exactly this role for Spark executors), and execute the same
sequence of jitted calls. Results live replicated or are all-gathered;
process 0 persists them, workers discard.

The channel's address defaults to the jax.distributed coordinator host
(process 0) at ``LO_TPU_JOB_PORT`` (coordinator port + 1 when unset).

Single-process runs (and the CPU-mesh test rig) skip all of this: every
entry point no-ops when ``jax.process_count() == 1``.
"""

from __future__ import annotations

import contextlib
import json
import socket
import threading
import time
from typing import Any, Dict, List, Optional

from learningorchestra_tpu import config as _config
from learningorchestra_tpu.utils import failpoints, resources, tracing
from learningorchestra_tpu.utils.structlog import get_logger

log = get_logger("spmd")

#: Deterministic fault-injection site: process 0, every worker ready,
#: about to release them with 'go' — the dispatch-side crash window the
#: watchdog + supervisor recovery path must survive (utils/failpoints.py).
FP_DISPATCH_PRE_GO = failpoints.declare("spmd.dispatch.pre_go")


class PodDegraded(RuntimeError):
    """The pod cannot run mesh jobs until its supervisor restarts it.
    Mapped to HTTP 503 + Retry-After by the serving layer (a restarting
    pod is a transient condition, not an internal error)."""


def is_multiprocess() -> bool:
    import jax

    return jax.process_count() > 1


def local_host_id() -> int:
    """This host's placement identity for shard-map planning — which
    ingest-partition owner's chunks count as host-local when
    ``mesh.shard_chunked`` classifies its feed (catalog/ingest.py records
    the map; mesh.py consumes it). ``LO_TPU_SHARD_HOST`` overrides
    explicitly (tests / asymmetric pods); otherwise the jax process
    index, which matches partition order because both follow pod rank."""
    override = _config.shard_host()
    if override is not None:
        return override
    import jax

    return jax.process_index()


def serialize_collectives(tree) -> None:
    """Order-fence for back-to-back dispatched collective programs on a
    multi-process CPU pod: blocks until ``tree``'s device work completes
    so the next program's collectives cannot overlap it in flight.

    On TPU this is a no-op — per-device execution streams run enqueued
    programs strictly in dispatch order, so enqueueing a build's fit
    programs back-to-back keeps collective order identical on every
    process (the whole point of the batched dispatch round). The CPU
    backend has no stream order: in-flight programs execute concurrently
    on thread pools, so two dispatched programs' gloo collectives can
    interleave differently per process and corrupt the pod (observed as
    ``gloo::EnforceNotMet: op.preamble.length <= op.nbytes`` on the
    2-process test rig). Single-process runs need no fence either —
    their collectives never cross a process boundary."""
    import jax

    if is_multiprocess() and jax.default_backend() == "cpu":
        jax.block_until_ready(tree)


def mesh_epoch() -> int:
    """This incarnation's mesh generation. The supervisor
    (learningorchestra_tpu/supervisor.py) bumps ``LO_TPU_MESH_EPOCH`` on
    every pod restart; the job channel rejects workers whose epoch
    differs at handshake, so a stale worker from a previous incarnation
    can never join the new pod's collectives. Read dynamically (not
    cached) so the poison scope below follows the env."""
    return _config.mesh_epoch()


def _job_addr() -> tuple:
    """(host, port) of the job channel — coordinator host, port + 1."""
    coord = _config.coordinator_address("127.0.0.1:8476")
    host, _, port = coord.rpartition(":")
    return host or "127.0.0.1", _config.job_port(int(port) + 1)


def _close_quietly(sock: socket.socket) -> None:
    try:
        sock.close()
    except OSError:
        pass


class _Conn:
    """One worker connection with line-buffered reads."""

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.buf = b""

    def recv_line(self, timeout: Optional[float]):
        """(status, line): ("ok", str) | ("timeout", None) | ("eof", None).

        Timeout and EOF are distinct on purpose: a slow worker (still
        prepping a big job) must not be treated as a dead one."""
        self.sock.settimeout(timeout)
        try:
            while b"\n" not in self.buf:
                data = self.sock.recv(1 << 16)
                if not data:
                    return "eof", None
                self.buf += data
        except (TimeoutError, socket.timeout):
            return "timeout", None
        except OSError:
            return "eof", None
        finally:
            try:
                self.sock.settimeout(None)
            except OSError:
                pass
        line, self.buf = self.buf.split(b"\n", 1)
        return "ok", line.decode("utf-8")


class _JobChannel:
    """Process-0 end: accepts one connection per worker, fans job specs
    out as JSON lines and collects per-worker ready/fail acks. Worker
    connections are accepted in the background so the server can start
    before (or after) its workers. Dead connections are pruned on IO
    errors — a worker process cannot rejoin a running pod (its
    jax.distributed identity died with it), so the channel's job is to
    fail *cleanly*, not to resync.

    Every connection starts with an epoch handshake: the worker sends
    ``{"op": "hello", "epoch": N}`` and is admitted only when N matches
    this process's ``mesh_epoch()``. A worker from a previous pod
    incarnation (stale epoch — e.g. one that outlived a supervisor
    restart) is rejected and closed instead of occupying a worker slot
    whose collectives it could never join correctly."""

    def __init__(self, n_workers: int):
        self.n_workers = n_workers
        self._lock = threading.Lock()
        #: Serializes all socket writes: a shutdown broadcast racing an
        #: in-flight dispatch must not interleave bytes within a line.
        self._wlock = threading.Lock()
        self._round = 0
        self._conns: List[_Conn] = []
        _, port = _job_addr()
        self._srv = socket.create_server(("", port))
        # thread-lifecycle: owner=_JobChannel; exits when close() closes
        # the server socket (accept raises OSError → return); daemon for
        # process teardown.
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name="lo-spmd-accept")
        t.start()

    def _accept_loop(self) -> None:
        while True:
            try:
                sock, _ = self._srv.accept()
            except OSError:
                return
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # Handshake off-thread: a half-open connection that never
            # sends its hello must not block later workers from joining.
            # thread-lifecycle: owner=_JobChannel; exits after one
            # recv_line (30s timeout) — rejects or registers the worker
            # and returns; daemon.
            threading.Thread(target=self._handshake, args=(sock,),
                             daemon=True, name="lo-spmd-handshake").start()

    def _handshake(self, sock: socket.socket) -> None:
        conn = _Conn(sock)
        status, line = conn.recv_line(timeout=30.0)
        if status != "ok":
            _close_quietly(sock)
            return
        try:
            hello = json.loads(line)
        except json.JSONDecodeError:
            hello = {}
        epoch = mesh_epoch()
        if hello.get("op") != "hello" or hello.get("epoch") != epoch:
            log.warning(
                "rejecting job-channel connection (epoch %r != pod epoch "
                "%d): stale worker from a previous pod incarnation",
                hello.get("epoch"), epoch)
            try:
                sock.sendall((json.dumps(
                    {"op": "reject", "epoch": epoch,
                     "reason": f"stale mesh epoch {hello.get('epoch')!r}; "
                               f"pod is at epoch {epoch}"}) + "\n")
                    .encode("utf-8"))
            except OSError:
                pass
            _close_quietly(sock)
            return
        try:
            sock.sendall((json.dumps({"op": "welcome", "epoch": epoch})
                          + "\n").encode("utf-8"))
        except OSError:
            _close_quietly(sock)
            return
        # The hello's lite resource snapshot seeds /cluster's pod view
        # before this worker has run a single job.
        resources.note_remote(hello.get("process"),
                              hello.get("resources"))
        with self._lock:
            self._conns.append(conn)

    def close(self) -> None:
        """Tear down the listener and every worker connection (tests and
        controlled shutdown)."""
        try:
            self._srv.close()
        except OSError:
            pass
        for conn in self._live():
            self._drop(conn)

    def _live(self) -> List[_Conn]:
        with self._lock:
            return list(self._conns)

    def _drop(self, conn: _Conn) -> None:
        with self._lock:
            if conn in self._conns:
                self._conns.remove(conn)
        try:
            conn.sock.close()
        except OSError:
            pass

    def _sendall(self, conns: List[_Conn], msg: Dict[str, Any]) -> None:
        data = (json.dumps(msg) + "\n").encode("utf-8")
        with self._wlock:
            for conn in conns:
                try:
                    conn.sock.sendall(data)
                except OSError:
                    self._drop(conn)

    def _read_ack(self, conn: _Conn, rnd: int, deadline: float):
        """This round's ack from one worker, skipping stale acks from
        aborted earlier rounds. Returns (status, ack_dict|None)."""
        while True:
            status, line = conn.recv_line(max(1.0, deadline - time.time()))
            if status != "ok":
                return status, None
            try:
                ack = json.loads(line)
            except json.JSONDecodeError:
                continue
            if ack.get("op") == "spans":
                # A worker's span shipment from an earlier job that the
                # post-job drain timed out on: merge it late rather than
                # dropping it — and never mistake it for this round's
                # ack (it carries the OLD round id, but defense in
                # depth beats a coincidence). The piggybacked resource
                # snapshot still freshens the /cluster pod view; the
                # job it belonged to is long gone, so its watermarks
                # are NOT merged into whatever job is dispatching now.
                tracing.ingest(ack.get("spans") or [])
                res = ack.get("resources") or {}
                resources.note_remote(res.get("process"),
                                      res.get("snapshot"))
                continue
            if ack.get("round") == rnd:
                return "ok", ack
            # stale ack from an earlier aborted round — discard

    def dispatch(self, spec: Dict[str, Any], connect_timeout_s: float = 60.0,
                 prep_timeout_s: float = 600.0) -> None:
        """Two-phase fan-out: send the (round-stamped) spec, wait for every
        worker's ``ready`` ack (host-side prep done — datasets loaded,
        shapes agreed), then release them with ``go``. Any failed/missing
        ack aborts the round on every worker and raises, so process 0
        never enters a collective some worker will not join. A *timed-out*
        worker is not dropped — it may just be slow, and its stale ack is
        discarded by round id on the next dispatch; only EOF (the process
        died — it cannot rejoin a running pod) removes a connection. (A
        failure *after* go — mid-collective — still wedges; that is
        inherent to collectives without timeouts and surfaces at pod
        supervision.)"""
        deadline = time.time() + connect_timeout_s
        while len(self._live()) < self.n_workers:
            if time.time() > deadline:
                raise TimeoutError(
                    f"only {len(self._live())}/{self.n_workers} workers "
                    "connected to the job channel")
            time.sleep(0.05)
        with self._lock:
            self._round += 1
            rnd = self._round
        conns = self._live()[:self.n_workers]
        self._sendall(conns, dict(spec, round=rnd))
        deadline = time.time() + prep_timeout_s
        failures = []
        for conn in conns:
            status, ack = self._read_ack(conn, rnd, deadline)
            if status == "eof":
                self._drop(conn)
                failures.append("worker died before ack")
                # A dead worker can never rejoin: poison the pod NOW so
                # later dispatches refuse immediately instead of each
                # burning the full connect timeout against a permanently
                # short-handed pod (same rule as mid-job deaths).
                _set_pod_error("worker died before ack")
            elif status == "timeout":
                failures.append(
                    f"worker ack timed out after {prep_timeout_s:.0f}s")
            elif ack.get("status") != "ready":
                failures.append(ack.get("error", "worker prep failed"))
        if failures:
            self._sendall(self._live(), {"op": "abort", "round": rnd})
            raise RuntimeError(
                f"SPMD dispatch aborted ({len(failures)} worker(s)): "
                + "; ".join(failures[:3]))
        failpoints.fire(FP_DISPATCH_PRE_GO)
        self._sendall(conns, {"op": "go", "round": rnd})

    def broadcast(self, msg: Dict[str, Any]) -> None:
        """Fire-and-forget control message (shutdown) — no ack round."""
        self._sendall(self._live(), msg)

    def drain_spans(self, rnd: int, timeout_s: float = 5.0) -> int:
        """Collect each worker's span shipment for round ``rnd`` (sent
        unprompted after its device ops finish) and merge it into this
        process's trace buffer. Runs inside the dispatch guard right
        after the coordinator's own device ops complete — the workers
        ran the same collective program, so their shipments are
        imminent; the timeout bounds a wedged/slow worker (its spans
        then merge at the next round's ack read instead). Each shipment
        also carries the worker's resource watermarks for the job and a
        lite process snapshot: the watermarks merge into the CURRENT
        job's profile (this runs inside the job's body on the
        coordinator, so ``peak_hbm_bytes`` becomes a pod-wide max) and
        the snapshot freshens ``GET /cluster``. Returns how many
        workers' shipments merged."""
        merged = 0
        for conn in self._live():
            deadline = time.time() + timeout_s
            while True:
                status, line = conn.recv_line(
                    max(0.1, deadline - time.time()))
                if status != "ok":
                    break                      # timeout/EOF: catch up later
                try:
                    msg = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if msg.get("op") == "spans":
                    tracing.ingest(msg.get("spans") or [])
                    res = msg.get("resources") or {}
                    resources.note_remote(res.get("process"),
                                          res.get("snapshot"))
                    from learningorchestra_tpu import jobs

                    wm = res.get("watermarks") or {}
                    if isinstance(wm, dict) and (
                            wm.get("peak_hbm_bytes")
                            or wm.get("compile_s")):
                        jobs.record_job_watermarks(
                            peak_hbm_bytes=int(
                                wm.get("peak_hbm_bytes") or 0) or None,
                            compile_s=float(
                                wm.get("compile_s") or 0.0) or None)
                    if msg.get("round") == rnd:
                        merged += 1
                        break
                # stale ack from an aborted round — discard, keep reading
                if time.time() >= deadline:
                    break
        return merged

    def monitor_workers(self, stop: threading.Event, on_death) -> None:
        """Poll worker sockets for EOF (MSG_PEEK — never consumes ack
        bytes) while a dispatched job computes. A worker dying after 'go'
        used to be a silent pod wedge (the surviving processes block in a
        collective forever); the monitor converts it into a detected
        failure: ``on_death(reason)`` fires once, the caller fails the
        job's output datasets (pollable) and poisons the pod for fast
        failure of subsequent jobs."""
        while not stop.is_set():
            for conn in self._live():
                try:
                    data = conn.sock.recv(
                        1, socket.MSG_PEEK | socket.MSG_DONTWAIT)
                    if data == b"":
                        on_death("worker process died mid-job")
                        return
                except (BlockingIOError, InterruptedError):
                    pass
                except OSError:
                    on_death("worker connection lost mid-job")
                    return
            stop.wait(0.2)


_channel: Optional[_JobChannel] = None
_channel_lock = threading.Lock()
_dispatch_lock = threading.Lock()
#: ``(mesh_epoch, reason)`` recorded when a worker died mid-job. A dead
#: worker can never rejoin a *running* pod (its jax.distributed identity
#: died with it), so once set every subsequent dispatch in the same
#: incarnation fails fast with this reason instead of timing out against
#: a permanently short-handed pod. The poison is EPOCH-SCOPED: a
#: supervisor restart bumps the mesh epoch, and poison recorded under an
#: earlier epoch no longer degrades the pod — the restarted incarnation
#: serves again without any manual clearing.
_pod_error: Optional[tuple] = None
#: Thread-local mesh-job scope: set while this thread is allowed to enter
#: mesh collectives on a multi-process pod (process 0 inside dispatch_guard,
#: workers while executing a dispatched job's device ops).
_scope = threading.local()


class mesh_scope:
    """Marks the current thread as inside a dispatched SPMD job. The mesh
    runtime's device-entry points (shard_rows/replicate) refuse to run on a
    multi-process pod outside this scope — the *structural* guard that
    makes "forgot to dispatch" a clean client error instead of a wedged
    pod (every mesh op funnels host data through those entry points)."""

    def __enter__(self):
        _scope.depth = getattr(_scope, "depth", 0) + 1
        return self

    def __exit__(self, *exc):
        _scope.depth -= 1
        return False


def in_mesh_scope() -> bool:
    return getattr(_scope, "depth", 0) > 0


def check_mesh_entry(what: str = "this mesh operation") -> None:
    """Called by MeshRuntime.shard_rows/replicate: on a multi-process pod,
    device entry requires an active dispatch scope, else the collectives
    the op runs would execute on this process alone and deadlock the pod.
    Raises the clean 406-style error instead."""
    if is_multiprocess() and not in_mesh_scope():
        raise ValueError(
            f"{what} would enter mesh collectives on a multi-process pod "
            "without an SPMD dispatch; run it through a dispatched job "
            "(spmd.dispatch under dispatch_guard) or on a single-process "
            "deployment")


def _get_channel() -> _JobChannel:
    import jax

    global _channel
    with _channel_lock:
        if _channel is None:
            _channel = _JobChannel(jax.process_count() - 1)
        return _channel


def ensure_channel() -> None:
    """Start the job channel's listener (process 0, at server startup).
    Without this, workers connecting at boot would exhaust their connect
    deadline while the channel waits for the first job. No-op elsewhere."""
    import jax

    if is_multiprocess() and jax.process_index() == 0:
        _get_channel()


def _set_pod_error(reason: str) -> None:
    global _pod_error
    _pod_error = (mesh_epoch(), reason)


def poison_pod(reason: str) -> None:
    """Externally-detected degradation (the job watchdog finding a hung
    device program): poison this incarnation exactly like a mid-job
    worker death — later dispatches fail fast, ``/cluster`` reports the
    reason, and the supervisor's health poll restarts the pod under the
    next mesh epoch (which is what actually tears the hung program
    down). Epoch-scoped like every poison: the restarted incarnation
    reads healthy with no manual clearing."""
    log.error("pod poisoned: %s", reason)
    _set_pod_error(reason)


def pod_error() -> Optional[str]:
    """The reason this pod is degraded, or None while healthy. Poison
    recorded under a previous mesh epoch is stale — the supervisor
    restarted the pod since — and reads as healthy."""
    if _pod_error is None:
        return None
    epoch, reason = _pod_error
    return reason if epoch == mesh_epoch() else None


def require_pod_health() -> None:
    """Raise :class:`PodDegraded` when this pod cannot run mesh jobs.
    The serving layer calls this at the top of every dispatching route so
    a degraded pod answers 503 + Retry-After (the supervisor is about to
    restart it) instead of accepting jobs doomed to fail."""
    reason = pod_error()
    if reason is not None:
        raise PodDegraded(
            f"pod is degraded ({reason}); a dead worker cannot rejoin a "
            "running pod — the supervisor will restart the pod under a "
            "new mesh epoch (deploy/run_pod.sh)")


def dispatch(spec: Dict[str, Any]) -> None:
    """Process-0 side: announce the next mesh job to every worker and
    rendezvous on their readiness. No-op single-process. Caller must then
    execute exactly the device-op sequence `run_job` executes for this
    spec. The spec is stamped with the pod's mesh epoch — workers nack
    specs from a different incarnation (defense in depth behind the
    connection handshake) — and with the coordinator's ambient trace
    context, so worker-process spans join the SAME trace and merge back
    on the coordinator (``GET /trace/{id}`` covers the whole pod)."""
    if not is_multiprocess():
        return
    require_pod_health()
    stamped = dict(spec, epoch=mesh_epoch())
    wire = tracing.to_wire()
    if wire is not None:
        stamped["trace"] = wire
    _get_channel().dispatch(stamped)


@contextlib.contextmanager
def dispatch_job(store, inputs, make_spec, outputs=()):
    """Process-0 preamble shared by every dispatched surface (build,
    predict, embed, histogram): require a persisted shared store, commit
    the input datasets workers rebuild from, serialize the mesh job, and
    dispatch the spec — then run the caller's device ops inside the mesh
    scope. ``make_spec`` may be the spec dict or a thunk evaluated *after*
    the saves (specs that pin journaled state need the post-save view).
    Single-process: plain passthrough (no guard, jobs stay overlapped).

    ``outputs`` names the job's output datasets. While the device ops run,
    a watchdog thread peeks every worker socket: a worker dying after 'go'
    wedges the surviving processes in a collective (inherent to
    collectives without timeouts), but the watchdog converts it from a
    SILENT wedge into a recorded failure — each output dataset flips to
    ``finished: true`` with a pollable ``error``, and the pod is poisoned
    so every later dispatch fails fast instead of timing out against a
    permanently short-handed pod."""
    if not is_multiprocess():
        yield
        return
    if not store.cfg.persist:
        op = (make_spec() if callable(make_spec) else make_spec).get("op")
        raise RuntimeError(
            f"multi-process {op} jobs require a persisted shared store "
            "(LO_TPU_PERSIST=1 on a shared store_root)")
    require_pod_health()
    for name in inputs:
        store.save(name)
    from learningorchestra_tpu import jobs

    with dispatch_guard():
        dispatch(make_spec() if callable(make_spec) else make_spec)
        # Progress mark: every worker acked ready and 'go' went out —
        # the job watchdog's liveness clock restarts here, so its
        # deadline bounds the one phase nothing else bounds: the 'go'
        # phase of the dispatched device program (connect and prep have
        # their own timeouts in _JobChannel.dispatch).
        jobs.heartbeat()
        stop = threading.Event()

        def on_death(reason: str) -> None:
            _set_pod_error(reason)
            log.error("pod degraded: %s — failing job outputs %s",
                      reason, list(outputs))
            for name in outputs:
                try:
                    store.fail(name, f"pod failure: {reason}")
                except Exception:  # noqa: BLE001 — best-effort flagging
                    log.exception("could not fail output %s", name)

        # thread-lifecycle: owner=dispatch_job; exits when the finally
        # below sets the stop event and joins it (2s timeout); on_death
        # failures are logged, never raised off-thread.
        monitor = threading.Thread(
            target=_get_channel().monitor_workers, args=(stop, on_death),
            daemon=True, name="lo-spmd-watchdog")
        monitor.start()
        try:
            yield
        finally:
            stop.set()
            monitor.join(timeout=2.0)
        # Merge the workers' spans + resource watermarks for this job
        # (they ship them unprompted once their device ops finish —
        # always, even untraced: the job profile's pod-wide
        # peak_hbm_bytes must not depend on the sampling decision).
        # Runs only when the device ops completed (an aborted round's
        # workers never ran, so waiting on their shipment would just
        # burn the timeout) and never on a degraded pod.
        if pod_error() is None:
            channel = _get_channel()
            with channel._lock:
                rnd = channel._round
            channel.drain_spans(rnd)
            # The workers' span/watermark shipments arriving is itself
            # progress: the pod-wide program completed end to end.
            jobs.heartbeat()
        # The compute may have completed on this process even though a
        # worker died (death after its last collective): the outputs were
        # already flagged failed, so surface the degradation to the caller
        # rather than silently persisting half-a-pod's results.
        require_pod_health()


class dispatch_guard:
    """Serializes mesh jobs under multi-process operation and opens the
    mesh scope for the calling thread.

    Collective programs from concurrently dispatched jobs would interleave
    differently on each process and deadlock; the guard makes dispatch +
    compute atomic. Single-process mode is a no-op (concurrent fits stay
    overlapped, the FAIR-scheduler behavior)."""

    def __init__(self):
        self._scope = mesh_scope()

    def __enter__(self):
        if is_multiprocess():
            _dispatch_lock.acquire()
            self._scope.__enter__()
        return self

    def __exit__(self, *exc):
        if is_multiprocess():
            self._scope.__exit__(*exc)
            _dispatch_lock.release()
        return False


# -- worker side -------------------------------------------------------------

def jsonable_state(state: Dict[str, Any]) -> Dict[str, Any]:
    """Preprocessing state → JSON-safe (numpy scalars/arrays → Python).
    Python's json round-trips floats exactly (repr), so a worker applying
    the deserialized state reproduces process 0's design matrix
    bit-for-bit."""
    import numpy as np

    def conv(v):
        if isinstance(v, np.generic):
            return v.item()
        if isinstance(v, np.ndarray):
            return [conv(x) for x in v.tolist()]
        if isinstance(v, dict):
            return {k: conv(x) for k, x in v.items()}
        if isinstance(v, (list, tuple)):
            return [conv(x) for x in v]
        return v

    return conv(state)


def _require_snapshot(seen: int, pinned: Optional[int], what: str) -> None:
    """Shared staleness guard for preppers: the dispatched spec pins a
    snapshot (row/chunk counts); a worker seeing *less* than the pin means
    the shared store is out of sync and prep must nack (fail before 'go')
    rather than let collectives diverge."""
    if pinned is not None and seen < pinned:
        raise RuntimeError(
            f"worker sees fewer {what} ({seen}) than the dispatched "
            f"snapshot ({pinned}) — shared store out of sync")


def prep_build_job(store, runtime, spec: Dict[str, Any]):
    """Host-side prep for a build job; returns the device-op callable.

    Mirrors ``ModelBuilder.build``'s per-classifier compute exactly (fit →
    predict_proba with the same shapes, same order). Host-only work
    (persistence, prediction datasets, metrics) is process-0 business and
    is skipped here. The spec pins process 0's snapshot: its row counts,
    fitted preprocessing state, and feature fields — a concurrent ingest
    commit between its save and this load may have appended rows or
    shifted stats, and any divergence would either wedge the collectives
    (shape mismatch) or silently assemble inconsistent global arrays.
    Rows only ever append, so truncating to the pinned counts reproduces
    the snapshot prefix; the pinned state makes the values identical.
    """
    from learningorchestra_tpu.models.registry import get_trainer
    from learningorchestra_tpu.ops import preprocess

    train_ds = store.load(spec["train"])
    test_ds = store.load(spec["test"])
    steps = spec.get("steps") or ()
    label = spec["label"]
    hparams = spec.get("hparams") or {}
    state = spec.get("state")
    ff = spec.get("feature_fields")
    n_train, n_test = spec.get("n_train"), spec.get("n_test")
    if spec.get("streamed"):
        # Mirror process 0's shard-local path: the same pinned state +
        # feature fields + row counts make every process's lazy design
        # identical, and each worker's device shards materialize from its
        # OWN row ranges only — the whole point of streaming (host RAM
        # divides by process count).
        _require_snapshot(train_ds.num_rows, n_train, "train rows")
        _require_snapshot(test_ds.num_rows, n_test, "test rows")
        X_train, y_train, ff, state = preprocess.design_matrix_streamed(
            train_ds, label, steps, state=state, feature_fields=ff,
            n_rows=n_train)
        X_test, y_test, _, _ = preprocess.design_matrix_streamed(
            test_ds, label, steps, state=state, feature_fields=ff,
            n_rows=n_test)
    else:
        X_train, y_train, ff, state = preprocess.design_matrix(
            train_ds, label, steps, state=state, feature_fields=ff)
        X_test, y_test, _, _ = preprocess.design_matrix(
            test_ds, label, steps, state=state, feature_fields=ff)
        if n_train is not None:
            _require_snapshot(len(X_train), n_train, "train rows")
            _require_snapshot(len(X_test), n_test, "test rows")
            X_train, y_train = X_train[:n_train], y_train[:n_train]
            X_test = X_test[:n_test]
            y_test = y_test[:n_test] if y_test is not None else None
    num_classes = int(max(int(y_train.max()) + 1,
                          2 if y_test is None else int(y_test.max()) + 1))

    def device_ops() -> None:
        # Batched dispatch round, mirroring ModelBuilder._build_dispatched
        # EXACTLY: every family's fit programs enqueue back-to-back first
        # (async dispatch — no host barrier between fits), then the
        # probability passes run in the same order. A family that fails
        # here fails identically on process 0 (deterministic inputs), so
        # both sides skip the same device ops and collective-program
        # order stays aligned.
        models = []
        for c in spec["classifiers"]:
            try:
                trainer = get_trainer(c)
                model = trainer(runtime, X_train, y_train, num_classes,
                                **hparams.get(c, {}))
                # Mirrors process 0's phase-1 fence (no-op on TPU).
                serialize_collectives(model.params)
                models.append(model)
            except Exception:  # noqa: BLE001 — mirror per-model boundary
                log.exception("worker fit %s failed", c)
                models.append(None)
        for c, model in zip(spec["classifiers"], models):
            if model is None:
                continue
            try:
                model.predict_proba(runtime, X_test)
            except Exception:  # noqa: BLE001 — mirror per-model boundary
                log.exception("worker predict %s failed", c)

    return device_ops


def prep_predict_job(store, runtime, spec: Dict[str, Any]):
    """Host-side prep mirroring ``ModelBuilder.predict``; returns the
    device-op callable."""
    from learningorchestra_tpu.models.persistence import ModelRegistry
    from learningorchestra_tpu.ops import preprocess

    registry = ModelRegistry(store.cfg)
    man, model = registry.load(spec["model"])
    pp = man["preprocess"]
    ds = store.load(spec["dataset"])
    n = spec.get("n_rows")
    if spec.get("streamed"):
        _require_snapshot(ds.num_rows, n, "rows")
        X, _, _, _ = preprocess.design_matrix_streamed(
            ds, pp["label"], pp["steps"], state=pp["state"],
            feature_fields=pp["feature_fields"], n_rows=n, need_y=False)
    else:
        X, _, _, _ = preprocess.design_matrix(
            ds, pp["label"], pp["steps"], state=pp["state"],
            feature_fields=pp["feature_fields"])
        if n is not None:
            _require_snapshot(len(X), n, "rows")
            X = X[:n]
    return lambda: model.predict_proba(runtime, X)


def prep_embed_job(store, runtime, spec: Dict[str, Any]):
    """Host-side prep mirroring ``create_embedding_image``'s compute: build
    the identical design matrix from the shared store (pinned rows +
    preprocessing state) and return the device-op callable running the
    same tsne/pca embed. PNG rendering is process-0 business."""
    from learningorchestra_tpu.ops import preprocess
    from learningorchestra_tpu.viz.pca import pca_embed
    from learningorchestra_tpu.viz.tsne import tsne_embed

    ds = store.load(spec["parent"])
    X, _, _, _ = preprocess.design_matrix(
        ds, spec["label"] or "__none__", (), state=spec.get("state"),
        feature_fields=spec.get("feature_fields"))
    n = spec["n_rows"]
    _require_snapshot(len(X), n, "rows")
    X = X[:n]
    kwargs = spec.get("embed_kwargs") or {}
    method = spec["method"]
    if method == "pca":
        return lambda: pca_embed(runtime, X)
    if method == "tsne":
        return lambda: tsne_embed(runtime, X, **kwargs)
    raise ValueError(f"unknown embed method: {method!r}")


def prep_histogram_job(store, runtime, spec: Dict[str, Any]):
    """Host-side prep mirroring ``create_histogram``'s streamed device
    counts. The spec pins the parent's journaled chunk count: chunk files
    are immutable and journal order is append-only, so truncating both
    sides to the pinned count makes every per-chunk device bincount (and
    the host/device path decision, which depends only on chunk data)
    identical across processes. Result writing is process-0 business.

    Unlike build/predict/embed (pure compute after 'go'), the streamed
    device ops re-read chunk files between collectives, so prep walks
    every chunk once first: a missing/corrupt chunk file nacks here —
    before 'go' — instead of wedging the pod mid-psum. The re-read at
    device time is cheap (immutable files, warm page cache)."""
    from learningorchestra_tpu.ops.histogram import histogram_totals

    ds = store.load(spec["parent"])
    n_chunks = spec["n_chunks"]
    fields = spec["fields"]
    _require_snapshot(len(ds.journal_files()), n_chunks, "chunks")
    for _ in ds.iter_chunks(list(fields), max_chunks=n_chunks):
        pass  # validation pass: every read that 'go' will need, fallible now
    return lambda: histogram_totals(runtime, ds, fields,
                                    max_chunks=n_chunks)


_PREPPERS = {"build": prep_build_job, "predict": prep_predict_job,
             "embed": prep_embed_job, "histogram": prep_histogram_job}


def _connect_to_controller(timeout_s: float = 120.0) -> socket.socket:
    host, port = _job_addr()
    deadline = time.time() + timeout_s
    while True:
        try:
            sock = socket.create_connection((host, port), timeout=5.0)
            sock.settimeout(None)  # jobs may be hours apart
            return sock
        except OSError:
            if time.time() > deadline:
                raise
            time.sleep(0.2)


def worker_loop(store, runtime) -> str:
    """Non-zero processes: block on the next job spec, prep host-side
    inputs, ack readiness, await ``go``, execute the device ops; repeat
    until shutdown. The store must point at the same (shared) store_root
    process 0 persists into — the data plane that replaces the reference's
    Mongo-as-shared-storage for Spark executors.

    Returns the exit reason — ``"shutdown"`` (controlled, exit 0) vs
    ``"controller-lost"`` / ``"stale-epoch"`` (this incarnation cannot
    continue; the caller should exit nonzero so the host's supervisor
    restarts the process into the pod's next incarnation)."""
    import jax

    epoch = mesh_epoch()
    # Spans this process records carry its pod rank, so the merged
    # coordinator view can attribute per-process time (the 2-process
    # propagation test pins exactly this).
    tracing.set_process(jax.process_index())
    log.info("worker %d/%d entering SPMD loop (epoch %d)",
             jax.process_index(), jax.process_count(), epoch)
    sock = _connect_to_controller()
    conn = _Conn(sock)

    def reply(msg: Dict[str, Any]) -> bool:
        """Send an ack; False when the controller is gone (socket closed
        after an abort, controller restart) — exit cleanly, not by
        traceback."""
        try:
            sock.sendall((json.dumps(msg) + "\n").encode("utf-8"))
            return True
        except OSError:
            return False

    # Epoch handshake: identify this incarnation before taking a worker
    # slot; the controller rejects a stale epoch (supervisor restarted the
    # pod since this process started). The hello carries a lite resource
    # snapshot so /cluster shows this worker's host/device state from
    # the moment it joins, not only after its first job.
    if not reply({"op": "hello", "epoch": epoch,
                  "process": jax.process_index(),
                  "resources": resources.process_snapshot(lite=True)}):
        log.info("controller lost during handshake; exiting")
        return "controller-lost"
    status, line = conn.recv_line(60.0)
    if status != "ok":
        log.info("controller lost during handshake; exiting")
        return "controller-lost"
    verdict = json.loads(line)
    if verdict.get("op") != "welcome":
        log.warning("controller rejected this worker: %s",
                    verdict.get("reason", verdict))
        return "stale-epoch"

    while True:
        status, line = conn.recv_line(None)
        if status != "ok":
            log.info("controller closed the job channel; exiting")
            return "controller-lost"
        spec = json.loads(line)
        op = spec.get("op")
        rnd = spec.get("round")
        if op == "shutdown":
            log.info("worker %d shutting down", jax.process_index())
            return "shutdown"
        if op in ("go", "abort"):
            continue  # stray control line from an aborted round
        prepper = _PREPPERS.get(op)
        device_ops = None
        # The coordinator's trace context rides the spec: this worker's
        # prep + device spans join the SAME trace and ship back after
        # the job, so GET /trace/{id} on the coordinator covers the pod.
        wctx = tracing.from_wire(spec.get("trace"))
        if prepper is None:
            ok = reply({"status": "fail", "round": rnd,
                        "error": f"unknown job op: {op!r}"})
        elif spec.get("epoch") not in (None, epoch):
            # Defense in depth behind the connection handshake: never run
            # a spec stamped by a different pod incarnation.
            ok = reply({"status": "fail", "round": rnd,
                        "error": f"stale mesh epoch: spec epoch "
                                 f"{spec.get('epoch')} != worker {epoch}"})
        else:
            try:
                with tracing.attach(wctx), tracing.span("worker.prep",
                                                        op=op):
                    device_ops = prepper(store, runtime, spec)
                ok = reply({"status": "ready", "round": rnd})
            except Exception as exc:  # noqa: BLE001 — nack, keep loop alive
                log.exception("worker prep for %r failed", op)
                ok = reply({"status": "fail", "round": rnd,
                            "error": f"{type(exc).__name__}: {exc}"})
        if not ok:
            log.info("controller lost while acking; exiting")
            return "controller-lost"
        # Await the controller's verdict for this round (blocking: the
        # controller may legitimately spend minutes collecting other
        # workers' acks; its death surfaces as EOF).
        status, line = conn.recv_line(None)
        if status != "ok":
            log.info("controller lost mid-round; exiting")
            return "controller-lost"
        verdict = json.loads(line).get("op")
        if verdict == "go" and device_ops is not None:
            resources.ensure_listener()
            c0 = resources.compile_seconds()
            try:
                with tracing.attach(wctx), \
                        tracing.span("dispatch.device", op=op), \
                        mesh_scope():
                    device_ops()
            except Exception:  # noqa: BLE001 — keep the loop alive
                log.exception("worker device ops for %r failed", op)
            # Ship this job's spans + this process's resource watermarks
            # to the coordinator (it drains them right after its own
            # device ops; a missed drain merges at the next round's ack
            # read). Always sent — the coordinator's job profile needs
            # the pod-wide peak even for unsampled traces; spans ride
            # along only when the trace recorded any. Failure to send =
            # controller gone, caught at the next recv.
            reply({"op": "spans", "round": rnd,
                   "spans": (tracing.pop_spans(wctx.trace_id)
                             if wctx is not None and wctx.sampled else []),
                   "resources": {
                       "process": jax.process_index(),
                       "snapshot": resources.process_snapshot(lite=True),
                       "watermarks": {
                           "peak_hbm_bytes": resources.hbm_bytes_in_use(),
                           "compile_s": round(
                               resources.compile_seconds() - c0, 6)}}})
        elif verdict == "shutdown":
            return "shutdown"


def shutdown_workers() -> None:
    """Process 0: release every worker from its loop (server shutdown)."""
    if is_multiprocess():
        _get_channel().broadcast({"op": "shutdown"})
