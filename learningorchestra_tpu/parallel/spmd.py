"""SPMD job dispatch — how one HTTP process drives a multi-process mesh.

The reference scales out by sending Spark jobs from a driver service to a
standalone master that fans work across worker JVMs (reference
docker-compose.yml:123-163, model_builder.py:70-95). Under ``jax.distributed``
the equivalent constraint is SPMD: every process in the pod must execute the
same jitted computations in the same order, or the collectives XLA emits
(psum/all_gather over ICI/DCN) deadlock. But jobs arrive dynamically over
HTTP on one process only.

Design: **process 0 owns the catalog and the REST surface; every other
process runs a worker loop** (`worker_loop`). Before process 0 runs a mesh
computation for a job, it sends a job spec to every worker over a
persistent TCP channel (newline-delimited JSON — the minimal analogue of
the reference's Spark RPC control plane, ports 7077/41352 + py4j). A
device collective cannot play this role: workers idle between jobs, and
collective rendezvous carries initialization/barrier timeouts (Gloo's 30 s
handshake on CPU), so the control plane must tolerate unbounded idle —
TCP recv does. Workers decode the spec, rebuild identical host inputs
from the *shared dataset store* (the data plane replacing Mongo, which
played exactly this role for Spark executors), and execute the same
sequence of jitted calls. Results live replicated or are all-gathered;
process 0 persists them, workers discard.

The channel's address defaults to the jax.distributed coordinator host
(process 0) at ``LO_TPU_JOB_PORT`` (coordinator port + 1 when unset).

Single-process runs (and the CPU-mesh test rig) skip all of this: every
entry point no-ops when ``jax.process_count() == 1``.
"""

from __future__ import annotations

import json
import logging
import os
import socket
import threading
import time
from typing import Any, Dict, List, Optional

log = logging.getLogger("lo_tpu.spmd")


def is_multiprocess() -> bool:
    import jax

    return jax.process_count() > 1


def _job_addr() -> tuple:
    """(host, port) of the job channel — coordinator host, port + 1."""
    coord = os.environ.get("LO_TPU_COORDINATOR", "127.0.0.1:8476")
    host, _, port = coord.rpartition(":")
    job_port = int(os.environ.get("LO_TPU_JOB_PORT", int(port) + 1))
    return host or "127.0.0.1", job_port


class _Conn:
    """One worker connection with line-buffered reads."""

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.buf = b""

    def recv_line(self, timeout: Optional[float]):
        """(status, line): ("ok", str) | ("timeout", None) | ("eof", None).

        Timeout and EOF are distinct on purpose: a slow worker (still
        prepping a big job) must not be treated as a dead one."""
        self.sock.settimeout(timeout)
        try:
            while b"\n" not in self.buf:
                data = self.sock.recv(1 << 16)
                if not data:
                    return "eof", None
                self.buf += data
        except (TimeoutError, socket.timeout):
            return "timeout", None
        except OSError:
            return "eof", None
        finally:
            try:
                self.sock.settimeout(None)
            except OSError:
                pass
        line, self.buf = self.buf.split(b"\n", 1)
        return "ok", line.decode("utf-8")


class _JobChannel:
    """Process-0 end: accepts one connection per worker, fans job specs
    out as JSON lines and collects per-worker ready/fail acks. Worker
    connections are accepted in the background so the server can start
    before (or after) its workers. Dead connections are pruned on IO
    errors — a worker process cannot rejoin a running pod (its
    jax.distributed identity died with it), so the channel's job is to
    fail *cleanly*, not to resync."""

    def __init__(self, n_workers: int):
        self.n_workers = n_workers
        self._lock = threading.Lock()
        #: Serializes all socket writes: a shutdown broadcast racing an
        #: in-flight dispatch must not interleave bytes within a line.
        self._wlock = threading.Lock()
        self._round = 0
        self._conns: List[_Conn] = []
        _, port = _job_addr()
        self._srv = socket.create_server(("", port))
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name="lo-spmd-accept")
        t.start()

    def _accept_loop(self) -> None:
        while True:
            try:
                sock, _ = self._srv.accept()
            except OSError:
                return
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                self._conns.append(_Conn(sock))

    def _live(self) -> List[_Conn]:
        with self._lock:
            return list(self._conns)

    def _drop(self, conn: _Conn) -> None:
        with self._lock:
            if conn in self._conns:
                self._conns.remove(conn)
        try:
            conn.sock.close()
        except OSError:
            pass

    def _sendall(self, conns: List[_Conn], msg: Dict[str, Any]) -> None:
        data = (json.dumps(msg) + "\n").encode("utf-8")
        with self._wlock:
            for conn in conns:
                try:
                    conn.sock.sendall(data)
                except OSError:
                    self._drop(conn)

    def _read_ack(self, conn: _Conn, rnd: int, deadline: float):
        """This round's ack from one worker, skipping stale acks from
        aborted earlier rounds. Returns (status, ack_dict|None)."""
        while True:
            status, line = conn.recv_line(max(1.0, deadline - time.time()))
            if status != "ok":
                return status, None
            try:
                ack = json.loads(line)
            except json.JSONDecodeError:
                continue
            if ack.get("round") == rnd:
                return "ok", ack
            # stale ack from an earlier aborted round — discard

    def dispatch(self, spec: Dict[str, Any], connect_timeout_s: float = 60.0,
                 prep_timeout_s: float = 600.0) -> None:
        """Two-phase fan-out: send the (round-stamped) spec, wait for every
        worker's ``ready`` ack (host-side prep done — datasets loaded,
        shapes agreed), then release them with ``go``. Any failed/missing
        ack aborts the round on every worker and raises, so process 0
        never enters a collective some worker will not join. A *timed-out*
        worker is not dropped — it may just be slow, and its stale ack is
        discarded by round id on the next dispatch; only EOF (the process
        died — it cannot rejoin a running pod) removes a connection. (A
        failure *after* go — mid-collective — still wedges; that is
        inherent to collectives without timeouts and surfaces at pod
        supervision.)"""
        deadline = time.time() + connect_timeout_s
        while len(self._live()) < self.n_workers:
            if time.time() > deadline:
                raise TimeoutError(
                    f"only {len(self._live())}/{self.n_workers} workers "
                    "connected to the job channel")
            time.sleep(0.05)
        with self._lock:
            self._round += 1
            rnd = self._round
        conns = self._live()[:self.n_workers]
        self._sendall(conns, dict(spec, round=rnd))
        deadline = time.time() + prep_timeout_s
        failures = []
        for conn in conns:
            status, ack = self._read_ack(conn, rnd, deadline)
            if status == "eof":
                self._drop(conn)
                failures.append("worker died before ack")
            elif status == "timeout":
                failures.append(
                    f"worker ack timed out after {prep_timeout_s:.0f}s")
            elif ack.get("status") != "ready":
                failures.append(ack.get("error", "worker prep failed"))
        if failures:
            self._sendall(self._live(), {"op": "abort", "round": rnd})
            raise RuntimeError(
                f"SPMD dispatch aborted ({len(failures)} worker(s)): "
                + "; ".join(failures[:3]))
        self._sendall(conns, {"op": "go", "round": rnd})

    def broadcast(self, msg: Dict[str, Any]) -> None:
        """Fire-and-forget control message (shutdown) — no ack round."""
        self._sendall(self._live(), msg)


_channel: Optional[_JobChannel] = None
_channel_lock = threading.Lock()
_dispatch_lock = threading.Lock()


def _get_channel() -> _JobChannel:
    import jax

    global _channel
    with _channel_lock:
        if _channel is None:
            _channel = _JobChannel(jax.process_count() - 1)
        return _channel


def ensure_channel() -> None:
    """Start the job channel's listener (process 0, at server startup).
    Without this, workers connecting at boot would exhaust their connect
    deadline while the channel waits for the first job. No-op elsewhere."""
    import jax

    if is_multiprocess() and jax.process_index() == 0:
        _get_channel()


def dispatch(spec: Dict[str, Any]) -> None:
    """Process-0 side: announce the next mesh job to every worker and
    rendezvous on their readiness. No-op single-process. Caller must then
    execute exactly the device-op sequence `run_job` executes for this
    spec."""
    if not is_multiprocess():
        return
    _get_channel().dispatch(spec)


class dispatch_guard:
    """Serializes mesh jobs under multi-process operation.

    Collective programs from concurrently dispatched jobs would interleave
    differently on each process and deadlock; the guard makes dispatch +
    compute atomic. Single-process mode is a no-op (concurrent fits stay
    overlapped, the FAIR-scheduler behavior)."""

    def __enter__(self):
        if is_multiprocess():
            _dispatch_lock.acquire()
        return self

    def __exit__(self, *exc):
        if is_multiprocess():
            _dispatch_lock.release()
        return False


# -- worker side -------------------------------------------------------------

def jsonable_state(state: Dict[str, Any]) -> Dict[str, Any]:
    """Preprocessing state → JSON-safe (numpy scalars/arrays → Python).
    Python's json round-trips floats exactly (repr), so a worker applying
    the deserialized state reproduces process 0's design matrix
    bit-for-bit."""
    import numpy as np

    def conv(v):
        if isinstance(v, np.generic):
            return v.item()
        if isinstance(v, np.ndarray):
            return [conv(x) for x in v.tolist()]
        if isinstance(v, dict):
            return {k: conv(x) for k, x in v.items()}
        if isinstance(v, (list, tuple)):
            return [conv(x) for x in v]
        return v

    return conv(state)


def prep_build_job(store, runtime, spec: Dict[str, Any]):
    """Host-side prep for a build job; returns the device-op callable.

    Mirrors ``ModelBuilder.build``'s per-classifier compute exactly (fit →
    predict_proba with the same shapes, same order). Host-only work
    (persistence, prediction datasets, metrics) is process-0 business and
    is skipped here. The spec pins process 0's snapshot: its row counts,
    fitted preprocessing state, and feature fields — a concurrent ingest
    commit between its save and this load may have appended rows or
    shifted stats, and any divergence would either wedge the collectives
    (shape mismatch) or silently assemble inconsistent global arrays.
    Rows only ever append, so truncating to the pinned counts reproduces
    the snapshot prefix; the pinned state makes the values identical.
    """
    from learningorchestra_tpu.models.registry import get_trainer
    from learningorchestra_tpu.ops import preprocess

    train_ds = store.load(spec["train"])
    test_ds = store.load(spec["test"])
    steps = spec.get("steps") or ()
    label = spec["label"]
    hparams = spec.get("hparams") or {}
    state = spec.get("state")
    ff = spec.get("feature_fields")
    X_train, y_train, ff, state = preprocess.design_matrix(
        train_ds, label, steps, state=state, feature_fields=ff)
    X_test, y_test, _, _ = preprocess.design_matrix(
        test_ds, label, steps, state=state, feature_fields=ff)
    n_train, n_test = spec.get("n_train"), spec.get("n_test")
    if n_train is not None:
        if len(X_train) < n_train or len(X_test) < n_test:
            raise RuntimeError(
                f"worker sees fewer rows than the dispatched snapshot "
                f"({len(X_train)}/{n_train} train, {len(X_test)}/{n_test} "
                "test) — shared store out of sync")
        X_train, y_train = X_train[:n_train], y_train[:n_train]
        X_test = X_test[:n_test]
        y_test = y_test[:n_test] if y_test is not None else None
    num_classes = int(max(int(y_train.max()) + 1,
                          2 if y_test is None else int(y_test.max()) + 1))

    def device_ops() -> None:
        for c in spec["classifiers"]:
            try:
                trainer = get_trainer(c)
                model = trainer(runtime, X_train, y_train, num_classes,
                                **hparams.get(c, {}))
                model.predict_proba(runtime, X_test)
            except Exception:  # noqa: BLE001 — mirror per-model boundary
                log.exception("worker fit %s failed", c)

    return device_ops


def prep_predict_job(store, runtime, spec: Dict[str, Any]):
    """Host-side prep mirroring ``ModelBuilder.predict``; returns the
    device-op callable."""
    from learningorchestra_tpu.models.persistence import ModelRegistry
    from learningorchestra_tpu.ops import preprocess

    registry = ModelRegistry(store.cfg)
    man, model = registry.load(spec["model"])
    pp = man["preprocess"]
    ds = store.load(spec["dataset"])
    X, _, _, _ = preprocess.design_matrix(
        ds, pp["label"], pp["steps"], state=pp["state"],
        feature_fields=pp["feature_fields"])
    n = spec.get("n_rows")
    if n is not None:
        if len(X) < n:
            raise RuntimeError(
                f"worker sees fewer rows ({len(X)}) than the dispatched "
                f"snapshot ({n}) — shared store out of sync")
        X = X[:n]
    return lambda: model.predict_proba(runtime, X)


_PREPPERS = {"build": prep_build_job, "predict": prep_predict_job}


def _connect_to_controller(timeout_s: float = 120.0) -> socket.socket:
    host, port = _job_addr()
    deadline = time.time() + timeout_s
    while True:
        try:
            sock = socket.create_connection((host, port), timeout=5.0)
            sock.settimeout(None)  # jobs may be hours apart
            return sock
        except OSError:
            if time.time() > deadline:
                raise
            time.sleep(0.2)


def worker_loop(store, runtime) -> None:
    """Non-zero processes: block on the next job spec, prep host-side
    inputs, ack readiness, await ``go``, execute the device ops; repeat
    until shutdown. The store must point at the same (shared) store_root
    process 0 persists into — the data plane that replaces the reference's
    Mongo-as-shared-storage for Spark executors."""
    import jax

    log.info("worker %d/%d entering SPMD loop",
             jax.process_index(), jax.process_count())
    sock = _connect_to_controller()
    conn = _Conn(sock)

    def reply(msg: Dict[str, Any]) -> bool:
        """Send an ack; False when the controller is gone (socket closed
        after an abort, controller restart) — exit cleanly, not by
        traceback."""
        try:
            sock.sendall((json.dumps(msg) + "\n").encode("utf-8"))
            return True
        except OSError:
            return False

    while True:
        status, line = conn.recv_line(None)
        if status != "ok":
            log.info("controller closed the job channel; exiting")
            return
        spec = json.loads(line)
        op = spec.get("op")
        rnd = spec.get("round")
        if op == "shutdown":
            log.info("worker %d shutting down", jax.process_index())
            return
        if op in ("go", "abort"):
            continue  # stray control line from an aborted round
        prepper = _PREPPERS.get(op)
        device_ops = None
        if prepper is None:
            ok = reply({"status": "fail", "round": rnd,
                        "error": f"unknown job op: {op!r}"})
        else:
            try:
                device_ops = prepper(store, runtime, spec)
                ok = reply({"status": "ready", "round": rnd})
            except Exception as exc:  # noqa: BLE001 — nack, keep loop alive
                log.exception("worker prep for %r failed", op)
                ok = reply({"status": "fail", "round": rnd,
                            "error": f"{type(exc).__name__}: {exc}"})
        if not ok:
            log.info("controller lost while acking; exiting")
            return
        # Await the controller's verdict for this round (blocking: the
        # controller may legitimately spend minutes collecting other
        # workers' acks; its death surfaces as EOF).
        status, line = conn.recv_line(None)
        if status != "ok":
            log.info("controller lost mid-round; exiting")
            return
        verdict = json.loads(line).get("op")
        if verdict == "go" and device_ops is not None:
            try:
                device_ops()
            except Exception:  # noqa: BLE001 — keep the loop alive
                log.exception("worker device ops for %r failed", op)
        elif verdict == "shutdown":
            return


def require_single_process(what: str) -> None:
    """Guard for mesh ops that are not yet SPMD-dispatched to workers:
    running their collectives on process 0 alone would wedge the pod.
    Raises a clean client error (406) instead."""
    if is_multiprocess():
        raise ValueError(
            f"{what} is not SPMD-dispatched yet and cannot run on a "
            "multi-process pod; run it on a single-process deployment")


def shutdown_workers() -> None:
    """Process 0: release every worker from its loop (server shutdown)."""
    if is_multiprocess():
        _get_channel().broadcast({"op": "shutdown"})
