"""Ring attention — sequence/context parallelism over the mesh ``seq`` axis.

The reference has no sequence models (SURVEY.md §5 "long-context: absent"),
but long-context scaling is first-class here: sequences longer than one
chip's HBM shard their *length* across the ``seq`` mesh axis, and exact
attention runs as a ring — each device keeps its Q shard resident while
K/V blocks rotate one hop per step via ``jax.lax.ppermute`` over ICI,
accumulating the softmax online (the numerically-stable m/l/o recurrence
of FlashAttention, applied block-wise). After ``seq`` steps every Q block
has seen every K/V block: exact attention, O(T/P) memory per device, and
the K/V transfer overlaps the attention matmuls of the previous block.

Causal masking uses global positions, so rotation order never changes
results: the block arriving at step ``t`` came from ring position
``(my_index − t) mod P`` and its keys carry that offset.

This module is mesh-agnostic: functions are written per-shard and must run
inside ``shard_map`` with the sequence axis named by ``axis_name``
(models/transformer.py wires it into a full training step; tests run it on
the 8-device CPU mesh).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


def _online_block(q, k_blk, v_blk, o, m, l, mask):
    """Fold one K/V block into the (o, m, l) online-softmax accumulators.

    q: (B, Tq, H, D); k_blk/v_blk: (B, Tk, H, D); o: (B, Tq, H, D);
    m, l: (B, Tq, H); mask: (Tq, Tk) additive (0 or -inf) or None.
    """
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(q.dtype)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk) * scale
    if mask is not None:
        s = s + mask[None, None, :, :]
    m_blk = s.max(axis=-1)                                  # (B, H, Tq)
    m_new = jnp.maximum(m, m_blk.transpose(0, 2, 1))        # (B, Tq, H)
    # exp shift factors; rows that have seen only -inf stay zeroed via l.
    alpha = jnp.exp(m - m_new)                              # (B, Tq, H)
    p = jnp.exp(s - m_new.transpose(0, 2, 1)[..., None])    # (B, H, Tq, Tk)
    l = l * alpha + p.sum(axis=-1).transpose(0, 2, 1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v_blk)
    o = o * alpha[..., None] + pv
    return o, m_new, l


#: Keys/values processed per online-softmax fold. Bounds the score
#: transient at (B, H, T_local, KV_BLOCK) regardless of sequence length —
#: the single-device/local-block analogue of flash attention's tiling
#: (without it, an 8k-seq single-chip step materialized 8 GB score
#: tensors per layer and OOM'd a 16 GB chip).
KV_BLOCK = 1024


def ring_attention(q, k, v, *, axis_name: str, causal: bool = False,
                   kv_block: int = KV_BLOCK):
    """Exact multi-head attention with sequence sharded over ``axis_name``.

    Per-shard shapes (inside shard_map): q, k, v — (B, T_local, H, D).
    Returns (B, T_local, H, D). With a size-1 axis this degrades to
    blockwise (flash-style) single-device attention: each ring hop's
    K/V block additionally folds through the online softmax in
    ``kv_block``-sized chunks, so memory stays O(T·kv_block) at any
    length (ragged tails pad the block and mask the padded keys).
    """
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    B, Tq, H, D = q.shape
    Tk = k.shape[1]

    # Derive the accumulators from q arithmetically so they inherit q's
    # varying-axes type (shard_map's vma tracking): a literal zeros_like
    # would be unvarying and reject the scan carry.
    qf = q.astype(jnp.float32)
    o = qf * 0.0
    m = qf[..., 0] * 0.0 - jnp.inf                          # (B, Tq, H)
    l = qf[..., 0] * 0.0

    q_pos = my_idx * Tq + jnp.arange(Tq)
    chunk = min(kv_block, Tk)
    n_chunks = -(-Tk // chunk)
    Tk_pad = n_chunks * chunk  # ragged tails pad; padded keys are masked

    def fold(o, m, l, k_blk, v_blk, t):
        # The block held at step t originated at ring position
        # (my_idx - t) mod P; its keys carry that global offset.
        src = (my_idx - t) % axis_size
        if Tk_pad != Tk:
            pad = ((0, 0), (0, Tk_pad - Tk), (0, 0), (0, 0))
            k_blk = jnp.pad(k_blk, pad)
            v_blk = jnp.pad(v_blk, pad)

        def fold_chunk(carry, ci):
            o, m, l = carry
            kc = jax.lax.dynamic_slice_in_dim(k_blk, ci * chunk, chunk,
                                              axis=1)
            vc = jax.lax.dynamic_slice_in_dim(v_blk, ci * chunk, chunk,
                                              axis=1)
            k_local = ci * chunk + jnp.arange(chunk)
            mask = None
            if Tk_pad != Tk:
                mask = jnp.where(k_local[None, :] >= Tk, -jnp.inf,
                                 0.0).astype(jnp.float32) * jnp.ones(
                                     (Tq, 1), jnp.float32)
            if causal:
                k_pos = src * Tk + k_local
                cm = jnp.where(k_pos[None, :] > q_pos[:, None],
                               -jnp.inf, 0.0).astype(jnp.float32)
                mask = cm if mask is None else mask + cm
            o, m, l = _online_block(qf, kc.astype(jnp.float32),
                                    vc.astype(jnp.float32), o, m, l, mask)
            return (o, m, l), None

        if n_chunks == 1:
            (o, m, l), _ = fold_chunk((o, m, l), 0)
        else:
            (o, m, l), _ = jax.lax.scan(
                jax.checkpoint(fold_chunk), (o, m, l),
                jnp.arange(n_chunks))
        return o, m, l

    # Own block first, then rotate-then-fold for the remaining P-1 hops —
    # no wasted final ppermute whose result would be discarded.
    o, m, l = fold(o, m, l, k, v, 0)

    def step(carry, t):
        o, m, l, k_blk, v_blk = carry
        perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        o, m, l = fold(o, m, l, k_blk, v_blk, t)
        return (o, m, l, k_blk, v_blk), None

    if axis_size > 1:
        (o, m, l, _, _), _ = jax.lax.scan(
            jax.checkpoint(step), (o, m, l, k, v),
            jnp.arange(1, axis_size))
    # Fully-masked rows (can't happen causally: a row always sees itself)
    # would have l == 0; guard anyway.
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)


@partial(jax.jit, static_argnames=("causal",))
def reference_attention(q, k, v, *, causal: bool = False):
    """Unsharded full attention — the numerics oracle for tests."""
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(jnp.float32)
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        T = q.shape[1]
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p,
                      v.astype(jnp.float32)).astype(q.dtype)
