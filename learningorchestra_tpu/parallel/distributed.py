"""Multi-host bootstrap — the communication backend over ICI/DCN.

The reference's distributed backend is Spark standalone RPC: driver-in-
service ↔ master:7077 ↔ workers:41352 over Docker overlay networks, with
py4j bridging Python↔JVM and all bulk data routed through MongoDB
(SURVEY.md §2 "Distributed communication backend"). Here the backend is
``jax.distributed`` + XLA collectives: one controller process per TPU host
joins a coordination service, after which ``jax.devices()`` is the *global*
device list and every collective (psum/all_gather/reduce_scatter/ppermute
emitted by pjit/shard_map) rides ICI within a slice and DCN across slices —
no first-party RPC layer to maintain.

Single-host (and CPU-simulated) runs skip initialization entirely; the same
mesh code paths work unchanged, which is what lets tests run on an 8-device
CPU mesh (tests/conftest.py) and the driver dry-run multi-chip shardings
without TPU hardware.
"""

from __future__ import annotations

import os
from typing import Optional

import jax

from learningorchestra_tpu import config

_initialized = False


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Join (or start) the multi-host coordination service.

    Arguments default from the standard env vars so a TPU pod launcher can
    start identical processes on every host:

    - ``LO_TPU_COORDINATOR`` (host:port of process 0),
    - ``LO_TPU_NUM_PROCESSES``, ``LO_TPU_PROCESS_ID``.

    The coordinator address is required to form a pod: besides seeding
    ``jax.distributed``, its host also locates the SPMD job channel
    (parallel/spmd.py — coordinator host, port + 1). No-op when unset
    (single-host dev/test).
    """
    global _initialized
    if _initialized:
        return
    coordinator_address = coordinator_address or config.coordinator_address()
    if num_processes is None:
        num_processes = config.num_processes()
    if process_id is None:
        process_id = config.process_id()
    if coordinator_address is None and num_processes is None:
        return  # single-host
    if "cpu" in (os.environ.get("JAX_PLATFORMS") or ""):
        # Cross-process collectives on the CPU backend need an explicit
        # implementation on older jax (0.4.x defaults to none, and every
        # multi-process psum fails to compile). Best-effort: the option
        # name may not exist on other versions.
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:  # noqa: BLE001 — version-dependent option
            pass
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id)
    _initialized = True


def process_info() -> dict:
    """Topology snapshot for the /cluster observability route."""
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_device_count": jax.local_device_count(),
        "global_device_count": jax.device_count(),
        "devices": [str(d) for d in jax.devices()],
        "platform": jax.default_backend(),
    }
