from learningorchestra_tpu.parallel.mesh import (  # noqa: F401
    MeshRuntime, get_runtime, local_mesh, pad_rows, replicate, shard_rows)
