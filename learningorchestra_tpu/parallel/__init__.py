# Version shims (jax.shard_map on older runtimes) must load before any
# kernel or mesh op runs; every compute module imports through this
# package, while jax-free entry points (supervisor, client SDK) never
# pay the jax import.
from learningorchestra_tpu.utils import compat as _compat  # noqa: F401

from learningorchestra_tpu.parallel.mesh import (  # noqa: F401
    MeshRuntime, get_runtime, local_mesh, pad_rows, replicate, shard_rows)
