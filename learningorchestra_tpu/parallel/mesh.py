"""Mesh runtime — the compute tier replacing the reference's Spark cluster.

The reference scales by adding Spark workers to a standalone cluster
(`docker service scale microservice_sparkworker=N`, reference
docs/usage.md:21-33) and partitions DataFrames across them (800 shuffle
partitions, model_builder.py:80). The TPU-native equivalent is a
``jax.sharding.Mesh`` over the attached devices with named axes:

- ``data`` — rows of a dataset are sharded across this axis (the analogue of
  Spark's RDD partitioning; SURVEY.md §2 parallelism #1). All trainers and
  analytics reductions psum over it, which XLA lowers to ICI all-reduces.
- ``model`` — parameters/features shard across this axis for wide models
  (no Spark analogue; the TPU-idiomatic hook SURVEY.md §2 calls for).

Arrays move host→device exactly once per job via ``shard_rows`` (row-sharded
``jax.device_put``); every subsequent op runs device-side. Multi-host:
``jax.distributed`` bootstrap lives in ``parallel/distributed.py``; this
module only sees the global device list, so the same code drives 1 chip or a
pod slice.
"""

from __future__ import annotations

import threading
import weakref
from typing import Optional, Tuple

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from learningorchestra_tpu.config import Settings, settings as global_settings

DATA_AXIS = "data"
MODEL_AXIS = "model"
#: Sequence/context-parallel axis: long sequences shard their length across
#: it and attention runs as a ring over ICI (parallel/ring_attention.py).
SEQ_AXIS = "seq"


def local_mesh(cfg: Optional[Settings] = None,
               devices=None) -> Mesh:
    """Build the (data, model, seq) mesh over the given (default: all)
    devices.

    Default layout puts every device on the data axis — the reference's
    pure-data-parallel Spark layout. ``cfg.mesh_shape = "D,M"`` or
    ``"D,M,S"`` forces the layout (e.g. "2,2,2" on 8 devices for
    data×model×seq sharding; the seq axis defaults to 1).
    """
    cfg = cfg or global_settings
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if cfg.mesh_shape:
        dims = [int(x) for x in cfg.mesh_shape.split(",")]
        if len(dims) not in (2, 3):
            raise ValueError(
                f"mesh_shape {cfg.mesh_shape!r} must be 'D,M' or 'D,M,S'")
        if len(dims) == 2:
            dims.append(1)                      # no seq axis requested
        d, m, s = dims
        if d * m * s != n:
            raise ValueError(
                f"mesh_shape {cfg.mesh_shape} != device count {n}")
    else:
        d, m, s = n, 1, 1
    arr = mesh_utils.create_device_mesh((d, m, s), devices=devices)
    return Mesh(arr, (DATA_AXIS, MODEL_AXIS, SEQ_AXIS))


def pad_rows(arr: np.ndarray, multiple: int) -> Tuple[np.ndarray, int]:
    """Pad axis-0 to a multiple (static shapes for XLA); returns (padded, n).

    Padding rows are zeros; compute masks them via ``row < n`` so results are
    exact — the device-side analogue of the reference filtering out its
    metadata row before compute (projection.py:105-110).
    """
    n = arr.shape[0]
    pad = (-n) % multiple
    if pad:
        arr = np.concatenate(
            [arr, np.zeros((pad,) + arr.shape[1:], dtype=arr.dtype)], axis=0)
    return arr, n


def shard_rows(mesh: Mesh, arr: np.ndarray) -> Tuple[jax.Array, int]:
    """Place a host array on the mesh sharded along rows (data axis).

    Returns the device array (rows padded to the data-axis size) and the
    true row count for masking.

    Multi-process: ``jax.device_put`` of a host array only addresses local
    devices, so the global array is assembled per-process from a callback —
    each process materializes exactly the row blocks its addressable shards
    own (every process holds the same host array, rebuilt from the shared
    store; SURVEY.md §2's Mongo-as-shared-data-plane role).
    """
    arr = np.asarray(arr)
    n_shards = mesh.shape[DATA_AXIS]
    padded, n = pad_rows(arr, n_shards)
    spec = P(DATA_AXIS, *([None] * (arr.ndim - 1)))
    sharding = NamedSharding(mesh, spec)
    if jax.process_count() > 1:
        out = jax.make_array_from_callback(
            padded.shape, sharding, lambda idx: padded[idx])
    else:
        out = jax.device_put(padded, sharding)
    return out, n


def _plan_placement(ranges, n_rows: int, shard_map) -> None:
    """Classify each addressable shard's row range against the dataset's
    ingest shard map (owner host → contiguous row range, recorded by the
    range-partitioned ingest in catalog/ingest.py): rows whose owning
    host is the host that will read them count local, the rest remote —
    readpipe's ``lo_shard_local_reads_total`` / ``_remote_reads_total``,
    whose local fraction is THE placement health signal. An aligned feed
    (devices in partition order over a partition-aligned dataset) plans
    ~1.0 local, with only boundary tails remote; those tails still read
    correctly through the replicate.fetch_chunk repair path.

    On a real multi-process pod every range here is addressed by THIS
    host (``spmd.local_host_id``) — as it is under an explicit
    ``LO_TPU_SHARD_HOST``. A single-process sim addresses every device,
    so it models the pod topology instead: consecutive devices per host,
    range k of D read by host k*H//D."""
    if not shard_map:
        return
    parts = shard_map.get("partitions") or []
    hosts = max(1, int(shard_map.get("hosts") or 1))
    if not parts:
        return
    from learningorchestra_tpu import config as _config
    from learningorchestra_tpu.catalog import readpipe
    from learningorchestra_tpu.parallel import spmd

    pinned = _config.shard_host() is not None or jax.process_count() > 1
    n_ranges = max(1, len(ranges))
    local_total = 0
    remote_total = 0
    for k, (start, stop) in enumerate(ranges):
        start, stop = int(start), min(int(stop), n_rows)
        if stop <= start:
            continue
        reader = (spmd.local_host_id() if pinned
                  else (k * hosts) // n_ranges)
        local = 0
        for p in parts:
            if int(p.get("host", -1)) != reader:
                continue
            r0 = int(p.get("row_start", 0))
            r1 = r0 + int(p.get("rows", 0))
            local += max(0, min(stop, r1) - max(start, r0))
        local_total += local
        remote_total += (stop - start) - local
    if local_total:
        readpipe.bump_shard("local_reads", local_total)
    if remote_total:
        readpipe.bump_shard("remote_reads", remote_total)


def shard_chunked(mesh: Mesh, design,
                  prefetch: Optional[int] = None) -> Tuple[jax.Array, int]:
    """Row-shard a LAZY design matrix (ops/preprocess.ChunkedDesign
    protocol: ``.shape``/``.dtype``/``.rows(start, stop)``) without ever
    materializing it fully on the host.

    ``jax.make_array_from_callback`` asks for each addressable shard's
    index; the callback materializes exactly that row range from the chunk
    store. On a pod each process therefore reads only its OWN shards —
    host-RAM cost divides by process count instead of multiplying
    (VERDICT r4 #1; the reference's executors likewise hold only their
    partitions, model_builder.py:200). Tail padding rows are zeros, masked
    by ``row < n`` downstream exactly like ``shard_rows``.

    Device feeding is DOUBLE-BUFFERED (the streamed-fit data path's
    host→device overlap): the addressable shard ranges are known up
    front, so a readpipe worker materializes shard i+1's rows from the
    chunk store while ``device_put`` of shard i runs on the caller
    thread. At most two shards are ever resident beyond what the device
    holds — per-process host memory stays O(shard), not O(dataset).
    ``prefetch=0`` (or a single addressable shard) degenerates to the
    strictly serial read→put loop, the parity oracle; a range jax
    requests that was not read ahead (defensive — callback order is
    expected to follow the addressable-device order) materializes
    inline."""
    n = int(design.shape[0])
    n_shards = mesh.shape[DATA_AXIS]
    padded_n = n + (-n) % n_shards
    tail = tuple(int(s) for s in design.shape[1:])
    sharding = NamedSharding(mesh, P(DATA_AXIS, *([None] * len(tail))))
    dtype = np.dtype(getattr(design, "dtype", np.float32))

    def read_range(start: int, stop: int) -> np.ndarray:
        parts = []
        if start < n:
            parts.append(np.ascontiguousarray(
                np.asarray(design.rows(start, min(stop, n)), dtype)))
        pad = stop - max(start, n)
        if pad > 0:
            parts.append(np.zeros((pad,) + tail, dtype))
        return parts[0] if len(parts) == 1 else np.concatenate(parts, 0)

    def norm(idx) -> Tuple[int, int]:
        rs = idx[0]
        return (rs.start or 0,
                padded_n if rs.stop is None else rs.stop)

    from learningorchestra_tpu.catalog import readpipe

    # Deduped addressable shard ranges in device order (devices on a >1
    # model/seq axis replicate a row range; read it once).
    order: list = []
    seen = set()
    for idx in sharding.addressable_devices_indices_map(
            (padded_n,) + tail).values():
        key = norm(idx)
        if key not in seen:
            seen.add(key)
            order.append(key)
    _plan_placement(order, n, getattr(design, "shard_map", None))
    depth = min(2, readpipe.prefetch_depth(prefetch))
    if depth <= 0 or len(order) <= 1:
        out = jax.make_array_from_callback(
            (padded_n,) + tail, sharding,
            lambda idx: read_range(*norm(idx)))
        return out, n

    pool = readpipe.pool()
    state_lock = threading.Lock()
    pending = list(order)            # ranges not yet submitted
    futures: dict = {}               # (start, stop) -> Future

    def submit_ahead() -> None:
        with state_lock:
            while pending and len(futures) < depth:
                key = pending.pop(0)
                futures[key] = pool.submit(read_range, *key)

    submit_ahead()

    def cb(idx):
        key = norm(idx)
        with state_lock:
            fut = futures.pop(key, None)
        submit_ahead()           # keep the next read in flight while we
        if fut is None:          # (possibly) block on this one
            return read_range(*key)
        if not fut.done():
            readpipe.bump("prefetch_stalls")
        try:
            return fut.result()
        except BaseException:
            readpipe.bump("worker_errors")
            raise

    try:
        out = jax.make_array_from_callback((padded_n,) + tail, sharding, cb)
    finally:
        with state_lock:
            leftover = list(futures.values())
            futures.clear()
            pending.clear()
        for fut in leftover:
            fut.cancel()
        for fut in leftover:
            if not fut.cancelled():
                try:
                    fut.result()
                except BaseException:  # noqa: BLE001 — result discarded
                    pass
    return out, n


def replicate(mesh: Mesh, x) -> jax.Array:
    """Replicate a value across every mesh device (fully-replicated spec)."""
    x = np.asarray(x)
    sharding = NamedSharding(mesh, P())
    if jax.process_count() > 1:
        return jax.make_array_from_callback(
            x.shape, sharding, lambda idx: x[idx])
    return jax.device_put(x, sharding)


def host_rows(x: jax.Array) -> np.ndarray:
    """Device array → host numpy, valid under multi-process.

    Row-sharded outputs are not fully addressable when the mesh spans
    processes; ``process_allgather`` (a collective — every process must
    call it, which the SPMD dispatch protocol guarantees) gathers the
    global value. Single-process is a plain copy."""
    if jax.process_count() > 1 and not x.is_fully_addressable:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(x, tiled=True))
    return np.asarray(x)


class MeshRuntime:
    """Process-wide mesh holder (built lazily on first compute job).

    The reference builds one SparkSession per request and tears it down
    (model_builder.py:70-95,177); devices are persistent here, so the mesh is
    built once and shared by every job in the server process.

    ``shard_rows`` memoizes host→device transfers per host array: a
    5-classifier build shards the same design matrix five times (and PCIe —
    or worse, a tunneled TPU link — makes each gigabyte-scale transfer the
    dominant cost), so the sharded device array is cached keyed by the host
    array's identity and dropped when the host array is garbage-collected.
    Callers must treat arrays handed to ``shard_rows`` as immutable; the
    cache *enforces* this by marking cached owner-arrays read-only (a later
    in-place write raises instead of silently computing on stale device
    data). Views are sharded uncached.
    """

    def __init__(self, cfg: Optional[Settings] = None):
        self.cfg = cfg or global_settings
        # RLock: cache-eviction finalizers can fire from gc inside a
        # lock-holding allocation; a plain Lock would self-deadlock.
        self._lock = threading.RLock()
        self._mesh: Optional[Mesh] = None
        self._transfer_cache: dict = {}

    @property
    def mesh(self) -> Mesh:
        with self._lock:
            if self._mesh is None:
                self._mesh = local_mesh(self.cfg)
            return self._mesh

    def shard_rows(self, arr: np.ndarray) -> Tuple[jax.Array, int]:
        # Structural SPMD guard: on a multi-process pod, host→device entry
        # is only legal inside a dispatched job scope (parallel/spmd.py) —
        # every mesh op funnels through here or replicate, so nothing can
        # "forget" to dispatch and wedge the pod mid-collective.
        from learningorchestra_tpu.parallel import spmd

        spmd.check_mesh_entry("shard_rows")
        if hasattr(arr, "rows") and not isinstance(arr, np.ndarray):
            # Lazy design matrix (ChunkedDesign protocol): device shards
            # materialize from per-shard range reads; cache by identity
            # like host arrays (a 5-classifier build shards the same
            # design five times). Designs pin their row snapshot at
            # construction, so identity-keyed caching is sound.
            key = ("design", id(arr))
            with self._lock:
                hit = self._transfer_cache.get(key)
            if hit is not None:
                return hit
            out = shard_chunked(self.mesh, arr,
                                prefetch=self.cfg.prefetch_chunks)
            with self._lock:
                self._transfer_cache[key] = out

                def _evict_d(cache=self._transfer_cache, key=key,
                             lock=self._lock):
                    with lock:
                        cache.pop(key, None)

                weakref.finalize(arr, _evict_d)
            return out
        if not isinstance(arr, np.ndarray):
            return shard_rows(self.mesh, arr)
        key = (id(arr), arr.shape, str(arr.dtype))
        with self._lock:
            hit = self._transfer_cache.get(key)
        if hit is not None:
            return hit
        # Enforce the immutability contract instead of just documenting it:
        # freeze the host array on first caching so an in-place mutation
        # (which would silently serve stale device data) raises at the
        # mutation site. Views never enter the cache — freezing a view
        # leaves its base writable, so mutation through the base would
        # still serve stale device data silently.
        if arr.base is not None or not arr.flags.owndata:
            return shard_rows(self.mesh, arr)
        arr.flags.writeable = False
        out = shard_rows(self.mesh, arr)
        with self._lock:
            self._transfer_cache[key] = out

            def _evict(cache=self._transfer_cache, key=key, lock=self._lock):
                with lock:
                    cache.pop(key, None)

            # Drop the device copy when the host array dies (also guards
            # against a recycled id() pointing at the stale entry).
            weakref.finalize(arr, _evict)
        return out

    def replicate(self, x) -> jax.Array:
        from learningorchestra_tpu.parallel import spmd

        spmd.check_mesh_entry("replicate")
        return replicate(self.mesh, x)


_runtime: Optional[MeshRuntime] = None
_runtime_lock = threading.Lock()


def get_runtime(cfg: Optional[Settings] = None) -> MeshRuntime:
    global _runtime
    with _runtime_lock:
        if _runtime is None:
            _runtime = MeshRuntime(cfg)
        return _runtime
