"""Python client SDK.

Mirrors the reference pip package ``learning_orchestra_client`` (reference
learning_orchestra_client/__init__.py): one class per service —
``DatabaseApi``, ``Projection``, ``Histogram``, ``DataTypeHandler``,
``Tsne``, ``Pca``, ``Model`` — sharing a ``Context`` and an
``AsyncronousWait`` helper that polls a dataset's metadata until
``finished`` flips true (reference __init__.py:14-32, 3-second cadence).

Differences from the reference, by design:
- one base URL instead of seven hard-coded ports (__init__.py:56-333) —
  the server hosts every surface under path prefixes;
- polling raises ``JobFailed`` when metadata carries ``error`` (the
  reference would poll forever on a crashed job, SURVEY.md §5);
- ``Model.create_model`` takes declarative ``steps`` in place of
  arbitrary ``preprocessor_code`` (exec is opt-in server-side).
"""

from __future__ import annotations

import json
import random
import re
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Sequence

import requests

DEFAULT_POLL_SECONDS = 3.0  # reference cadence (__init__.py:31)


class JobFailed(RuntimeError):
    pass


class JobDeadlineExpired(JobFailed):
    """A server-side job was killed by the liveness watchdog: it made no
    progress for ``LO_TPU_JOB_DEADLINE_S`` (hung device program). The
    failure is retryable INFRASTRUCTURE — the supervisor restarts the
    pod and the rescan re-runs the job, which resumes from its fit
    checkpoint — so polling the same dataset again after the pod
    recovers may find it finished. Subclasses :class:`JobFailed` so
    existing handlers keep working."""


class DeadlineExpired(RuntimeError):
    """A per-call deadline budget ran out client-side: raised instead of
    sending (or retrying) a request whose answer the caller no longer
    wants. The server's 504 for the same condition also surfaces as
    this, so callers handle one type either way."""


class Context:
    """Connection context shared by the service clients.

    ``timeout`` bounds job polling (and the synchronous model build, which
    legitimately runs for the whole fit); ``request_timeout`` bounds every
    other HTTP call so a hung server can never hang the client forever.
    Connection errors and 503s (pod mid-recovery) retry with capped,
    full-jitter exponential backoff on every method: GET/DELETE are
    idempotent by nature, and POSTs carry an ``Idempotency-Key`` header
    the server dedupes on, so a retried create whose first attempt
    actually landed replays the original response instead of surfacing a
    spurious 409 (this closes the old "POSTs never auto-retry" carve-out).

    Backoff discipline (every sleep is bounded):
    - per-attempt sleep is ``uniform(0, min(backoff_cap, base * 2^n))``
      (full jitter — a fleet of clients retrying a recovering pod must
      not stampede it in lockstep);
    - a server ``Retry-After`` hint is honored but clamped to
      ``retry_after_cap`` (a confused server must not park clients for
      an hour);
    - cumulative sleep across one logical request never exceeds
      ``max_retry_wait``: past it, the last response/error is returned/
      raised even if retries remain.
    """

    def __init__(self, base_url: str, poll_seconds: float =
                 DEFAULT_POLL_SECONDS, timeout: float = 600.0,
                 request_timeout: float = 30.0, retries: int = 3,
                 backoff_seconds: float = 0.5,
                 backoff_cap_seconds: float = 15.0,
                 retry_after_cap: float = 30.0,
                 max_retry_wait: float = 120.0):
        self.base_url = base_url.rstrip("/")
        self.poll_seconds = poll_seconds
        self.timeout = timeout
        self.request_timeout = request_timeout
        self.retries = retries
        self.backoff_seconds = backoff_seconds
        self.backoff_cap_seconds = backoff_cap_seconds
        self.retry_after_cap = retry_after_cap
        self.max_retry_wait = max_retry_wait
        self._tls = threading.local()

    def url(self, path: str) -> str:
        return f"{self.base_url}{path}"

    def _session(self) -> requests.Session:
        """One keep-alive session per (Context, thread) — connection
        reuse instead of a TCP handshake + a fresh server handler
        thread per call (the ingest path got the same treatment in
        PR 5; measured here: 2x HTTP throughput and a ~2x p50 cut on
        the online predict loop). Thread-local because
        ``requests.Session`` is not thread-safe."""
        s = getattr(self._tls, "session", None)
        if s is None:
            s = self._tls.session = requests.Session()
        return s

    def _backoff(self, attempt: int) -> float:
        return random.uniform(0.0, min(self.backoff_cap_seconds,
                                       self.backoff_seconds * (2 ** attempt)))

    def request(self, method: str, path: str,
                timeout: Optional[float] = None,
                retry_503: bool = True,
                deadline_ms: Optional[float] = None, **kwargs):
        """``retry_503=False`` returns a 503 response immediately instead
        of backing off: a health probe's 503 IS the answer (degraded),
        not backpressure to wait out. Connection-error retries keep
        their normal budget either way.

        ``deadline_ms`` is an END-TO-END budget for this logical call:
        every attempt carries the REMAINING budget in ``X-Deadline-Ms``
        (the server's admission control and in-queue expiry honor it),
        retry sleeps and per-attempt socket timeouts are clamped so the
        retry loop can never outlive the budget, and a spent budget
        raises :class:`DeadlineExpired` client-side rather than sending
        a request whose answer nobody will read. A 504 (the server's
        terminal deadline answer) is NEVER retried — re-sending
        already-abandoned work only deepens the overload that caused
        the miss."""
        deadline = timeout if timeout is not None else self.request_timeout
        retries = self.retries
        hard_deadline = (time.monotonic() + deadline_ms / 1e3
                         if deadline_ms is not None else None)
        if method.upper() == "POST":
            # One key per LOGICAL create, shared by all its retries: the
            # server replays the first landed attempt's response.
            headers = dict(kwargs.pop("headers", None) or {})
            headers.setdefault("Idempotency-Key", uuid.uuid4().hex)
            kwargs["headers"] = headers
        attempt = 0
        slept = 0.0

        def remaining_ms() -> Optional[float]:
            if hard_deadline is None:
                return None
            return (hard_deadline - time.monotonic()) * 1e3

        def sleep(wait: float) -> bool:
            """Sleep within the total-wait budget; False = budget spent
            (either the jitter budget or the caller's deadline)."""
            nonlocal slept
            wait = min(wait, max(0.0, self.max_retry_wait - slept))
            rem = remaining_ms()
            if rem is not None:
                # A sleep that would consume the whole remaining budget
                # guarantees the next attempt dies at admission: stop
                # retrying instead.
                if wait * 1e3 >= rem:
                    return False
                wait = min(wait, max(0.0, rem / 1e3))
            if wait <= 0 and slept >= self.max_retry_wait:
                return False
            time.sleep(wait)
            slept += wait
            return True

        while True:
            rem = remaining_ms()
            attempt_timeout = deadline
            if rem is not None:
                if rem <= 0:
                    raise DeadlineExpired(
                        f"deadline budget ({deadline_ms:.0f}ms) spent "
                        f"before {method} {path} could complete")
                # Fresh copy per attempt: mutating a caller-supplied
                # headers dict would leak this call's (stale, shrinking)
                # budget into the caller's later requests.
                headers = dict(kwargs.get("headers") or {})
                headers["X-Deadline-Ms"] = str(int(max(1, rem)))
                kwargs["headers"] = headers
                # Small slack past the remaining budget: the server
                # answers its terminal 504 AT the deadline, and cutting
                # the socket exactly there loses the typed answer to a
                # photo-finish race.
                attempt_timeout = min(deadline, rem / 1e3 + 0.5)
            try:
                resp = self._session().request(method, self.url(path),
                                               timeout=attempt_timeout,
                                               **kwargs)
            except requests.ConnectionError as e:
                # ConnectTimeout is BOTH ConnectionError and Timeout: it
                # is terminal-as-deadline only when the budget is
                # actually gone; with budget left it keeps a connection
                # error's normal retry behavior.
                if hard_deadline is not None and isinstance(
                        e, requests.Timeout) and (remaining_ms() or 0) <= 0:
                    raise DeadlineExpired(
                        f"deadline budget ({deadline_ms:.0f}ms) spent "
                        f"connecting for {method} {path}") from None
                if attempt >= retries or not sleep(self._backoff(attempt)):
                    raise
                attempt += 1
                continue
            except requests.Timeout:
                # Terminal DeadlineExpired ONLY when the budget really
                # is gone (the attempt's socket timeout was the clamped
                # remaining budget). A plain request_timeout firing with
                # budget to spare stays a Timeout — misreporting it as
                # a deadline miss would hide a retryable stall.
                if hard_deadline is not None and (remaining_ms() or 0) <= 0:
                    raise DeadlineExpired(
                        f"deadline budget ({deadline_ms:.0f}ms) spent "
                        f"waiting on {method} {path}") from None
                raise
            if resp.status_code == 503 and retry_503 and attempt < retries:
                # Pod mid-recovery (supervisor restart): honor the
                # server's backoff hint, clamped.
                try:
                    wait = float(resp.headers.get("Retry-After", ""))
                except ValueError:
                    wait = self._backoff(attempt)
                if not sleep(min(max(wait, 0.0), self.retry_after_cap)):
                    return resp
                attempt += 1
                continue
            return resp

    def get(self, path: str, **kw):
        return self.request("GET", path, **kw)

    def post(self, path: str, **kw):
        return self.request("POST", path, **kw)

    def patch(self, path: str, **kw):
        return self.request("PATCH", path, **kw)

    def delete(self, path: str, **kw):
        return self.request("DELETE", path, **kw)

    # -- tracing (GET /traces, GET /trace/{id}) ------------------------------

    def traces(self, route: Optional[str] = None,
               kind: Optional[str] = None,
               min_ms: Optional[float] = None,
               limit: Optional[int] = None) -> List[Dict[str, Any]]:
        """Recent traces from the server's ring buffer, newest first —
        filterable by route substring (HTTP traces), job kind, and
        minimum root-span duration (ms)."""
        params = {k: v for k, v in (("route", route), ("kind", kind),
                                    ("min_ms", min_ms), ("limit", limit))
                  if v is not None}
        return ResponseTreat.treatment(self.get("/traces", params=params))

    def trace(self, trace_id: str) -> Dict[str, Any]:
        """One trace's span tree (``GET /trace/{id}``). Every response
        carries its trace id in ``X-Request-Id`` — and every error this
        client raises quotes it — so the id to pass here is always at
        hand."""
        return ResponseTreat.treatment(self.get(f"/trace/{trace_id}"))


class ResponseTreat:
    """Uniform response handling (reference __init__.py:35-52)."""

    @staticmethod
    def treatment(response, pretty: bool = False):
        payload = response.json()
        if response.status_code >= 400:
            # Quote the server's X-Request-Id: the trace id of the failed
            # call, resolvable via GET /trace/{id} and greppable in the
            # server's structured logs.
            rid = response.headers.get("X-Request-Id")
            msg = (f"HTTP {response.status_code}: {payload.get('result')}"
                   + (f" [request-id {rid}]" if rid else ""))
            if response.status_code == 504:
                # The server's terminal deadline answer: typed so
                # callers handle client-side and server-side budget
                # expiry identically — and so nothing upstream is
                # tempted to retry it.
                raise DeadlineExpired(msg)
            raise RuntimeError(msg)
        return json.dumps(payload, indent=2) if pretty else payload


class AsyncronousWait:
    """Polls dataset metadata until finished (reference __init__.py:14-32;
    the misspelling is the reference's own public API name)."""

    def __init__(self, context: Context):
        self.context = context

    def wait(self, dataset_name: str,
             tolerate_missing: bool = False) -> Dict[str, Any]:
        """Poll until the dataset's metadata reports ``finished``.

        ``tolerate_missing`` keeps polling through 404s until the deadline —
        for datasets the server has *promised* to create (an async model
        build creates its prediction datasets only after preprocessing), as
        opposed to datasets that must already exist.
        """
        deadline = time.time() + self.context.timeout
        while True:
            resp = self.context.get(f"/files/{dataset_name}",
                                    params={"limit": 1})
            if resp.status_code == 404:
                if not tolerate_missing:
                    raise KeyError(f"dataset not found: {dataset_name}")
                if time.time() > deadline:
                    raise TimeoutError(
                        f"timed out waiting for {dataset_name} to appear")
                time.sleep(self.context.poll_seconds)
                continue
            docs = ResponseTreat.treatment(resp)
            if docs:
                meta = docs[0]
                if meta.get("error"):
                    retries = meta.get("retries")
                    suffix = (f" (retries={retries})"
                              if retries else "")
                    msg = f"{dataset_name}: {meta['error']}{suffix}"
                    # The watchdog's kill is typed: callers can treat
                    # "the job hung and will be retried after the pod
                    # restarts" differently from a deterministic input
                    # error that would fail identically again.
                    if str(meta["error"]).startswith(
                            "interrupted: watchdog"):
                        raise JobDeadlineExpired(msg)
                    raise JobFailed(msg)
                if meta.get("finished"):
                    return meta
            if time.time() > deadline:
                raise TimeoutError(f"timed out waiting for {dataset_name}")
            time.sleep(self.context.poll_seconds)


def micro_batches(rows: Sequence[Any],
                  max_batch: int) -> List[Sequence[Any]]:
    """Split an inline-rows payload into server-acceptable micro-batches
    (the server rejects requests above its ``serve_max_batch`` with 406;
    splitting client-side lets ``predict_online`` take any size input)."""
    if max_batch <= 0:
        raise ValueError("max_batch must be positive")
    return [rows[i:i + max_batch] for i in range(0, len(rows), max_batch)]


class _ServiceClient:
    def __init__(self, context: Context):
        self.context = context
        self.waiter = AsyncronousWait(context)


class DatabaseApi(_ServiceClient):
    """Dataset CRUD (reference __init__.py:55-101)."""

    def create_file(self, filename: str, url: str, wait: bool = False,
                    partitions: Optional[int] = None) -> Dict:
        """``partitions`` opts this ingest into the server's
        range-partitioned path (N concurrent per-host byte-range
        fetches); None defers to the server's configured default."""
        body: Dict = {"filename": filename, "url": url}
        if partitions is not None:
            body["partitions"] = int(partitions)
        resp = self.context.post("/files", json=body)
        out = ResponseTreat.treatment(resp)
        if wait:
            self.waiter.wait(filename)
        return out

    def read_file(self, filename: str, skip: int = 0, limit: int = 10,
                  query: Optional[Dict] = None) -> List[Dict]:
        params = {"skip": skip, "limit": limit}
        if query:
            params["query"] = json.dumps(query)
        return ResponseTreat.treatment(
            self.context.get(f"/files/{filename}", params=params))

    def read_files_descriptor(self) -> List[Dict]:
        return ResponseTreat.treatment(self.context.get("/files"))

    def delete_file(self, filename: str) -> Dict:
        return ResponseTreat.treatment(
            self.context.delete(f"/files/{filename}"))


class Projection(_ServiceClient):
    """Column projection (reference __init__.py:104-135)."""

    def create_projection(self, parent_filename: str,
                          projection_filename: str,
                          fields: Sequence[str],
                          wait: bool = True) -> Dict:
        self.waiter.wait(parent_filename)
        resp = self.context.post(
            f"/projections/{parent_filename}",
            json={"projection_filename": projection_filename,
                  "fields": list(fields)})
        out = ResponseTreat.treatment(resp)
        if wait:
            self.waiter.wait(projection_filename)
        return out


class Histogram(_ServiceClient):
    """Histogram creation (reference __init__.py:138-169)."""

    def create_histogram(self, parent_filename: str,
                         histogram_filename: str, fields: Sequence[str],
                         wait: bool = True) -> Dict:
        self.waiter.wait(parent_filename)
        resp = self.context.post(
            f"/histograms/{parent_filename}",
            json={"histogram_filename": histogram_filename,
                  "fields": list(fields)})
        out = ResponseTreat.treatment(resp)
        if wait:
            self.waiter.wait(histogram_filename)
        return out


class DataTypeHandler(_ServiceClient):
    """Field type coercion (reference __init__.py:311-329)."""

    def change_file_type(self, filename: str,
                         fields_dict: Dict[str, str]) -> Dict:
        self.waiter.wait(filename)
        return ResponseTreat.treatment(self.context.patch(
            f"/fieldtypes/{filename}", json=fields_dict))


class _ImageClient(_ServiceClient):
    method = ""

    def create_image_plot(self, image_name: str, parent_filename: str,
                          label_name: Optional[str] = None,
                          wait: bool = True, **kwargs) -> Dict:
        self.waiter.wait(parent_filename)
        body = {"image_name": image_name, **kwargs}
        if label_name:
            body["label_name"] = label_name
        resp = self.context.post(
            f"/{self.method}/images/{parent_filename}", json=body)
        out = ResponseTreat.treatment(resp)
        if wait and "poll" in out:
            self.waiter.wait(out["poll"])
        return out

    def read_image_plot(self, image_name: str) -> bytes:
        resp = self.context.get(f"/{self.method}/images/{image_name}")
        if resp.status_code >= 400:
            raise RuntimeError(f"HTTP {resp.status_code}")
        return resp.content

    def read_image_plots(self) -> List[str]:
        return ResponseTreat.treatment(
            self.context.get(f"/{self.method}/images"))

    def delete_image_plot(self, image_name: str) -> Dict:
        return ResponseTreat.treatment(
            self.context.delete(f"/{self.method}/images/{image_name}"))


class Tsne(_ImageClient):
    """t-SNE image service (reference __init__.py:172-240)."""

    method = "tsne"


class Pca(_ImageClient):
    """PCA image service (reference __init__.py:243-308)."""

    method = "pca"


class Observability(_ServiceClient):
    """Server-side job and metrics introspection (upgrade over the
    reference, which exposed only Spark's web UIs — SURVEY.md §5)."""

    def jobs(self) -> List[Dict]:
        return ResponseTreat.treatment(self.context.get("/jobs"))

    def metrics(self) -> Dict:
        return ResponseTreat.treatment(self.context.get("/metrics"))

    def cluster(self) -> Dict:
        return ResponseTreat.treatment(self.context.get("/cluster"))

    def traces(self, **filters) -> List[Dict]:
        return self.context.traces(**filters)

    def trace(self, trace_id: str) -> Dict:
        return self.context.trace(trace_id)

    # -- resource & capacity plane (GET /resources, /alerts, /healthz) -------

    def resources(self) -> Dict:
        """Per-device HBM + host + disk + compile snapshot of the server
        process (plus last-known worker snapshots on a pod)."""
        return ResponseTreat.treatment(self.context.get("/resources"))

    def alerts(self) -> Dict:
        """The SLO alert engine's state: firing rule names plus every
        rule's value/threshold/streaks (docs/observability.md has the
        rule table), and ``flightrec_latest`` — the freshest flight-
        recorder bundle id, when one exists."""
        return ResponseTreat.treatment(self.context.get("/alerts"))

    def replication(self) -> Dict:
        """The cross-host replication plane (``GET /replication``):
        per-dataset journal lag against each peer's acked watermark,
        the under-replicated list, push/fetch/repair counters, and the
        local ReplicaServer's counters when one is running."""
        return ResponseTreat.treatment(self.context.get("/replication"))

    def healthz(self) -> Dict:
        """The deep health rollup. Returns the check document on 200;
        raises on 503 with the FIRING ALERT NAMES in the message — a
        degraded service names its reasons instead of a bare status
        code — plus the freshest flight-recorder bundle id, so the
        error itself points at the frozen evidence. The probe never
        retries the 503 (the 503 is the answer)."""
        resp = self.context.get("/healthz", retry_503=False)
        try:
            doc = resp.json()
        except ValueError:
            doc = {}
        if resp.status_code == 503:
            checks = doc.get("checks") or {}
            firing = (checks.get("alerts") or {}).get("firing") or []
            failed = sorted(k for k, c in checks.items()
                            if isinstance(c, dict) and not c.get("ok"))
            rid = resp.headers.get("X-Request-Id")
            bundle = doc.get("flightrec_latest")
            # Under-replication names its datasets with their lag: the
            # operator reading this error knows exactly which data a
            # host loss would cost, without a second round trip.
            under = (checks.get("replication") or {}).get(
                "under_replicated") or []
            under_msg = "; under-replicated " + ", ".join(
                f"{u.get('dataset')} ({u.get('lag_bytes')}B behind "
                f"{u.get('peer')})" for u in under) if under else ""
            raise RuntimeError(
                "healthz degraded: failing checks "
                f"{failed or ['unknown']}; firing alerts "
                f"{firing or ['none']}" + under_msg
                + (f" [flight recording {bundle}]" if bundle else "")
                + (f" [request-id {rid}]" if rid else ""))
        return ResponseTreat.treatment(resp)

    # -- telemetry history & flight recorder ---------------------------------

    def history(self, series: Optional[Sequence[str]] = None,
                window_s: Optional[float] = None) -> Dict:
        """Retained metric time-series (``GET /metrics/history``):
        per-series ``[t, value]`` points merged from the server's
        in-memory ring and on-disk segments — including windows from
        BEFORE its last restart. ``series`` filters by exact name or
        dotted prefix (``serving`` matches every ``serving.*``)."""
        params: Dict[str, Any] = {}
        if series:
            params["series"] = ",".join(series)
        if window_s is not None:
            params["window"] = window_s
        return ResponseTreat.treatment(
            self.context.get("/metrics/history", params=params))

    def flight_recordings(self) -> List[Dict]:
        """Flight-recorder bundle summaries, newest first
        (``GET /debug/flightrec``) — each names its reason, wall time
        and on-disk files under ``<store_root>/_flightrec/``."""
        return ResponseTreat.treatment(
            self.context.get("/debug/flightrec"))

    def record_flight(self, reason: str = "manual") -> Dict:
        """Force a flight-recorder bundle right now
        (``POST /debug/flightrec``) — the operator's "freeze the
        evidence" button; returns the bundle id and directory."""
        return ResponseTreat.treatment(self.context.post(
            "/debug/flightrec", json={"reason": reason}))


class Model(_ServiceClient):
    """Model builder (reference __init__.py:332-370)."""

    #: Server-side per-request row cap, learned from the first 406 an
    #: oversized ``predict_online`` gets back (see there).
    _server_max_batch: Optional[int] = None

    def create_model(self, training_filename: str, test_filename: str,
                     prediction_filename: str,
                     classificators_list: Sequence[str], label: str,
                     steps: Sequence[Dict[str, Any]] = (),
                     preprocessor_code: Optional[str] = None,
                     hparams: Optional[Dict] = None,
                     sync: bool = True) -> Dict:
        # Wait on both input datasets first (reference __init__.py:358-359).
        self.waiter.wait(training_filename)
        self.waiter.wait(test_filename)
        body: Dict[str, Any] = {
            "training_filename": training_filename,
            "test_filename": test_filename,
            "prediction_filename": prediction_filename,
            "classificators_list": list(classificators_list),
            "label": label, "sync": sync,
        }
        if steps:
            body["steps"] = list(steps)
        if preprocessor_code is not None:
            body["preprocessor_code"] = preprocessor_code
        if hparams:
            body["hparams"] = hparams
        out = ResponseTreat.treatment(self.context.post(
            "/models", json=body,
            timeout=self.context.timeout if sync else None))
        if not sync:
            for c in classificators_list:
                self.waiter.wait(f"{prediction_filename}_{c}",
                                 tolerate_missing=True)
        return out

    def tune(self, training_filename: str, tune_filename: str,
             classificator: str, configs: Sequence[Dict[str, Any]],
             label: str, steps: Sequence[Dict[str, Any]] = (),
             folds: Optional[int] = None, rungs: Optional[int] = None,
             promote: bool = False, sync: bool = True) -> Dict:
        """Device-resident hyperparameter search (``POST /tune``): fit a
        population of same-family ``configs`` as ONE vmapped device
        program with masked k-fold cross-validation and successive
        halving. The leaderboard (per-config fold scores, fit seconds,
        rung survival, winner) lands in ``tune_filename``'s metadata;
        ``promote=True`` additionally refits the winner on all rows and
        persists it under ``tune_filename`` in the trained-model
        registry (servable via :meth:`predict` / :meth:`predict_online`).
        """
        self.waiter.wait(training_filename)
        body: Dict[str, Any] = {
            "training_filename": training_filename,
            "tune_filename": tune_filename,
            "classificator": classificator,
            "configs": list(configs),
            "label": label, "promote": promote, "sync": sync,
        }
        if steps:
            body["steps"] = list(steps)
        if folds is not None:
            body["folds"] = folds
        if rungs is not None:
            body["rungs"] = rungs
        out = ResponseTreat.treatment(self.context.post(
            "/tune", json=body,
            timeout=self.context.timeout if sync else None))
        if not sync:
            self.waiter.wait(tune_filename, tolerate_missing=True)
        return out

    # -- persisted-model registry (upgrade: reference discards models) ------

    def list_trained_models(self) -> List[Dict]:
        return ResponseTreat.treatment(self.context.get("/trained-models"))

    def predict(self, model_name: str, dataset_name: str,
                prediction_filename: str, wait: bool = True) -> Dict:
        """Apply a persisted model (``<prediction>_<classifier>`` from a
        previous create_model) to any stored dataset. The server runs the
        predict as an async job; ``wait`` polls the output dataset."""
        self.waiter.wait(dataset_name)
        out = ResponseTreat.treatment(self.context.post(
            f"/trained-models/{model_name}/predictions",
            json={"dataset_name": dataset_name,
                  "prediction_filename": prediction_filename}))
        if wait:
            self.waiter.wait(prediction_filename)
        return out

    def predict_online(self, model_name: str, rows: Sequence[Any],
                       max_batch: int = 256,
                       deadline_ms: Optional[float] = None
                       ) -> Dict[str, Any]:
        """Request/response predictions from the online inference tier
        (``POST /trained-models/<name>/predict`` — no dataset, no job,
        no polling; inline feature rows in, predictions out).

        Rides the standard retry machinery: a 503 from a full predict
        queue carries Retry-After, which ``Context.request`` honors
        with capped jittered backoff — so under server backpressure this
        call paces itself instead of failing. The endpoint is exempt
        from server-side idempotency replay (it is read-like), so every
        retry genuinely re-executes against the model.

        Inputs larger than ``max_batch`` (the server's per-request cap,
        ``LO_TPU_SERVE_MAX_BATCH``) split into sequential micro-batches
        client-side. A server configured with a SMALLER cap than
        ``max_batch`` rejects the oversized request with a 406 naming
        its cap; the client reads it and re-splits once instead of
        failing — so the default call works against any server
        configuration. Results concatenate in row order.

        ``deadline_ms`` is an end-to-end budget across the WHOLE call —
        all micro-batches and any retries share it. Each POST carries
        the remaining budget (``X-Deadline-Ms``; the server's admission
        control and in-queue expiry honor it), retry backoff can never
        outlive it, and expiry — client-side or the server's terminal
        504 — raises :class:`DeadlineExpired` immediately, never
        retrying (re-sending work the caller abandoned only deepens
        the overload that caused the miss).

        **Body format**: list-form numeric rows (already-assembled
        design rows) are sent as the binary columnar body
        (``application/x-lo-columnar`` — a packed float32 matrix the
        server feeds to the device with zero per-row JSON decode);
        anything else (dict rows, non-numeric values) falls back to the
        JSON body. Responses are bit-identical either way, and both
        formats work against any server topology
        (``LO_TPU_HTTP_WORKERS``).
        """
        rows = list(rows)
        # One eligibility decision per call: a clean float32 matrix
        # means every micro-batch ships binary.
        columnar = None
        if rows and isinstance(rows[0], (list, tuple)):
            import numpy as _np

            try:
                X = _np.asarray(rows, dtype=_np.float32)
                if X.ndim == 2:
                    columnar = X
            except (TypeError, ValueError):
                columnar = None
        hard_deadline = (time.monotonic() + deadline_ms / 1e3
                         if deadline_ms is not None else None)
        if self._server_max_batch is not None:
            max_batch = min(max_batch, self._server_max_batch)
        for _ in range(2):                   # second pass: server's cap
            preds: List[int] = []
            probs: List[List[float]] = []
            out: Dict[str, Any] = {}
            try:
                # An empty input still makes one POST: the server's
                # contract for empty rows (406) must surface — returning
                # a fabricated empty success would mask e.g. a typo'd
                # model name.
                for idx, chunk in enumerate(
                        micro_batches(rows, max_batch) or [rows]):
                    lo = idx * max_batch
                    rem = None
                    if hard_deadline is not None:
                        rem = (hard_deadline - time.monotonic()) * 1e3
                        if rem <= 0:
                            raise DeadlineExpired(
                                f"deadline budget ({deadline_ms:.0f}ms) "
                                "spent mid-call; "
                                f"{len(preds)}/{len(rows)} rows answered")
                    if columnar is not None:
                        from learningorchestra_tpu.serving.rowchannel \
                            import (COLUMNAR_CONTENT_TYPE,
                                    encode_columnar)

                        resp = self.context.post(
                            f"/trained-models/{model_name}/predict",
                            data=encode_columnar(
                                columnar[lo:lo + max_batch]),
                            headers={"Content-Type":
                                     COLUMNAR_CONTENT_TYPE},
                            deadline_ms=rem)
                    else:
                        resp = self.context.post(
                            f"/trained-models/{model_name}/predict",
                            json={"rows": list(chunk)}, deadline_ms=rem)
                    out = ResponseTreat.treatment(resp)
                    preds.extend(out["predictions"])
                    probs.extend(out["probabilities"])
            except RuntimeError as e:
                m = re.search(r"serve_max_batch=(\d+)", str(e))
                if m and int(m.group(1)) < max_batch:
                    # Remember the server's cap so later calls split
                    # correctly up front instead of paying a guaranteed
                    # 406 round trip each time.
                    max_batch = self._server_max_batch = int(m.group(1))
                    continue
                raise
            return {"model": model_name, "kind": out.get("kind"),
                    "predictions": preds, "probabilities": probs}
        raise RuntimeError(      # pragma: no cover — loop always returns
            "predict_online failed to satisfy the server's batch cap")

    def delete_trained_model(self, model_name: str) -> Dict:
        return ResponseTreat.treatment(
            self.context.delete(f"/trained-models/{model_name}"))
