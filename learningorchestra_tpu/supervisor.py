"""Pod supervisor — elastic recovery for degraded pods.

The reference self-heals at the orchestration tier: every service runs
under Docker Swarm with ``restart_policy: on-failure`` (reference
docker-compose.yml:14-15), so a crashed worker JVM comes back and Spark
re-runs its lost tasks (the MLlib execution model). Our pod runtime had
only the *detection* half: the SPMD watchdog (parallel/spmd.py) converts
a worker death into a poisoned pod with pollable job failures — and then
a human had to rerun ``deploy/run_pod.sh``.

This module closes the loop. A :class:`Supervisor` owns every pod
process on its host and

1. **watches** them — child exit codes, plus a periodic ``/cluster``
   health poll that catches degradations where no *local* process died
   (a remote host's worker vanished and the watchdog poisoned process 0);
2. **restarts** the whole pod on failure, under bounded exponential
   backoff and a restart budget (``Settings.restart_budget`` /
   ``restart_backoff_s``), killing every child first — half a pod can
   never rejoin, so the unit of recovery is the pod, not the process;
3. **advances the mesh epoch** (``LO_TPU_MESH_EPOCH``) on every restart.
   The job channel's handshake rejects a worker whose epoch differs
   (spmd._JobChannel), so a stale process that somehow outlived the kill
   is turned away instead of corrupting the new incarnation's
   collectives, and the epoch-scoped pod poison clears itself — the
   restarted pod serves without manual intervention;
4. **exhausts cleanly**: past the restart budget the supervisor stops
   trying and serves a minimal fallback ``/cluster`` on the pod's port
   reporting why, so operators (and the client SDK) see a reasoned
   failure instead of connection refused.

Job-level recovery composes on top: on startup, process 0's App rescans
the store for datasets failed with an infrastructure error (``pod
failure:`` / ``interrupted:``), and re-runs their recorded job specs up
to ``LO_TPU_JOB_RETRIES`` times (jobs.select_retry_groups +
serving/app.py) — safe because the chunk store is journaled and output
datasets are reset via ``DatasetStore.reopen`` before the re-run. The
full lifecycle is detect (watchdog) → fail (pollable outputs) → restart
(this module, new epoch) → retry (rescan) → succeed.

Run as ``python -m learningorchestra_tpu.supervisor -- <pod command>``;
``deploy/run_pod.sh`` wires this up per host.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional, Sequence

from learningorchestra_tpu.config import Settings, settings as global_settings
from learningorchestra_tpu.utils import structlog

log = structlog.get_logger("supervisor")

#: Exit code a pod process uses for "this incarnation cannot continue but
#: the pod should" — controller lost / stale epoch (serving/__main__.py).
#: Follower supervisors treat it as pod-coordination, not local failure.
RESTARTABLE_EXIT = 3


class Supervisor:
    """Supervise the pod processes of one host; restart them together.

    ``commands`` is one argv per pod process to run on this host (one
    entry in production — the serving module decides server vs worker
    role from LO_TPU_PROCESS_ID; tests pass several to host a whole pod
    under a single supervisor). ``health_url`` optionally names process
    0's ``/cluster`` endpoint; a poll reporting ``pod_error`` triggers a
    restart just like a child death does. ``fallback_port`` is where the
    budget-exhausted failure report is served.

    **Multi-host epoch agreement.** On a pod spanning hosts, each host
    runs its own supervisor, and the job channel admits workers only at
    the exact pod epoch — so the counters must agree. The single source
    of truth is an epoch FILE on the shared store root
    (``<LO_TPU_STORE_ROOT>/.mesh_epoch`` — the same shared filesystem
    the data plane already requires). Host 0's supervisor (env
    ``LO_TPU_PROCESS_ID`` unset or ``0``) OWNS the file: it increments
    it on every restart. Every other supervisor FOLLOWS it: it respawns
    with the file's value, never counts its own increments, and treats
    a file change while its children run as the signal that the pod
    restarted — it restarts its local children at the new epoch without
    consuming restart budget (a coordinated follow-up, not a local
    failure). A worker that races ahead of a restart simply gets
    rejected at handshake, exits nonzero, and its supervisor respawns
    it with the then-current file value — convergent, because the owner
    only moves the epoch forward. Without ``LO_TPU_STORE_ROOT`` in the
    environment (single-host dev), the epoch is a local counter.
    """

    #: Child poll cadence, seconds.
    POLL_S = 0.2
    #: Grace between detecting an incident and killing survivors — lets
    #: process 0's watchdog flush ``pod failure:`` flags to the store so
    #: the restarted incarnation's retry rescan sees the root cause.
    SETTLE_S = 1.0
    #: SIGTERM → SIGKILL escalation grace, seconds.
    TERM_GRACE_S = 5.0

    def __init__(self, commands: Sequence[Sequence[str]], *,
                 cfg: Optional[Settings] = None,
                 env: Optional[Dict[str, str]] = None,
                 health_url: Optional[str] = None,
                 fallback_host: str = "0.0.0.0",
                 fallback_port: Optional[int] = None,
                 initial_epoch: int = 0,
                 epoch_file: Optional[str] = None):
        if not commands:
            raise ValueError("supervisor needs at least one command")
        self.commands = [list(c) for c in commands]
        self.cfg = cfg or global_settings
        self.env = dict(env if env is not None else os.environ)
        self.health_url = health_url
        self.fallback_host = fallback_host
        self.fallback_port = fallback_port
        # Shared-epoch wiring (see class docstring): the file lives on
        # the pod's shared store root; host 0 owns it, others follow it.
        if epoch_file is None and self.env.get("LO_TPU_STORE_ROOT"):
            epoch_file = os.path.join(self.env["LO_TPU_STORE_ROOT"],
                                      ".mesh_epoch")
        self.epoch_file = epoch_file
        self.epoch_owner = self.env.get("LO_TPU_PROCESS_ID", "0") in ("", "0")
        self.epoch = int(initial_epoch)
        if self.epoch_file:
            if self.epoch_owner:
                # Resume monotonically across supervisor restarts: a
                # worker that outlived a full redeploy must still read
                # as stale.
                self.epoch = max(self.epoch, self._read_epoch_file())
                self._write_epoch_file()
            else:
                self.epoch = self._read_epoch_file()
        self.restarts = 0
        self.failure: Optional[str] = None
        self.fallback_server = None
        self._procs: List[subprocess.Popen] = []
        self._stop = threading.Event()
        #: Planned rolling restart requested (SIGHUP / tests): children
        #: are SIGTERMed and given the full graceful-drain window
        #: (LO_TPU_DRAIN_TIMEOUT_S — the server finishes its accepted
        #: requests behind its drain gate) before SIGKILL; consumes no
        #: restart budget and advances the mesh epoch like any restart.
        self._planned = threading.Event()

    # -- shared mesh-epoch file ----------------------------------------------

    def _read_epoch_file(self) -> int:
        try:
            with open(self.epoch_file) as f:
                return int(f.read().strip() or 0)
        except (OSError, ValueError):
            return 0

    def _write_epoch_file(self) -> None:
        try:
            os.makedirs(os.path.dirname(self.epoch_file), exist_ok=True)
            tmp = self.epoch_file + ".tmp"
            with open(tmp, "w") as f:
                f.write(str(self.epoch))
            os.replace(tmp, self.epoch_file)
        except OSError as exc:
            log.error("could not write epoch file %s: %s",
                      self.epoch_file, exc)

    def _advance_epoch(self) -> None:
        """Move to the next incarnation's epoch: the owner increments
        (and publishes); followers adopt whatever the owner last
        published — convergent even when a follower restarts first."""
        if self.epoch_owner:
            self.epoch += 1
            if self.epoch_file:
                self._write_epoch_file()
        else:
            self.epoch = self._read_epoch_file() if self.epoch_file \
                else self.epoch + 1

    # -- process control -----------------------------------------------------

    def _spawn_all(self) -> None:
        env = dict(self.env)
        env["LO_TPU_MESH_EPOCH"] = str(self.epoch)
        env["LO_TPU_RESTART_COUNT"] = str(self.restarts)
        self._procs = [
            subprocess.Popen(argv, env=env) for argv in self.commands]
        log.info("spawned %d pod process(es) at mesh epoch %d",
                 len(self._procs), self.epoch)

    def _kill_all(self, grace_s: Optional[float] = None) -> None:
        """SIGTERM every child, escalate to SIGKILL after ``grace_s``
        (default: the crash-path TERM_GRACE_S). The planned-restart path
        passes the graceful-drain window instead — SIGTERM triggers the
        server's drain (serving/__main__.py), and killing it mid-drain
        would drop exactly the accepted requests the drain exists to
        finish."""
        for p in self._procs:
            if p.poll() is None:
                try:
                    p.terminate()
                except OSError:
                    pass
        deadline = time.time() + (self.TERM_GRACE_S if grace_s is None
                                  else grace_s)
        for p in self._procs:
            while p.poll() is None and time.time() < deadline:
                time.sleep(0.05)
            if p.poll() is None:
                try:
                    p.kill()
                except OSError:
                    pass
                p.wait()

    def request_planned_restart(self) -> None:
        """Ask for a graceful rolling restart (wired to SIGHUP in
        ``main``): drain-then-restart under a fresh mesh epoch, zero
        accepted requests lost, zero restart budget consumed."""
        self._planned.set()

    def request_stop(self) -> None:
        """Stop supervising: kill the children and end ``run()`` (tests,
        controlled shutdown)."""
        self._stop.set()

    def wait_for_stop(self) -> None:
        """Block until ``request_stop`` (signal handlers route here) —
        how ``main()`` keeps the budget-exhausted fallback responder up
        while staying killable by SIGTERM/SIGINT."""
        self._stop.wait()

    # -- health --------------------------------------------------------------

    def _poll_health(self) -> Optional[str]:
        """The pod's degradation reason per ``/cluster``, or None. An
        unreachable endpoint is NOT an incident — the server may still be
        initializing; child exit codes govern liveness."""
        if not self.health_url:
            return None
        try:
            with urllib.request.urlopen(self.health_url, timeout=2.0) as r:
                info = json.loads(r.read().decode("utf-8"))
        except (OSError, ValueError, urllib.error.URLError):
            return None
        err = info.get("pod_error")
        return str(err) if err else None

    # -- the supervision loop ------------------------------------------------

    def run(self) -> int:
        """Supervise until clean exit (0), stop request (0), or restart
        budget exhaustion (1, with the reason served on the fallback
        ``/cluster`` responder)."""
        self._spawn_all()
        next_health = time.time() + self.cfg.health_interval_s
        healthy_since = time.time()
        while not self._stop.is_set():
            if self._planned.is_set():
                self._planned.clear()
                log.info("planned rolling restart at epoch %d: draining "
                         "children (up to %.0fs)", self.epoch,
                         self.cfg.drain_timeout_s)
                # SIGTERM → the server drains (finishes accepted work,
                # rejects new 503) → exits; escalate only past the drain
                # window plus the usual grace. Not an incident: no
                # budget, no backoff — but a fresh epoch, like any
                # restart, so stale workers are turned away.
                self._kill_all(
                    grace_s=self.cfg.drain_timeout_s + self.TERM_GRACE_S)
                self._advance_epoch()
                next_health = time.time() + self.cfg.health_interval_s
                self._spawn_all()
                healthy_since = time.time()
                continue
            codes = [p.poll() for p in self._procs]
            if all(c == 0 for c in codes):
                log.info("all pod processes exited cleanly")
                return 0
            incident = None
            follow = False
            bad = [(i, c) for i, c in enumerate(codes)
                   if c is not None and c != 0]
            if bad:
                incident = "; ".join(
                    f"process {i} exited with code {c}" for i, c in bad)
                if (not self.epoch_owner and self.epoch_file
                        and all(c == RESTARTABLE_EXIT for _, c in bad)):
                    # Exit 3 = controller lost / epoch went stale
                    # (serving/__main__.py): the pod is restarting under
                    # host 0's supervisor. A coordinated follow-up, not a
                    # local failure — no budget; just wait out the new
                    # epoch below.
                    follow = True
            elif time.time() >= next_health:
                next_health = time.time() + self.cfg.health_interval_s
                reason = self._poll_health()
                if reason:
                    incident = f"pod degraded: {reason}"
                elif (not self.epoch_owner and self.epoch_file
                      and self._read_epoch_file() != self.epoch):
                    # The pod restarted under host 0's supervisor: follow
                    # it. A coordinated follow-up, not a local failure —
                    # it consumes no restart budget.
                    incident = (f"pod epoch advanced to "
                                f"{self._read_epoch_file()}")
                    follow = True
            if incident is None:
                # Restart-budget decay: after LO_TPU_RESTART_HEALTHY_S
                # of CONTINUOUS healthy uptime, consumed budget resets —
                # an incident from hours ago must not doom tonight's
                # single blip (exhaustion used to be permanent). A pod
                # flapping faster than the window never reaches here
                # with budget consumed long enough to reset, so repeated
                # failure still exhausts exactly as before.
                if (self.restarts > 0 and self.cfg.restart_healthy_s > 0
                        and time.time() - healthy_since
                        >= self.cfg.restart_healthy_s):
                    log.info(
                        "pod healthy for %.0fs: restart budget restored "
                        "(%d restart(s) forgiven)",
                        self.cfg.restart_healthy_s, self.restarts)
                    self.restarts = 0
                self._stop.wait(self.POLL_S)
                continue
            log.warning("pod incident at epoch %d: %s", self.epoch, incident)
            self._record_incident(incident, codes, follow)
            # Give the watchdog time to flush pollable failure records
            # before the survivors die with it.
            if self._stop.wait(self.SETTLE_S):
                break
            self._kill_all()
            if follow and not self.epoch_owner and self.epoch_file:
                # Respawn only once host 0 has published the next epoch —
                # respawning sooner would just be rejected at handshake
                # and look like a local failure. (If host 0's supervisor
                # exhausted its budget the pod is dead; we idle here,
                # still killable via request_stop/SIGTERM.)
                while (not self._stop.is_set()
                       and self._read_epoch_file() == self.epoch):
                    self._stop.wait(self.POLL_S)
                if self._stop.is_set():
                    break
            if not follow:
                self.restarts += 1
                if self.restarts > self.cfg.restart_budget:
                    self.failure = (
                        f"restart budget exhausted "
                        f"({self.cfg.restart_budget} restart(s)); "
                        f"last incident: {incident}")
                    log.error("%s", self.failure)
                    self._serve_fallback()
                    return 1
                backoff = min(
                    self.cfg.restart_backoff_max_s,
                    self.cfg.restart_backoff_s * (2 ** (self.restarts - 1)))
                log.info("restarting pod in %.1fs (restart %d/%d)",
                         backoff, self.restarts, self.cfg.restart_budget)
                if self._stop.wait(backoff):
                    break
            self._advance_epoch()
            next_health = time.time() + self.cfg.health_interval_s
            self._spawn_all()
            healthy_since = time.time()
        self._kill_all()
        return 0

    def _record_incident(self, incident: str, codes: List[Optional[int]],
                         follow: bool) -> None:
        """Drop a manifest-only flight-recorder bundle on the shared
        store root: the children about to be killed can no longer dump
        their own, and the supervisor is the only witness to exit
        codes. A coordinated epoch follow-up is not an incident worth a
        bundle. Best-effort — recording must never delay the restart."""
        if follow:
            return
        store_root = self.env.get("LO_TPU_STORE_ROOT") or \
            self.cfg.store_root
        from learningorchestra_tpu.utils import flightrec

        flightrec.dump_minimal(
            store_root, "supervisor:incident",
            detail={"incident": incident,
                    "exit_codes": codes,
                    "mesh_epoch": self.epoch,
                    "restarts": self.restarts,
                    "restart_budget": self.cfg.restart_budget},
            keep=self.cfg.flightrec_keep)

    # -- budget-exhausted fallback -------------------------------------------

    def _serve_fallback(self) -> None:
        """Serve a minimal ``/cluster`` on the pod's port reporting the
        terminal failure — the pod stays *observably* failed instead of
        going connection-refused dark."""
        if self.fallback_port is None:
            return
        from learningorchestra_tpu.serving.http import HttpError, Router, \
            Server

        sup = self

        router = Router()

        @router.route("GET", "/cluster")
        def cluster(_req) -> Any:
            return 200, {
                "supervisor": "failed",
                "pod_error": sup.failure,
                "restarts": sup.restarts,
                "restart_budget": sup.cfg.restart_budget,
                "mesh_epoch": sup.epoch,
                "healthy": False,
            }

        @router.route("GET", "/status")
        def status(_req) -> Any:
            raise HttpError(503, sup.failure or "pod failed",
                            headers={"Retry-After": "60"})

        try:
            self.fallback_server = Server(
                router, self.fallback_host, self.fallback_port)
            self.fallback_server.start_background()
            log.info("fallback /cluster responder on %s:%d",
                     self.fallback_host, self.fallback_port)
        except OSError as exc:
            log.error("could not start fallback responder: %s", exc)

    def close(self) -> None:
        self.request_stop()
        self._kill_all()
        if self.fallback_server is not None:
            self.fallback_server.stop()
            self.fallback_server = None


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        description="learningorchestra_tpu pod supervisor",
        epilog="Everything after '--' is the pod command to supervise; "
               "defaults to 'python -m learningorchestra_tpu.serving'.")
    parser.add_argument("--health-url", default=None,
                        help="process 0's /cluster URL to poll (host 0 only)")
    parser.add_argument("--fallback-port", type=int, default=None,
                        help="serve the budget-exhausted failure report "
                             "on this port")
    args, rest = parser.parse_known_args(argv)
    if rest and rest[0] == "--":
        rest = rest[1:]
    command = rest or [sys.executable, "-m", "learningorchestra_tpu.serving"]

    sup = Supervisor([command], health_url=args.health_url,
                     fallback_port=args.fallback_port)
    signal.signal(signal.SIGTERM, lambda *_: sup.request_stop())
    signal.signal(signal.SIGINT, lambda *_: sup.request_stop())
    # SIGHUP = planned rolling restart: children drain gracefully (zero
    # accepted requests lost), then respawn under the next mesh epoch.
    signal.signal(signal.SIGHUP, lambda *_: sup.request_planned_restart())
    rc = sup.run()
    if rc != 0 and sup.fallback_server is not None:
        # Stay up serving the failure report until SIGTERM/SIGINT (the
        # handlers above set the stop event this waits on).
        sup.wait_for_stop()
        sup.close()
    return rc


if __name__ == "__main__":
    structlog.configure()
    sys.exit(main())
