"""Classifier registry — the reference's switcher, extended.

The reference maps ``{"lr", "dt", "rf", "gb", "nb"}`` to pyspark.ml
classifiers (reference model_builder.py:152-158) and returns 409 for unknown
names (ModelBuilderRequestValidator, model_builder.py:284-292). Same five
names here, plus the TPU-native "mlp" extension.
"""

from __future__ import annotations

from typing import Callable, Dict

from learningorchestra_tpu.models import logistic, mlp, naive_bayes, trees

CLASSIFIERS: Dict[str, Callable] = {
    "lr": logistic.fit,
    "dt": trees.fit_dt,
    "rf": trees.fit_rf,
    "gb": trees.fit_gb,
    "nb": naive_bayes.fit,
    "mlp": mlp.fit,
}


def get_trainer(name: str) -> Callable:
    try:
        return CLASSIFIERS[name]
    except KeyError:
        raise ValueError(
            f"invalid classifier {name!r}; choose from "
            f"{sorted(CLASSIFIERS)}") from None
