"""Classifier registry — the reference's switcher, extended.

The reference maps ``{"lr", "dt", "rf", "gb", "nb"}`` to pyspark.ml
classifiers (reference model_builder.py:152-158) and returns 409 for unknown
names (ModelBuilderRequestValidator, model_builder.py:284-292). Same five
names here, plus the TPU-native extensions: "mlp" (dp×tp perceptron) and
"tx" (the dp×tp×sp transformer with ring attention, models/sequence.py).
"""

from __future__ import annotations

from typing import Callable, Dict

from learningorchestra_tpu.models import (
    logistic, mlp, naive_bayes, sequence, trees)

CLASSIFIERS: Dict[str, Callable] = {
    "lr": logistic.fit,
    "dt": trees.fit_dt,
    "rf": trees.fit_rf,
    "gb": trees.fit_gb,
    "nb": naive_bayes.fit,
    "mlp": mlp.fit,
    "tx": sequence.fit,
}

#: Families the ONLINE predict tier (models/aot.py, serving/batcher.py)
#: serves: every continuous-feature family. "tx" is excluded — it
#: consumes token sequences, so inline JSON feature rows are
#: out-of-domain for it (its serving story is the batch predictions
#: route).
ONLINE_KINDS = ("lr", "nb", "dt", "rf", "gb", "mlp")


def get_trainer(name: str) -> Callable:
    try:
        return CLASSIFIERS[name]
    except KeyError:
        raise ValueError(
            f"invalid classifier {name!r}; choose from "
            f"{sorted(CLASSIFIERS)}") from None


def predictor_for(kind: str, hparams: Dict) -> Callable:
    """Rebuild the (params, X) -> probs function for a persisted model.

    Every family's predictor is a module function parameterized only by
    static hparams, so a checkpoint of (kind, hparams, params) fully
    reconstructs a servable model (models/persistence.py)."""
    from functools import partial

    from learningorchestra_tpu.models import trees

    if kind in ("dt", "rf"):
        return partial(trees._forest_proba_static,
                       max_depth=int(hparams["max_depth"]))
    if kind == "gb":
        # ovr_classes marks a one-vs-rest multiclass booster stack
        # (leading class axis on the tree params); absent = the binary
        # reference-parity model.
        fn = (trees._gbt_ovr_proba_static if hparams.get("ovr_classes")
              else trees._gbt_proba_static)
        return partial(fn, max_depth=int(hparams["max_depth"]))
    if kind == "lr":
        return logistic._predict_proba
    if kind == "nb":
        return (naive_bayes._predict_multinomial
                if hparams.get("event_model") == "multinomial"
                else naive_bayes._predict_proba)
    if kind == "mlp":
        return mlp._predict_proba
    if kind == "tx":
        return sequence.predictor(hparams)
    raise ValueError(f"no predictor for classifier kind {kind!r}")
