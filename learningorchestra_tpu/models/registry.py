"""Classifier registry — the reference's switcher, extended.

The reference maps ``{"lr", "dt", "rf", "gb", "nb"}`` to pyspark.ml
classifiers (reference model_builder.py:152-158) and returns 409 for unknown
names (ModelBuilderRequestValidator, model_builder.py:284-292). Same five
names here, plus the TPU-native extensions: "mlp" (dp×tp perceptron) and
"tx" (the dp×tp×sp transformer with ring attention, models/sequence.py).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

from learningorchestra_tpu.models import (
    logistic, mlp, naive_bayes, sequence, trees)

CLASSIFIERS: Dict[str, Callable] = {
    "lr": logistic.fit,
    "dt": trees.fit_dt,
    "rf": trees.fit_rf,
    "gb": trees.fit_gb,
    "nb": naive_bayes.fit,
    "mlp": mlp.fit,
    "tx": sequence.fit,
}

#: Families the ONLINE predict tier (models/aot.py, serving/batcher.py)
#: serves: every continuous-feature family. "tx" is excluded — it
#: consumes token sequences, so inline JSON feature rows are
#: out-of-domain for it (its serving story is the batch predictions
#: route).
ONLINE_KINDS = ("lr", "nb", "dt", "rf", "gb", "mlp")


def _int_range(lo: int, hi: int) -> Tuple[Callable, str]:
    return (lambda v: isinstance(v, int) and not isinstance(v, bool)
            and lo <= v <= hi, f"an integer in [{lo}, {hi}]")


def _positive() -> Tuple[Callable, str]:
    return (lambda v: isinstance(v, (int, float))
            and not isinstance(v, bool) and v > 0, "a number > 0")


def _nonneg() -> Tuple[Callable, str]:
    return (lambda v: isinstance(v, (int, float))
            and not isinstance(v, bool) and v >= 0, "a number >= 0")


def _choice(*opts: str) -> Tuple[Callable, str]:
    return (lambda v: v in opts, f"one of {sorted(opts)}")


def _boolean() -> Tuple[Callable, str]:
    return (lambda v: isinstance(v, bool), "a boolean")


#: Per-family user-settable hyperparameters with their legal ranges —
#: the single validation table behind the 406s on ``POST /models`` and
#: ``POST /tune``. Keys the builder injects itself (``edges``, ``ckpt``)
#: are deliberately absent: a request naming them is rejected as
#: unknown instead of silently colliding with the injected values. The
#: tree-depth/bin caps mirror the builders' structural limits (uint8
#: bin codes; 2^(depth+1)-1 node arrays).
_SEED = _int_range(0, 2 ** 31 - 1)
HPARAM_SPECS: Dict[str, Dict[str, Tuple[Callable, str]]] = {
    "lr": {"seed": _SEED, "iters": _int_range(1, 1_000_000),
           "lr": _positive(), "l2": _nonneg(),
           "solver": _choice("auto", "newton", "adam")},
    "dt": {"seed": _SEED, "max_depth": _int_range(1, 12),
           "n_bins": _int_range(2, 256)},
    "rf": {"seed": _SEED, "max_depth": _int_range(1, 12),
           "n_bins": _int_range(2, 256), "n_trees": _int_range(1, 1024),
           "mtry": _int_range(1, 65536)},
    "gb": {"seed": _SEED, "max_depth": _int_range(1, 12),
           "n_bins": _int_range(2, 256), "n_rounds": _int_range(1, 4096),
           "step_size": _positive()},
    "nb": {"seed": _SEED, "smoothing": _positive(),
           "event_model": _choice("gaussian", "multinomial")},
    "mlp": {"seed": _SEED, "hidden": _int_range(1, 65536),
            "iters": _int_range(1, 1_000_000), "lr": _positive(),
            "l2": _nonneg()},
    "tx": {"seed": _SEED, "d_model": _int_range(8, 4096),
           "n_heads": _int_range(1, 64), "n_layers": _int_range(1, 64),
           "d_ff": _int_range(8, 16384), "vocab": _int_range(0, 2 ** 22),
           "train_steps": _int_range(1, 1_000_000),
           "batch": _int_range(1, 1 << 22), "lr": _positive(),
           "causal": _boolean(), "remat": _boolean()},
}


def validate_hparams(classifier: str, hparams: Any) -> None:
    """Reject unknown hyperparameter names and out-of-range values with a
    ValueError NAMING the offending key (the serving tier maps it to a
    406) — instead of the TypeError-500 a bad ``**kwargs`` splat would
    raise from deep inside a trainer."""
    get_trainer(classifier)  # unknown classifier: its own ValueError
    if hparams in (None, {}):
        return
    if not isinstance(hparams, dict):
        raise ValueError(
            f"hparams for classifier {classifier!r} must be an object of "
            f"name->value, got {type(hparams).__name__}")
    spec = HPARAM_SPECS[classifier]
    for key, value in hparams.items():
        if key not in spec:
            raise ValueError(
                f"unknown hparam {key!r} for classifier {classifier!r}; "
                f"known: {sorted(spec)}")
        check, expect = spec[key]
        if not check(value):
            raise ValueError(
                f"hparam {key!r} for classifier {classifier!r} is out of "
                f"range: expected {expect}, got {value!r}")


def get_trainer(name: str) -> Callable:
    try:
        return CLASSIFIERS[name]
    except KeyError:
        raise ValueError(
            f"invalid classifier {name!r}; choose from "
            f"{sorted(CLASSIFIERS)}") from None


def predictor_for(kind: str, hparams: Dict) -> Callable:
    """Rebuild the (params, X) -> probs function for a persisted model.

    Every family's predictor is a module function parameterized only by
    static hparams, so a checkpoint of (kind, hparams, params) fully
    reconstructs a servable model (models/persistence.py)."""
    from functools import partial

    from learningorchestra_tpu.models import trees

    if kind in ("dt", "rf"):
        return partial(trees._forest_proba_static,
                       max_depth=int(hparams["max_depth"]))
    if kind == "gb":
        # ovr_classes marks a one-vs-rest multiclass booster stack
        # (leading class axis on the tree params); absent = the binary
        # reference-parity model.
        fn = (trees._gbt_ovr_proba_static if hparams.get("ovr_classes")
              else trees._gbt_proba_static)
        return partial(fn, max_depth=int(hparams["max_depth"]))
    if kind == "lr":
        return logistic._predict_proba
    if kind == "nb":
        return (naive_bayes._predict_multinomial
                if hparams.get("event_model") == "multinomial"
                else naive_bayes._predict_proba)
    if kind == "mlp":
        return mlp._predict_proba
    if kind == "tx":
        return sequence.predictor(hparams)
    raise ValueError(f"no predictor for classifier kind {kind!r}")
