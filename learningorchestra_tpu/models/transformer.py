"""Sequence transformer — the long-context model family (dp × tp × sp).

No reference behavior exists to match (the reference predates sequence
models, SURVEY.md §5); this family exists because long-context and
distributed execution are first-class in the rebuild. The training step is
one SPMD program over the full 3-axis mesh (parallel/mesh.py):

- ``data``  — batch rows sharded (the reference's only parallelism axis);
- ``model`` — Megatron-style tensor parallelism: attention heads and the
  FFN hidden dimension are column-split, output projections row-split with
  one ``psum`` per block over ICI;
- ``seq``   — context parallelism: sequence length is sharded and exact
  attention runs as a ring of ``ppermute`` hops
  (parallel/ring_attention.py), so max context scales linearly with the
  seq-axis size.

Differentiation goes *through* ``shard_map`` (check_vma replication
tracking makes the psum/ppermute transposes produce correctly-reduced
gradients for replicated and sharded parameters alike), so the optimizer
update is ordinary optax on sharded pytrees.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from learningorchestra_tpu.parallel.mesh import (
    DATA_AXIS, MODEL_AXIS, SEQ_AXIS)
from learningorchestra_tpu.parallel.ring_attention import ring_attention


@dataclass(frozen=True)
class TxConfig:
    vocab: int = 256
    d_model: int = 128
    n_heads: int = 8
    n_layers: int = 2
    d_ff: int = 256
    n_classes: int = 2
    max_len: int = 1024
    causal: bool = False          # classifier default; True for LM-style
    #: Rematerialize each layer's activations in the backward pass
    #: (jax.checkpoint) — trades ~30% step time for O(1)-in-depth live
    #: activation memory, the standard long-context lever (32k tokens on
    #: one 16 GB chip needs it).
    remat: bool = False


def init_params(key, cfg: TxConfig) -> Dict[str, Any]:
    hd = cfg.d_model // cfg.n_heads
    keys = iter(jax.random.split(key, 4 + 6 * cfg.n_layers))

    def dense(k, *shape, scale=None):
        scale = scale or 1.0 / np.sqrt(shape[0])
        return (jax.random.normal(k, shape, jnp.float32) * scale)

    params: Dict[str, Any] = {
        "embed": dense(next(keys), cfg.vocab, cfg.d_model, scale=0.02),
        "pos": dense(next(keys), cfg.max_len, cfg.d_model, scale=0.02),
        "head_w": dense(next(keys), cfg.d_model, cfg.n_classes),
        "head_b": jnp.zeros(cfg.n_classes),
        "layers": [],
    }
    for _ in range(cfg.n_layers):
        params["layers"].append({
            "ln1_g": jnp.ones(cfg.d_model), "ln1_b": jnp.zeros(cfg.d_model),
            "wqkv": dense(next(keys), cfg.d_model, 3, cfg.n_heads, hd),
            "wo": dense(next(keys), cfg.n_heads, hd, cfg.d_model,
                        scale=1.0 / np.sqrt(cfg.d_model)),
            "ln2_g": jnp.ones(cfg.d_model), "ln2_b": jnp.zeros(cfg.d_model),
            "w1": dense(next(keys), cfg.d_model, cfg.d_ff),
            "b1": jnp.zeros(cfg.d_ff),
            "w2": dense(next(keys), cfg.d_ff, cfg.d_model),
            "b2": jnp.zeros(cfg.d_model),
        })
    return params


def param_specs(cfg: TxConfig) -> Dict[str, Any]:
    """PartitionSpec per leaf: heads / FFN hidden on the model axis, the
    rest replicated (small embeddings; sharding them buys nothing here)."""
    layer = {
        "ln1_g": P(), "ln1_b": P(),
        "wqkv": P(None, None, MODEL_AXIS, None),
        "wo": P(MODEL_AXIS, None, None),
        "ln2_g": P(), "ln2_b": P(),
        "w1": P(None, MODEL_AXIS), "b1": P(MODEL_AXIS),
        "w2": P(MODEL_AXIS, None), "b2": P(),
    }
    return {"embed": P(), "pos": P(), "head_w": P(), "head_b": P(),
            "layers": [dict(layer) for _ in range(cfg.n_layers)]}


def _ln(x, g, b, eps=1e-5):
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + eps) * g + b


def forward_shard(params, tokens, *, cfg: TxConfig):
    """Per-shard forward (runs inside shard_map over the 3-axis mesh).

    tokens: (B_local, T_local) int32 → logits (B_local, n_classes),
    replicated over model and seq axes.
    """
    seq_idx = jax.lax.axis_index(SEQ_AXIS)
    seq_size = jax.lax.psum(1, SEQ_AXIS)
    Tl = tokens.shape[1]
    if Tl * seq_size > cfg.max_len:
        # Caught at trace time (both values static): an out-of-range
        # position gather would silently clamp to the last row under jit.
        raise ValueError(
            f"sequence length {Tl * seq_size} exceeds max_len "
            f"{cfg.max_len}")
    pos = seq_idx * Tl + jnp.arange(Tl)
    x = params["embed"][tokens] + params["pos"][pos][None, :, :]

    def layer_fn(x, lyr):
        # --- attention: heads column-split (tp), ring over seq (sp) -------
        h = _ln(x, lyr["ln1_g"], lyr["ln1_b"])
        qkv = jnp.einsum("btd,dkhe->btkhe", h, lyr["wqkv"])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        attn = ring_attention(q, k, v, axis_name=SEQ_AXIS,
                              causal=cfg.causal)
        out = jnp.einsum("bthe,hed->btd", attn, lyr["wo"])
        x = x + jax.lax.psum(out, MODEL_AXIS)      # row-parallel reduce
        # --- FFN: hidden dim column-split (tp) ----------------------------
        h = _ln(x, lyr["ln2_g"], lyr["ln2_b"])
        ff = jax.nn.gelu(h @ lyr["w1"] + lyr["b1"])
        return x + jax.lax.psum(ff @ lyr["w2"], MODEL_AXIS) + lyr["b2"]

    if cfg.remat:
        layer_fn = jax.checkpoint(layer_fn)
    for lyr in params["layers"]:
        x = layer_fn(x, lyr)

    # Mean-pool over the (sharded) sequence, then classify.
    pool = jax.lax.psum(x.sum(axis=1), SEQ_AXIS) / (Tl * seq_size)
    return pool @ params["head_w"] + params["head_b"]


def make_loss_fn(cfg: TxConfig, mesh: Mesh):
    specs = param_specs(cfg)

    def shard_fn(params, tokens, labels):
        logits = forward_shard(params, tokens, cfg=cfg)
        logp = jax.nn.log_softmax(logits)
        local = -jnp.take_along_axis(logp, labels[:, None], axis=1).sum()
        n = jax.lax.psum(jnp.float32(labels.shape[0]), DATA_AXIS)
        return jax.lax.psum(local, DATA_AXIS) / n

    def loss_fn(params, tokens, labels):
        return jax.shard_map(
            shard_fn, mesh=mesh,
            in_specs=(specs, P(DATA_AXIS, SEQ_AXIS), P(DATA_AXIS)),
            out_specs=P())(params, tokens, labels)

    return loss_fn


def make_train_step(cfg: TxConfig, mesh: Mesh, opt: optax.GradientTransformation):
    loss_fn = make_loss_fn(cfg, mesh)

    @jax.jit
    def train_step(params, opt_state, tokens, labels):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens, labels)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    return train_step


def shard_params(params, cfg: TxConfig, mesh: Mesh):
    """Place a host/param pytree on the mesh per param_specs."""
    specs = param_specs(cfg)
    return jax.tree.map(
        lambda v, s: jax.device_put(v, NamedSharding(mesh, s)),
        params, specs, is_leaf=lambda x: isinstance(x, P))


# --- single-device numerics oracle (tests) --------------------------------

@partial(jax.jit, static_argnames=("cfg",))
def forward_reference(params, tokens, *, cfg: TxConfig):
    """Unsharded forward: same math, no mesh — must match forward_shard."""
    from learningorchestra_tpu.parallel.ring_attention import (
        reference_attention)

    Tl = tokens.shape[1]
    if Tl > cfg.max_len:
        raise ValueError(f"sequence length {Tl} exceeds max_len "
                         f"{cfg.max_len}")
    x = params["embed"][tokens] + params["pos"][jnp.arange(Tl)][None]
    for lyr in params["layers"]:
        h = _ln(x, lyr["ln1_g"], lyr["ln1_b"])
        qkv = jnp.einsum("btd,dkhe->btkhe", h, lyr["wqkv"])
        attn = reference_attention(qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2],
                                   causal=cfg.causal)
        x = x + jnp.einsum("bthe,hed->btd", attn, lyr["wo"])
        h = _ln(x, lyr["ln2_g"], lyr["ln2_b"])
        x = x + jax.nn.gelu(h @ lyr["w1"] + lyr["b1"]) @ lyr["w2"] + lyr["b2"]
    pool = x.mean(axis=1)
    return pool @ params["head_w"] + params["head_b"]
