"""Trainer interface shared by all classifier families.

The reference's model zoo is the pyspark.ml switcher
``{lr, dt, rf, gb, nb}`` (reference model_builder.py:152-158): each entry
fits on a Spark DataFrame of assembled feature vectors and transforms the
test set into prediction + probability columns. Here a trainer is a function
``fit(runtime, X, y, num_classes, seed, **hparams) -> TrainedModel`` over
dense device arrays; every fit shards rows across the mesh data axis and
returns replicated parameters, so predict runs on any subset of devices.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict

import numpy as np

from learningorchestra_tpu.parallel.mesh import MeshRuntime, host_rows


def as_design(X):
    """Normalize a trainer's X input: lazy designs (ChunkedDesign
    protocol, recognized by ``.rows``) pass through untouched — calling
    ``np.asarray`` on one would materialize the full matrix and defeat
    shard-local loading; anything else becomes a float32 ndarray."""
    if hasattr(X, "rows") and not isinstance(X, np.ndarray):
        return X
    return np.asarray(X, np.float32)


@dataclass
class TrainedModel:
    """A fitted classifier: replicated params + a jit'd probability fn."""

    kind: str
    params: Any                       # pytree of replicated jax arrays
    predict_proba_fn: Callable        # (params, X_dev) -> (n, C) probs
    num_classes: int
    hparams: Dict[str, Any] = field(default_factory=dict)

    #: Rows per device predict call — bounds transient device memory on
    #: huge test sets (an (n, C)-shaped probability tensor lane-pads its
    #: trailing dim to 128 on TPU, so n must stay bounded).
    PREDICT_CHUNK = 2_000_000

    def predict_proba(self, runtime: MeshRuntime, X: np.ndarray) -> np.ndarray:
        X = as_design(X)
        if len(X) <= self.PREDICT_CHUNK:
            X_dev, n = runtime.shard_rows(X)
            return host_rows(self.predict_proba_fn(self.params, X_dev))[:n]
        outs = []
        for i in range(0, len(X), self.PREDICT_CHUNK):
            chunk = (X.rows(i, i + self.PREDICT_CHUNK)
                     if hasattr(X, "rows")
                     else np.ascontiguousarray(X[i:i + self.PREDICT_CHUNK]))
            X_dev, n = runtime.shard_rows(chunk)
            outs.append(
                host_rows(self.predict_proba_fn(self.params, X_dev))[:n])
        return np.concatenate(outs, axis=0)

    def predict(self, runtime: MeshRuntime, X: np.ndarray) -> np.ndarray:
        return np.argmax(self.predict_proba(runtime, X), axis=1)


@dataclass
class FitReport:
    """What the reference persists per classifier: the model's metrics +
    wall-clock fit time (model_builder.py:199-225)."""

    kind: str
    fit_time: float
    metrics: Dict[str, float] = field(default_factory=dict)


class Timer:
    def __enter__(self):
        self.t0 = time.time()
        return self

    def __exit__(self, *exc):
        self.elapsed = time.time() - self.t0
