"""Tree-ensemble trainers: "dt", "rf", "gb" — histogram-split trees in XLA.

The reference fits ``pyspark.ml`` DecisionTreeClassifier,
RandomForestClassifier and GBTClassifier as distributed Spark jobs
(reference model_builder.py:153-155). Spark's tree algorithm is itself
histogram-based (maxBins feature quantization + per-node sufficient
statistics aggregated across executors) — which is exactly the shape that
maps onto a TPU, so this module re-designs it as a fixed-shape XLA program
(SURVEY.md §7 "hard part (a)"):

- Features are quantized once to ``n_bins`` quantile bins (Spark's maxBins).
- A tree is grown *level-wise*: every node at a level computes a
  (node, feature, bin, stat) histogram with one scatter-add over the rows,
  split quality for every candidate comes from a cumulative sum over bins,
  and the best split is an argmax — no data-dependent control flow, so the
  whole build jit-compiles with static shapes.
- Rows stay sharded across the mesh data axis for the entire build inside a
  single ``shard_map``: each shard scatter-adds its local rows, one
  ``lax.psum`` per level reduces histograms over ICI (the analogue of
  Spark's per-level executor aggregation), and node decisions are computed
  identically on every shard.
- One generic builder serves all three families: classification trees carry
  per-class weight stats (gini criterion); boosted trees carry
  gradient/hessian stats (Newton gain, XGBoost-hist style).

Defaults match Spark 2.4's: maxDepth=5, maxBins=32, numTrees=20 (rf),
maxIter=20 + stepSize=0.1 (gb), and "gb" is binary-only exactly as Spark's
GBTClassifier is.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from learningorchestra_tpu.models.base import TrainedModel, as_design
from learningorchestra_tpu.ops import pallas_kernels
from learningorchestra_tpu.parallel import spmd
from learningorchestra_tpu.parallel.mesh import DATA_AXIS, MeshRuntime

NEG = -1e30


def _use_tree_kernel(runtime: Optional[MeshRuntime] = None) -> bool:
    """Whether tree fits route their hot loops through the fused Pallas
    kernels (ops/pallas_kernels.py). ``LO_TPU_TREE_KERNEL=0`` selects
    the pure-XLA contraction path — kept as the bit-parity oracle
    (docs/performance.md); the master ``LO_TPU_USE_PALLAS`` switch
    disables every Pallas kernel at once. Off-TPU the kernels run in
    interpreter mode, so the default exercises the same code path on
    the CPU mesh."""
    if runtime is not None:
        cfg = runtime.cfg
    else:
        from learningorchestra_tpu.config import settings as cfg
    return bool(cfg.use_pallas and cfg.tree_kernel
                and pallas_kernels.tree_kernels_supported())


def _hist_dtype():
    """Histogram matmul operand dtype: bf16 on TPU (halves the dominant
    one-hot HBM traffic; MXU accumulates in f32 via
    preferred_element_type), f32 elsewhere (the CPU dot thunk lacks
    BF16×BF16→F32)."""
    return jnp.bfloat16 if jax.default_backend() == "tpu" else jnp.float32


def _sel_col(Bblk: jax.Array, f_idx: jax.Array) -> jax.Array:
    """Per-row feature select ``B[i, f_i]`` as a dense compare+sum.

    ``take_along_axis`` lowers to a per-row gather, which serializes on
    TPU — profiled at 2.7 ms per 262k-row block, it dominated tree fits
    (~17 s of a 27 s gb fit across routing+descent). The (blk, d) one-hot
    masked sum is a single fused VPU pass."""
    d = Bblk.shape[1]
    oh = f_idx[:, None] == jnp.arange(d, dtype=f_idx.dtype)[None, :]
    return jnp.where(oh, Bblk.astype(jnp.int32), 0).sum(axis=1)


def _sel_table(table: jax.Array, idx: jax.Array) -> jax.Array:
    """Per-row small-table lookup ``table[idx]`` as a dense compare+sum
    (same gather-avoidance rationale as ``_sel_col``; tables here are the
    ≤2^(depth+1) per-node arrays)."""
    M = table.shape[0]
    oh = idx[:, None] == jnp.arange(M, dtype=idx.dtype)[None, :]
    t = table.astype(jnp.int32) if table.dtype == jnp.bool_ else table
    out = jnp.where(oh, t[None, :], 0).sum(axis=1)
    return out.astype(jnp.bool_) if table.dtype == jnp.bool_ else out


def _sel_table_blocked(table: jax.Array, idx: jax.Array) -> jax.Array:
    """Blocked ``table[idx]`` over a full row-length index array (e.g. the
    per-round leaf-value broadcast in boosting): the (n, M) one-hot
    transient stays one block wide instead of gigabytes."""
    n = idx.shape[0]
    blk, nbk, n_pad = _block_shape(n)
    if n_pad != n:
        idx = jnp.pad(idx, (0, n_pad - n))

    def body(acc, i):
        ib = jax.lax.dynamic_slice_in_dim(idx, i * blk, blk)
        return jax.lax.dynamic_update_slice_in_dim(
            acc, _sel_table(table, ib), i * blk, axis=0), None

    out, _ = jax.lax.scan(
        body, jnp.zeros((n_pad,), table.dtype), jnp.arange(nbk))
    return out[:n]


def _sel_rows_blocked(table: jax.Array, idx: jax.Array) -> jax.Array:
    """Blocked ``table[idx]`` for a 2-D (M, S) table: per block, a
    (blk, M) one-hot @ (M, S) dot — exact in f32, transients stay one
    block wide (an unblocked one-hot for a 2M-row predict chunk × 20
    vmapped trees would be gigabytes of lane-padded HBM)."""
    n = idx.shape[0]
    M, S = table.shape
    blk, nbk, n_pad = _block_shape(n)
    if n_pad != n:
        idx = jnp.pad(idx, (0, n_pad - n))

    def body(acc, i):
        ib = jax.lax.dynamic_slice_in_dim(idx, i * blk, blk)
        oh = (ib[:, None] == jnp.arange(M, dtype=ib.dtype)[None, :]
              ).astype(table.dtype)
        return jax.lax.dynamic_update_slice_in_dim(
            acc, oh @ table, i * blk, axis=0), None

    out, _ = jax.lax.scan(
        body, jnp.zeros((n_pad, S), table.dtype), jnp.arange(nbk))
    return out[:n]


# ---------------------------------------------------------------------------
# Quantization (Spark's maxBins analogue)
# ---------------------------------------------------------------------------

def quantile_edges(X: np.ndarray, n_bins: int,
                   sample: int = 200_000) -> np.ndarray:
    """Per-feature bin edges from quantiles of a row sample. (d, n_bins-1)."""
    n = len(X)
    if n > sample:
        idx = np.random.default_rng(0).choice(n, sample, replace=False)
        Xs = X[idx]
    else:
        Xs = X
    qs = np.linspace(0, 1, n_bins + 1)[1:-1]
    edges = np.quantile(Xs, qs, axis=0).T.astype(np.float32)  # (d, n_bins-1)
    return np.ascontiguousarray(edges)


def validate_n_bins(n_bins: int) -> None:
    """Single guard for the uint8 bin-code representation ``bin_features``
    produces — every tree entry point (edge prep, dt/rf, gb) funnels
    through it."""
    if n_bins > 256:
        raise ValueError("n_bins is capped at 256 (uint8 bin codes)")


@jax.jit
def bin_features(X: jax.Array, edges: jax.Array) -> jax.Array:
    """float features → uint8 bin codes: code = #edges strictly below x.

    One fused compare+sum over the (n, d, n_bins-1) broadcast instead of
    per-feature ``searchsorted`` (which lowers to gather-heavy binary
    search on TPU); the compare form is a single VPU pass, XLA fuses the
    broadcast away, and — crucially — it has no cross-row op, so a
    row-sharded ``X`` yields a row-sharded result with no resharding.

    uint8 keeps the resident bin matrix 4× smaller than int32 (and TPU
    lane padding makes (n, d<128) arrays pay for 128 lanes regardless, so
    narrow dtypes are the only lever); n_bins is capped at 256.
    """
    return (X[:, :, None] > edges[None, :, :]).sum(
        axis=-1, dtype=jnp.int32).astype(jnp.uint8)  # (n, d)


# ---------------------------------------------------------------------------
# Generic level-wise histogram tree builder (runs inside shard_map)
# ---------------------------------------------------------------------------

#: Rows per histogram/routing block. Level-wise stats accumulate in a
#: lax.scan over row blocks so nothing (n, d, S)-shaped ever materializes —
#: at HIGGS scale (11M × 28) that tensor would be gigabytes *before* TPU
#: lane padding inflates trailing small dims to 128 lanes (a (n·d, 2) f32
#: scatter operand allocates 64× its logical size).
_ROW_BLOCK = 1 << 18
#: f32 elements allowed for the per-block (blk, d·n_bins) one-hot operand of
#: the histogram contraction (~128 MB) — bounds transient HBM per block.
_ONEHOT_BUDGET = 32 * 1024 * 1024


def _block_shape(n, onehot_cols=0):
    blk = _ROW_BLOCK
    if onehot_cols:
        cap = max(512, _ONEHOT_BUDGET // onehot_cols)
        blk = min(blk, 1 << (cap.bit_length() - 1))
    blk = min(blk, n)
    nbk = -(-n // blk)
    return blk, nbk, nbk * blk


def _hist_level_xla(B, stats_T, rel, active, *, n_nodes, n_bins, blk):
    """One level's local (node, feature, bin, stat) histogram via the
    blocked MXU-contraction emulation — the ``LO_TPU_TREE_KERNEL=0``
    oracle path.

    The histogram is ONE MXU contraction per block — not scatters (TPU
    scatter-adds serialize) and not a per-feature matmul loop (n_bins=32
    lane-pads to 128, NL·S is sublane-starved, and the d-way unroll
    bloats compile time). The (feature, bin) one-hot packs into a single
    (blk, d·n_bins) operand so every feature rides the same matmul: A
    packs node-masked per-row stats (NL·S, blk); one
    (NL·S, blk) @ (blk, d·n_bins) product per block. Blocks are carved
    with dynamic_slice inside the scan body (index scan) rather than
    scanning over a stacked (nbk, blk, ...) operand: XLA:TPU compiles
    scans over multi-hundred-MB stacked inputs ~30x slower (measured
    23.5s vs 0.8s for a trivial body at 11 x 1M rows). The one-hot
    operands materialize in HBM per block — the traffic the Pallas
    kernel path exists to eliminate.
    """
    n_pad, d = B.shape
    S = stats_T.shape[0]
    nbk = n_pad // blk
    bins_u8 = jnp.arange(n_bins, dtype=jnp.uint8)[None, None, :]

    def hist_block(hist, i):
        Bblk = jax.lax.dynamic_slice_in_dim(B, i * blk, blk)
        relblk = jax.lax.dynamic_slice_in_dim(rel, i * blk, blk)
        ablk = jax.lax.dynamic_slice_in_dim(active, i * blk, blk)
        sblk = jax.lax.dynamic_slice_in_dim(
            stats_T, i * blk, blk, axis=1)               # (S, blk)
        node_oh = ((relblk[:, None] == jnp.arange(n_nodes)[None, :])
                   & ablk[:, None])                      # (blk, NL)
        # bf16 operands (on TPU) halve the dominant HBM traffic (the
        # (blk, d·n_bins) one-hot materialization); products of {0,1}
        # one-hots with bf16-rounded stats are exact, and partial
        # sums accumulate in f32 via preferred_element_type.
        hdt = _hist_dtype()
        A = (node_oh[:, :, None].astype(hdt)
             * sblk.T.astype(hdt)[:, None, :])           # (blk, NL, S)
        At = A.reshape(blk, n_nodes * S).T               # (NL·S, blk)
        oh = (Bblk[:, :, None] == bins_u8).astype(hdt)
        return hist + jax.lax.dot(
            At, oh.reshape(blk, d * n_bins),
            preferred_element_type=jnp.float32), None

    hist, _ = jax.lax.scan(
        hist_block, jnp.zeros((n_nodes * S, d * n_bins), jnp.float32),
        jnp.arange(nbk))
    # (NL·S, d·nb) → (NL, d, bins, S)
    return hist.reshape(n_nodes, S, d, n_bins).transpose(0, 2, 3, 1)


def _route_level_xla(B, rel, active, assign, best_f, best_t, split, *,
                     blk):
    """One level's routing pass (oracle path): rows of split nodes go to
    their children, leaf rows keep their node. Blocked for the same
    lane-padding reason as the histogram."""
    nbk = B.shape[0] // blk

    def route_block(asg, i):
        Bblk = jax.lax.dynamic_slice_in_dim(B, i * blk, blk)
        relblk = jax.lax.dynamic_slice_in_dim(rel, i * blk, blk)
        ablk = jax.lax.dynamic_slice_in_dim(active, i * blk, blk)
        asgblk = jax.lax.dynamic_slice_in_dim(asg, i * blk, blk)
        rf = _sel_table(best_f, relblk)
        rt = _sel_table(best_t, relblk)
        rs = _sel_table(split, relblk) & ablk
        gr = _sel_col(Bblk, rf) > rt
        new = jnp.where(rs, 2 * asgblk + 1 + gr.astype(jnp.int32),
                        asgblk)
        return jax.lax.dynamic_update_slice_in_dim(
            asg, new, i * blk, axis=0), None

    asg, _ = jax.lax.scan(route_block, assign, jnp.arange(nbk))
    return asg


def _leaf_stats_xla(assign, stats_T, *, n_nodes, blk):
    """Local per-leaf sufficient statistics (oracle path) — the same
    matmul-histogram trick over the final assignment. (S, M)."""
    S = stats_T.shape[0]
    nbk = assign.shape[0] // blk

    def leaf_block(acc, i):
        asgblk = jax.lax.dynamic_slice_in_dim(assign, i * blk, blk)
        sblk = jax.lax.dynamic_slice_in_dim(stats_T, i * blk, blk, axis=1)
        hdt = _hist_dtype()
        oh = (asgblk[:, None] == jnp.arange(n_nodes)[None, :]).astype(hdt)
        return acc + jax.lax.dot(sblk.astype(hdt), oh,
                                 preferred_element_type=jnp.float32), None

    leaf, _ = jax.lax.scan(
        leaf_block, jnp.zeros((S, n_nodes), jnp.float32), jnp.arange(nbk))
    return leaf


def _build_tree(B, stats_T, feat_gain_mask, *, max_depth, n_bins,
                gain_fn, weight_fn, min_child_weight, min_gain,
                use_kernel=False, bin_gain_mask=None, level_allow=None):
    """Grow one tree. All shapes static; call inside shard_map.

    B: (n, d) uint8 bin codes (local shard rows).
    stats_T: (S, n) float32 per-row sufficient statistics, TRANSPOSED so
        the long row axis sits in TPU lanes (zero columns for masked
        rows — padding/bootstrap-excluded rows simply carry zero weight).
    feat_gain_mask: (d,) float32 — 0 allows a feature, NEG forbids it
        (random-forest per-tree feature subsampling).
    gain_fn(left, total) -> gain over trailing stat dim; higher is better.
    weight_fn(stat_sums) -> scalar node weight for min_child_weight.
    use_kernel: route the histogram/routing/leaf passes through the
        fused Pallas kernels (ops/pallas_kernels.py) instead of the
        blocked XLA contraction oracle. Must be static (it selects the
        compiled program); split decisions and per-level psums are
        identical either way.
    bin_gain_mask: optional (n_bins,) float32 traced mask — 0 allows a
        split threshold, NEG forbids it. The hyperparameter-population
        path (models/tune.py) builds at the population's STATIC maximum
        n_bins and forbids thresholds ≥ a member's own n_bins - 1, which
        reproduces that member's standalone split set exactly (its high
        bins hold zero mass, so allowed gains are bit-identical).
    level_allow: optional (max_depth,) traced mask — False forbids
        splitting any node at that level. Same population trick for
        per-member max_depth under a static maximum: forbidden levels
        leave nodes as leaves, so node ids [0, 2^(member_depth+1)-1)
        match a standalone build at the member's own depth.

    Returns (feat (M,), thr (M,), is_internal (M,), leaf_stats (M, S)) with
    M = 2^(max_depth+1) - 1 nodes; children of i at 2i+1 / 2i+2.
    """
    n, d = B.shape
    S = stats_T.shape[0]
    M = 2 ** (max_depth + 1) - 1
    if use_kernel:
        # Kernel row tiles are VMEM-sized; everything else about the
        # level loop (and the per-level psum) is shared with the oracle.
        blk = pallas_kernels.tree_tile(d, n_bins)
        nbk = -(-n // blk)
        n_pad = nbk * blk
    else:
        blk, nbk, n_pad = _block_shape(n, d * n_bins)
    if n_pad != n:
        B = jnp.pad(B, ((0, n_pad - n), (0, 0)))
        stats_T = jnp.pad(stats_T, ((0, 0), (0, n_pad - n)))
    hdt = _hist_dtype()

    #: Fixed per-level node width: the deepest processed level has
    #: 2^(max_depth-1) nodes, and every level runs at that width so the
    #: whole level loop is ONE lax.scan body (a per-level Python unroll
    #: re-traces 5 distinct level shapes and blew gb's compile time to
    #: minutes). Slots past a level's real node count carry all-zero
    #: stats — their gain is NEG so they never split — and their
    #: node-id writes spill into exactly the id range later levels
    #: rewrite (binary-heap layout: level l writes [2^l-1, 2^l-1+NL),
    #: and every id ≥ 2^(l+1)-1 is level-(l+1)+ territory).
    NL = 2 ** max(max_depth - 1, 0)

    def level_step(carry, xs):
        l, lvl_ok = xs
        feat, thr, is_internal, assign = carry
        offset = jnp.left_shift(1, l) - 1            # 2^l - 1
        nl = offset + 1                              # 2^l real nodes
        rel = assign - offset
        active = (rel >= 0) & (rel < nl)
        rel = jnp.where(active, rel, 0)

        if use_kernel:
            hist = pallas_kernels.tree_histogram(
                B, stats_T, rel, active, n_nodes=NL, n_bins=n_bins,
                tile=blk, operand_dtype=hdt)
        else:
            hist = _hist_level_xla(B, stats_T, rel, active, n_nodes=NL,
                                   n_bins=n_bins, blk=blk)
        hist = jax.lax.psum(hist, DATA_AXIS)                 # ICI reduce

        left = jnp.cumsum(hist, axis=2)                          # ≤ bin t
        total = left[:, :, -1:, :]                               # (NL,d,1,S)
        gain = gain_fn(left, total)                              # (NL,d,nb)
        # A split at the last bin sends everything left — forbid it.
        gain = gain.at[:, :, -1].set(NEG)
        lw = weight_fn(left)
        rw = weight_fn(total) - lw
        ok = (lw >= min_child_weight) & (rw >= min_child_weight)
        gain = jnp.where(ok, gain, NEG) + feat_gain_mask[None, :, None]
        if bin_gain_mask is not None:
            gain = gain + bin_gain_mask[None, None, :]

        flat = gain.reshape(NL, d * n_bins)
        best = jnp.argmax(flat, axis=1)
        best_gain = jnp.take_along_axis(flat, best[:, None], axis=1)[:, 0]
        best_f = (best // n_bins).astype(jnp.int32)
        best_t = (best % n_bins).astype(jnp.int32)
        split = (best_gain > min_gain) & lvl_ok

        node_ids = offset + jnp.arange(NL)
        feat = feat.at[node_ids].set(jnp.where(split, best_f, 0))
        thr = thr.at[node_ids].set(jnp.where(split, best_t, 0))
        is_internal = is_internal.at[node_ids].set(split)

        if use_kernel:
            asg = pallas_kernels.tree_route_level(
                B, rel, active, assign, best_f, best_t, split, tile=blk)
        else:
            asg = _route_level_xla(B, rel, active, assign, best_f,
                                   best_t, split, blk=blk)
        return (feat, thr, is_internal, asg), None

    if level_allow is None:
        level_allow = jnp.ones((max_depth,), bool)
    (feat, thr, is_internal, assign), _ = jax.lax.scan(
        level_step,
        (jnp.zeros((M,), jnp.int32), jnp.zeros((M,), jnp.int32),
         jnp.zeros((M,), bool), jnp.zeros((n_pad,), jnp.int32)),
        (jnp.arange(max_depth), level_allow))

    # Leaf sufficient statistics over ALL nodes (every row sits at a leaf;
    # padded columns carry zero stats).
    if use_kernel:
        leaf = pallas_kernels.tree_leaf_stats(
            assign, stats_T, n_nodes=M, tile=blk, operand_dtype=hdt)
    else:
        leaf = _leaf_stats_xla(assign, stats_T, n_nodes=M, blk=blk)
    leaf = jax.lax.psum(leaf.T, DATA_AXIS)                   # (M, S)
    return feat, thr, is_internal, leaf


def _descend(B, feat, thr, is_internal, max_depth, use_kernel=False):
    """Blocked routing of binned rows to their leaf node id.

    ``use_kernel`` routes through the fused Pallas descent kernel; the
    result is bit-identical either way (integer arithmetic throughout),
    so predict paths may flip it per batch shape — batches below the
    kernel row tile (e.g. the serving tier's row-wise AOT programs) stay
    on the oracle, where tile padding would dominate."""
    n, d = B.shape
    if use_kernel and n >= pallas_kernels.TREE_ROUTE_TILE:
        return pallas_kernels.tree_descend(B, feat, thr, is_internal,
                                           max_depth=max_depth)
    blk, nbk, n_pad = _block_shape(n)
    if n_pad != n:
        B = jnp.pad(B, ((0, n_pad - n), (0, 0)))

    def desc_block(acc, i):
        Bblk = jax.lax.dynamic_slice_in_dim(B, i * blk, blk)
        a = jnp.zeros((blk,), jnp.int32)
        for _ in range(max_depth):
            f = _sel_table(feat, a)
            t = _sel_table(thr, a)
            internal = _sel_table(is_internal, a)
            go_right = _sel_col(Bblk, f) > t
            a = jnp.where(internal, 2 * a + 1 + go_right.astype(jnp.int32),
                          a)
        return jax.lax.dynamic_update_slice_in_dim(acc, a, i * blk,
                                                   axis=0), None

    a, _ = jax.lax.scan(desc_block, jnp.zeros((n_pad,), jnp.int32),
                        jnp.arange(nbk))
    return a[:n]


# ---------------------------------------------------------------------------
# Criteria
# ---------------------------------------------------------------------------

def _gini_gain(left, total):
    """Weighted gini impurity decrease; stats are per-class weights."""
    right = total - left
    lw = left.sum(-1)
    rw = right.sum(-1)
    tw = total.sum(-1)

    def gini_w(counts, w):
        # w * gini = w - sum(c^2)/w
        return w - (counts ** 2).sum(-1) / jnp.maximum(w, 1e-12)

    parent = gini_w(total, tw)
    child = gini_w(left, lw) + gini_w(right, rw)
    return (parent - child) / jnp.maximum(tw, 1e-12)


def _make_newton_gain(lam: float):
    """XGBoost-style gain on [grad, hess] stats."""

    def gain(left, total):
        right = total - left
        gl, hl = left[..., 0], left[..., 1]
        gr, hr = right[..., 0], right[..., 1]
        g, h = total[..., 0], total[..., 1]
        return (gl ** 2 / (hl + lam) + gr ** 2 / (hr + lam)
                - g ** 2 / (h + lam))

    return gain


# ---------------------------------------------------------------------------
# dt / rf  (classification trees, gini)
# ---------------------------------------------------------------------------

def _forest_batch_shape(n_trees: int):
    """(trees per vmapped batch, batch count). Batch = the largest
    divisor of n_trees ≤ 8, falling back to padded batches of 8 when
    n_trees has no usable divisor (the discarded pad trees cost < one
    batch). Shared by the oracle and the checkpoint-segmented path so
    per-batch shapes — and therefore values — cannot diverge."""
    tb = max((t for t in range(1, min(8, n_trees) + 1)
              if n_trees % t == 0), default=1)
    if tb < 4 and n_trees > 8:
        tb = 8
    nb = -(-n_trees // tb)
    return tb, nb


def _one_tree_fn(B, y, valid, *, num_classes, n_trees, max_depth, n_bins,
                 mtry, min_child_weight, use_kernel):
    """The per-tree builder (bootstrap + feature subsample + level-wise
    build), shared verbatim by the oracle's lax.map and the
    checkpoint-segmented per-batch program. Runs inside shard_map."""
    d = B.shape[1]
    # Per-class weights TRANSPOSED to (C, n): the long row axis must
    # sit in TPU lanes (an (n, C<128) layout pays for 128 lanes).
    classes = jnp.arange(num_classes, dtype=y.dtype)[:, None]
    base_stats = ((y[None, :] == classes).astype(jnp.float32)
                  * valid[None, :])

    def one_tree(key):
        kb, kf = jax.random.split(key)
        if n_trees == 1:
            stats = base_stats
            fmask = jnp.zeros((d,), jnp.float32)
        else:
            # Poisson(1) bootstrap weights; identical draw on every
            # shard would correlate rows, so fold in the shard index.
            kb = jax.random.fold_in(kb, jax.lax.axis_index(DATA_AXIS))
            w = jax.random.poisson(kb, 1.0, (B.shape[0],)).astype(
                jnp.float32)
            stats = base_stats * w[None, :]
            # mtry features allowed per tree (same mask on all shards).
            perm = jax.random.permutation(kf, d)
            allowed = jnp.zeros((d,), bool).at[perm[:mtry]].set(True)
            fmask = jnp.where(allowed, 0.0, NEG)
        feat, thr, internal, leaf = _build_tree(
            B, stats, fmask, max_depth=max_depth, n_bins=n_bins,
            gain_fn=_gini_gain, weight_fn=lambda s: s.sum(-1),
            min_child_weight=min_child_weight, min_gain=1e-9,
            use_kernel=use_kernel)
        return feat, thr, internal, leaf

    return one_tree


@partial(jax.jit,
         static_argnames=("num_classes", "max_depth", "n_bins", "n_trees",
                          "mesh", "mtry", "use_kernel"))
def _fit_forest(B, y, valid, key, *, num_classes, max_depth, n_bins,
                n_trees, mesh, mtry, min_child_weight=1.0,
                use_kernel=False):
    """dt (n_trees=1, no bagging) and rf (bootstrap + feature subsampling)."""

    def shard_fn(B, y, valid, key):
        one_tree = _one_tree_fn(
            B, y, valid, num_classes=num_classes, n_trees=n_trees,
            max_depth=max_depth, n_bins=n_bins, mtry=mtry,
            min_child_weight=min_child_weight, use_kernel=use_kernel)
        # Trees build in vmapped batches: a batch's (NL·S, blk) histogram
        # operands stack into one (tb·NL·S, blk) @ (blk, d·n_bins) MXU
        # contraction per row block — ~2× over tree-at-a-time lax.map on
        # rf fits — while the outer sequential map bounds live per-tree
        # row state (stats/weights/assign are O(tb·n), not O(n_trees·n),
        # so n_trees=100 still fits HBM).
        tb, nb = _forest_batch_shape(n_trees)
        keys = jax.random.split(key, nb * tb)
        outs = jax.lax.map(jax.vmap(one_tree),
                           keys.reshape(nb, tb, *keys.shape[1:]))
        return jax.tree.map(
            lambda a: a.reshape(nb * tb, *a.shape[2:])[:n_trees], outs)

    return jax.shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), P()),
        out_specs=P(), check_vma=False,
    )(B, y, valid, key)


@partial(jax.jit,
         static_argnames=("num_classes", "max_depth", "n_bins", "n_trees",
                          "mesh", "mtry", "use_kernel"))
def _fit_forest_batch(B, y, valid, keys_b, *, num_classes, max_depth,
                      n_bins, n_trees, mesh, mtry, min_child_weight=1.0,
                      use_kernel=False):
    """ONE vmapped tree batch of the forest — the checkpoint-segmented
    complement to ``_fit_forest``'s internal lax.map: the same vmapped
    ``one_tree`` body over an explicit key slice, so batch b's trees are
    bit-identical to the oracle's iteration b (``n_trees`` stays the
    FULL forest size — it selects the bagging branch, not the batch
    width). Only engaged when ``LO_TPU_FIT_CKPT_ROUNDS > 0``."""

    def shard_fn(B, y, valid, keys_b):
        one_tree = _one_tree_fn(
            B, y, valid, num_classes=num_classes, n_trees=n_trees,
            max_depth=max_depth, n_bins=n_bins, mtry=mtry,
            min_child_weight=min_child_weight, use_kernel=use_kernel)
        return jax.vmap(one_tree)(keys_b)

    return jax.shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), P()),
        out_specs=P(), check_vma=False,
    )(B, y, valid, keys_b)


# ---------------------------------------------------------------------------
# Config-population programs (models/tune.py)
#
# The hyperparameter-search tier vmaps a POPULATION of same-family
# configs over the member axis — the tree-batch vmap one level up. All
# static shapes are the population's maxima (max_depth, n_bins,
# n_trees); a member's smaller depth/bin-count is enforced by the
# traced ``level_allow``/``bin_gain_mask`` arguments of ``_build_tree``,
# which reproduce the member's standalone split set exactly. Per-member
# row weights carry validity × k-fold membership (and drop to zero when
# successive halving kills the member), so folds are index masks over
# the ONE resident design — never data copies. The Pallas kernel path
# stays off here: the kernels are shaped per single tree, and the
# oracle contraction is the documented bit-parity reference.
# ---------------------------------------------------------------------------

@jax.jit
def _bin_features_pop(X, edges_pop):
    """Per-member bin codes from per-member (inf-padded) edge stacks:
    (n, d) × (Pm, d, n_bins_max - 1) → (Pm, n, d) uint8. Padding edges
    with +inf yields codes bit-identical to binning with the member's
    own (shorter) edge list."""
    return jax.vmap(lambda e: bin_features(X, e))(edges_pop)


@partial(jax.jit,
         static_argnames=("num_classes", "max_depth", "n_bins", "n_trees",
                          "mesh"))
def _fit_forest_pop_batch(B_pop, y, w_pop, bin_mask, level_allow,
                          mtry_vec, keys_b, *, num_classes, max_depth,
                          n_bins, n_trees, mesh):
    """One vmapped tree batch for a POPULATION of dt/rf configs.

    Mirrors ``_fit_forest_batch`` with a member axis on top: per member
    its own bin matrix, row weights (validity × fold × alive), bin/level
    masks and mtry. ``n_trees`` is the population-shared forest size (it
    selects the bagging branch and the key count, exactly as in the
    serial oracle, so per-member trees are bit-identical to that
    member's standalone fit)."""

    def shard_fn(B_pop, y, w_pop, bin_mask, level_allow, mtry_vec,
                 keys_b):
        d = B_pop.shape[2]
        classes = jnp.arange(num_classes, dtype=y.dtype)[:, None]

        def one_member(B, w, bmask, lallow, mtry_m, keys):
            base_stats = ((y[None, :] == classes).astype(jnp.float32)
                          * w[None, :])

            def one_tree(key):
                kb, kf = jax.random.split(key)
                if n_trees == 1:
                    stats = base_stats
                    fmask = jnp.zeros((d,), jnp.float32)
                else:
                    kb = jax.random.fold_in(
                        kb, jax.lax.axis_index(DATA_AXIS))
                    wb = jax.random.poisson(
                        kb, 1.0, (B.shape[0],)).astype(jnp.float32)
                    stats = base_stats * wb[None, :]
                    # First-mtry-of-perm mask via the inverse permutation
                    # (rank < mtry) — the traced-mtry form of the
                    # oracle's static ``perm[:mtry]`` scatter; the
                    # resulting feature set is identical.
                    perm = jax.random.permutation(kf, d)
                    allowed = jnp.argsort(perm) < mtry_m
                    fmask = jnp.where(allowed, 0.0, NEG)
                return _build_tree(
                    B, stats, fmask, max_depth=max_depth, n_bins=n_bins,
                    gain_fn=_gini_gain, weight_fn=lambda s: s.sum(-1),
                    min_child_weight=1.0, min_gain=1e-9,
                    use_kernel=False, bin_gain_mask=bmask,
                    level_allow=lallow)

            return jax.vmap(one_tree)(keys)

        return jax.vmap(one_member)(B_pop, w_pop, bin_mask, level_allow,
                                    mtry_vec, keys_b)

    return jax.shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(None, DATA_AXIS), P(DATA_AXIS), P(None, DATA_AXIS),
                  P(), P(), P(), P()),
        out_specs=P(), check_vma=False,
    )(B_pop, y, w_pop, bin_mask, level_allow, mtry_vec, keys_b)


@partial(jax.jit, static_argnames=("max_depth", "mesh"))
def _forest_pop_scores(B_pop, y, ew_pop, feat, thr, internal, leaf, *,
                       max_depth, mesh):
    """Per-member forest accuracy on per-member (eval-fold) row weights.

    Tree arrays arrive at the FULL (Pm, n_trees, ...) shape with
    all-zero slots for not-yet-built trees (their leaf counts are zero,
    contributing zero probability mass), so every halving rung scores
    through this one compiled program."""

    def shard_fn(B_pop, y, ew_pop, feat, thr, internal, leaf):
        def one_member(B, ew, f, t, it, lf):
            def tree_proba(f1, t1, it1, lf1):
                assign = _descend(B, f1, t1, it1, max_depth)
                counts = _sel_rows_blocked(lf1, assign)
                return counts / jnp.maximum(
                    counts.sum(-1, keepdims=True), 1e-12)

            probs = jax.vmap(tree_proba)(f, t, it, lf).mean(axis=0)
            pred = jnp.argmax(probs, axis=1).astype(y.dtype)
            hit = jax.lax.psum(
                ((pred == y).astype(jnp.float32) * ew).sum(), DATA_AXIS)
            tot = jax.lax.psum(ew.sum(), DATA_AXIS)
            return hit / jnp.maximum(tot, 1.0)

        return jax.vmap(one_member)(B_pop, ew_pop, feat, thr, internal,
                                    leaf)

    return jax.shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(None, DATA_AXIS), P(DATA_AXIS), P(None, DATA_AXIS),
                  P(), P(), P(), P()),
        out_specs=P(), check_vma=False,
    )(B_pop, y, ew_pop, feat, thr, internal, leaf)


@partial(jax.jit,
         static_argnames=("max_depth", "n_bins", "n_rounds", "mesh"))
def _fit_gbt_pop_seg(B_pop, y, w_pop, margin0, step_sizes, round_active,
                     bin_mask, level_allow, *, max_depth, n_bins,
                     n_rounds, mesh):
    """One SEGMENT of boost rounds for a POPULATION of gb configs.

    ``round_active`` is (Pm, n_rounds) ∈ {0, 1}: a zero round leaves the
    member's margin untouched and zeroes the round's leaf values (so the
    stacked trees stay inert in prediction) — this is how per-member
    ``n_rounds`` under the static maximum and halving-dropped members
    are expressed. Per-member ``step_sizes`` ride as traced scalars, the
    boost-round arithmetic is the serial oracle's (lam = 1.0)."""

    def shard_fn(B_pop, y, w_pop, margin0, step_sizes, round_active,
                 bin_mask, level_allow):
        gain_fn = _make_newton_gain(1.0)
        yf = y.astype(jnp.float32)

        def one_member(B, w, margin, step_size, ractive, bmask, lallow):
            def boost_round(margin, act):
                p = jax.nn.sigmoid(margin)
                g = (p - yf) * w
                h = jnp.maximum(p * (1 - p), 1e-6) * w
                stats = jnp.stack([g, h], axis=0)
                feat, thr, internal, leaf = _build_tree(
                    B, stats, jnp.zeros((B.shape[1],), jnp.float32),
                    max_depth=max_depth, n_bins=n_bins, gain_fn=gain_fn,
                    weight_fn=lambda s: s[..., 1],
                    min_child_weight=1e-3, min_gain=1e-9,
                    use_kernel=False, bin_gain_mask=bmask,
                    level_allow=lallow)
                leaf_val = (-leaf[:, 0] / (leaf[:, 1] + 1.0)) * act
                assign = _descend(B, feat, thr, internal, max_depth)
                margin = margin + step_size * _sel_table_blocked(
                    leaf_val, assign)
                return margin, (feat, thr, internal, leaf_val)

            margin, trees_out = jax.lax.scan(boost_round, margin,
                                             ractive)
            return trees_out, margin

        return jax.vmap(one_member)(B_pop, w_pop, margin0, step_sizes,
                                    round_active, bin_mask, level_allow)

    return jax.shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(None, DATA_AXIS), P(DATA_AXIS), P(None, DATA_AXIS),
                  P(None, DATA_AXIS), P(), P(), P(), P()),
        out_specs=(P(), P(None, DATA_AXIS)), check_vma=False,
    )(B_pop, y, w_pop, margin0, step_sizes, round_active, bin_mask,
      level_allow)


@partial(jax.jit, static_argnames=("max_depth", "mesh"))
def _gbt_pop_replay_margin(B_pop, feat, thr, internal, leaf_val,
                           step_sizes, *, max_depth, mesh):
    """Per-member margin replay from checkpointed population trees — the
    resume path's analogue of ``_gbt_replay_margin``. Leaf values were
    stored already round-activity-scaled, so the replayed fold is the
    training scan's own sequence bit-for-bit."""

    def shard_fn(B_pop, feat, thr, internal, leaf_val, step_sizes):
        def one_member(B, f, t, it, lv, ss):
            def one(margin, tree):
                f1, t1, it1, lv1 = tree
                assign = _descend(B, f1, t1, it1, max_depth)
                return margin + ss * _sel_table_blocked(lv1, assign), None

            margin, _ = jax.lax.scan(
                one, jnp.zeros(B.shape[0], jnp.float32), (f, t, it, lv))
            return margin

        return jax.vmap(one_member)(B_pop, feat, thr, internal, leaf_val,
                                    step_sizes)

    return jax.shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(None, DATA_AXIS), P(), P(), P(), P(), P()),
        out_specs=P(None, DATA_AXIS), check_vma=False,
    )(B_pop, feat, thr, internal, leaf_val, step_sizes)


@partial(jax.jit, static_argnames=("max_depth", "mesh"))
def _gbt_pop_scores(B_pop, y, ew_pop, feat, thr, internal, leaf_val,
                    step_sizes, *, max_depth, mesh):
    """Per-member binary-gb accuracy on eval-fold weights. Unbuilt/inert
    rounds carry zero leaf values, so the fixed (Pm, R_max, ...) shape
    scores every rung through one compiled program."""

    def shard_fn(B_pop, y, ew_pop, feat, thr, internal, leaf_val,
                 step_sizes):
        def one_member(B, ew, f, t, it, lv, ss):
            def tree_margin(f1, t1, it1, lv1):
                return _sel_table_blocked(
                    lv1, _descend(B, f1, t1, it1, max_depth))

            margin = ss * jax.vmap(tree_margin)(f, t, it, lv).sum(axis=0)
            pred = (jax.nn.sigmoid(margin) > 0.5).astype(y.dtype)
            hit = jax.lax.psum(
                ((pred == y).astype(jnp.float32) * ew).sum(), DATA_AXIS)
            tot = jax.lax.psum(ew.sum(), DATA_AXIS)
            return hit / jnp.maximum(tot, 1.0)

        return jax.vmap(one_member)(B_pop, ew_pop, feat, thr, internal,
                                    leaf_val, step_sizes)

    return jax.shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(None, DATA_AXIS), P(DATA_AXIS), P(None, DATA_AXIS),
                  P(), P(), P(), P(), P()),
        out_specs=P(), check_vma=False,
    )(B_pop, y, ew_pop, feat, thr, internal, leaf_val, step_sizes)


def _edge_prep(X, n_bins: int = 32, **_ignored) -> dict:
    """Host-side prep shared by every tree family: per-feature quantile
    bin edges from a row sample. Exposed as the trainers' ``host_prep``
    hook so the pipelined builder can run this (chunk-store reads for
    lazy designs, host quantiles) OUTSIDE the device phase — overlapping
    another family's device compute. Deterministic (seeded sampler), so
    pod workers recomputing it inside their trainer calls produce
    bit-identical edges. Lazy designs never exist fully on the host: the
    sample comes from strided range reads (quantile sketches over samples
    are the norm for histogram GBTs — the full-matrix path itself
    subsamples to 200k)."""
    validate_n_bins(n_bins)
    X = as_design(X)
    return {"edges": quantile_edges(
        X if isinstance(X, np.ndarray) else X.sample_rows(200_000), n_bins)}


def _run_forest_checkpointed(runtime, ckpt, B_dev, y_dev, valid_dev,
                             seed, *, num_classes, max_depth, n_bins,
                             n_trees, mtry, use_kernel):
    """Batch-at-a-time forest build with a checkpoint at every vmapped
    tree-batch boundary. Keys, batch shapes and the per-tree body are
    the oracle's, so the stacked result is bit-identical to one
    ``_fit_forest`` call; a resume skips the completed batches."""
    from learningorchestra_tpu import jobs
    from learningorchestra_tpu.utils import fitckpt

    mesh = runtime.mesh
    tb, nb = _forest_batch_shape(n_trees)
    keys = np.asarray(jax.random.split(jax.random.PRNGKey(seed), nb * tb))
    names = ("feat", "thr", "internal", "leaf")
    done_b = 0
    host: dict = {}
    loaded = ckpt.load()
    if loaded is not None:
        trees_done, arrays, meta = loaded
        if trees_done % tb == 0 and 0 < trees_done <= nb * tb and all(
                k in arrays for k in names):
            done_b = trees_done // tb
            host = {k: arrays[k] for k in names}
            fitckpt.count_resume()
            jobs.record_job_resume(ckpt.family, {
                "trees": int(trees_done), "of": int(n_trees),
                "mesh_epoch": meta.get("mesh_epoch")})
        else:
            ckpt.clear()
    for b in range(done_b, nb):
        outs = _fit_forest_batch(
            B_dev, y_dev, valid_dev,
            jnp.asarray(keys[b * tb:(b + 1) * tb]),
            num_classes=num_classes, max_depth=max_depth, n_bins=n_bins,
            n_trees=n_trees, mesh=mesh, mtry=mtry, use_kernel=use_kernel)
        seg = {k: np.asarray(a) for k, a in zip(names, outs)}
        host = ({k: np.concatenate([host[k], seg[k]]) for k in names}
                if host else seg)
        jobs.heartbeat()
        if b + 1 < nb:
            ckpt.save((b + 1) * tb, host)
    return tuple(jnp.asarray(host[k][:n_trees]) for k in names)


def _fit_cls_trees(kind, runtime, X, y, num_classes, seed, *, n_trees,
                   max_depth, n_bins, mtry=None, edges=None, ckpt=None):
    validate_n_bins(n_bins)

    X = as_design(X)
    if edges is None:
        edges = _edge_prep(X, n_bins)["edges"]
    # Shard the raw design matrix (one cached host→device transfer shared
    # with every other family in a multi-classifier build) and bin ON
    # DEVICE: binning is row-local, so the uint8 codes come out row-sharded
    # with no host round-trip of the bin matrix.
    X_dev, n = runtime.shard_rows(X)
    B_dev = bin_features(X_dev, runtime.replicate(edges))
    y_dev, _ = runtime.shard_rows(np.asarray(y, np.int32))
    padded_len = len(X) + (-len(X)) % runtime.mesh.shape[DATA_AXIS]
    valid_dev, _ = runtime.shard_rows(
        (np.arange(padded_len) < n).astype(np.float32))
    d = X.shape[1]
    mtry = mtry or max(1, int(np.sqrt(d)))
    use_kernel = _use_tree_kernel(runtime)
    if (ckpt is not None and ckpt.enabled
            and _forest_batch_shape(n_trees)[1] > 1):
        feat, thr, internal, leaf = _run_forest_checkpointed(
            runtime, ckpt, B_dev, y_dev, valid_dev, seed,
            num_classes=num_classes, max_depth=max_depth, n_bins=n_bins,
            n_trees=n_trees, mtry=mtry, use_kernel=use_kernel)
    else:
        feat, thr, internal, leaf = _fit_forest(
            B_dev, y_dev, valid_dev, jax.random.PRNGKey(seed),
            num_classes=num_classes, max_depth=max_depth, n_bins=n_bins,
            n_trees=n_trees, mesh=runtime.mesh, mtry=mtry,
            use_kernel=use_kernel)
    params = {"edges": jnp.asarray(edges), "feat": feat, "thr": thr,
              "internal": internal, "leaf": leaf}
    return TrainedModel(
        kind=kind, params=params,
        predict_proba_fn=partial(_forest_proba_static, max_depth=max_depth),
        num_classes=num_classes,
        hparams={"n_trees": n_trees, "max_depth": max_depth,
                 "n_bins": n_bins})


@partial(jax.jit, static_argnames=("max_depth",))
def _forest_proba_static(params, X, *, max_depth):
    B = bin_features(X, params["edges"])
    # Trace-time kernel selection is safe here: descent is integer
    # arithmetic, so probabilities are bit-identical on either path (the
    # AOT row-wise predict programs stay on the oracle via the batch-size
    # gate in _descend).
    use_kernel = _use_tree_kernel()

    def tree_proba(f, t, it, lf):
        assign = _descend(B, f, t, it, max_depth, use_kernel=use_kernel)
        counts = _sel_rows_blocked(lf, assign)
        return counts / jnp.maximum(counts.sum(-1, keepdims=True), 1e-12)

    probs = jax.vmap(tree_proba)(params["feat"], params["thr"],
                                 params["internal"], params["leaf"])
    return probs.mean(axis=0)


def fit_dt(runtime: MeshRuntime, X, y, num_classes, seed=0, *,
           max_depth: int = 5, n_bins: int = 32,
           edges=None, ckpt=None) -> TrainedModel:
    return _fit_cls_trees("dt", runtime, X, y, num_classes, seed,
                          n_trees=1, max_depth=max_depth, n_bins=n_bins,
                          edges=edges, ckpt=ckpt)


def fit_rf(runtime: MeshRuntime, X, y, num_classes, seed=0, *,
           n_trees: int = 20, max_depth: int = 5,
           n_bins: int = 32, mtry: Optional[int] = None,
           edges=None, ckpt=None) -> TrainedModel:
    return _fit_cls_trees("rf", runtime, X, y, num_classes, seed,
                          n_trees=n_trees, max_depth=max_depth,
                          n_bins=n_bins, mtry=mtry, edges=edges,
                          ckpt=ckpt)


fit_dt.host_prep = _edge_prep
fit_rf.host_prep = _edge_prep


# ---------------------------------------------------------------------------
# gb  (gradient-boosted trees, binary, logistic loss — as Spark's GBT)
# ---------------------------------------------------------------------------

def _boost_round_fn(B, yf, valid, *, max_depth, n_bins, step_size, lam,
                    use_kernel):
    """The per-round boosting body, shared verbatim by the oracle scan
    (``_fit_gbt``) and the checkpoint-segmented scan (``_fit_gbt_seg``)
    so the two paths cannot drift numerically."""
    gain_fn = _make_newton_gain(lam)

    def boost_round(margin, _):
        p = jax.nn.sigmoid(margin)
        g = (p - yf) * valid          # d loss / d margin
        h = jnp.maximum(p * (1 - p), 1e-6) * valid
        stats = jnp.stack([g, h], axis=0)          # (2, n) — lanes = n
        feat, thr, internal, leaf = _build_tree(
            B, stats, jnp.zeros((B.shape[1],), jnp.float32),
            max_depth=max_depth, n_bins=n_bins, gain_fn=gain_fn,
            weight_fn=lambda s: s[..., 1],
            min_child_weight=1e-3, min_gain=1e-9,
            use_kernel=use_kernel)
        leaf_val = -leaf[:, 0] / (leaf[:, 1] + lam)       # (M,)
        assign = _descend(B, feat, thr, internal, max_depth,
                          use_kernel=use_kernel)
        margin = margin + step_size * _sel_table_blocked(leaf_val,
                                                         assign)
        return margin, (feat, thr, internal, leaf_val)

    return boost_round


@partial(jax.jit,
         static_argnames=("max_depth", "n_bins", "n_rounds", "mesh",
                          "use_kernel"))
def _fit_gbt(B, y, valid, *, max_depth, n_bins, n_rounds, mesh,
             step_size=0.1, lam=1.0, use_kernel=False):
    def shard_fn(B, y, valid):
        yf = y.astype(jnp.float32)
        margin = jnp.zeros(B.shape[0], jnp.float32)
        boost_round = _boost_round_fn(
            B, yf, valid, max_depth=max_depth, n_bins=n_bins,
            step_size=step_size, lam=lam, use_kernel=use_kernel)
        _, trees = jax.lax.scan(boost_round, margin, None,
                                length=n_rounds)
        return trees

    return jax.shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=P(), check_vma=False,
    )(B, y, valid)


@partial(jax.jit,
         static_argnames=("max_depth", "n_bins", "n_rounds", "mesh",
                          "use_kernel"))
def _fit_gbt_seg(B, y, valid, margin0, *, max_depth, n_bins, n_rounds,
                 mesh, step_size=0.1, lam=1.0, use_kernel=False):
    """One SEGMENT of boost rounds for the checkpointed gb path: takes
    the carried margin in (row-sharded), returns it back out next to the
    segment's trees — so a fit interrupted between segments resumes from
    the persisted trees with bit-identical arithmetic (the round body is
    the oracle's, shared via ``_boost_round_fn``). Only engaged when
    ``LO_TPU_FIT_CKPT_ROUNDS > 0``; the single-scan oracle above stays
    today's path otherwise."""
    def shard_fn(B, y, valid, margin0):
        yf = y.astype(jnp.float32)
        boost_round = _boost_round_fn(
            B, yf, valid, max_depth=max_depth, n_bins=n_bins,
            step_size=step_size, lam=lam, use_kernel=use_kernel)
        margin, trees = jax.lax.scan(boost_round, margin0, None,
                                     length=n_rounds)
        return trees, margin

    return jax.shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS),
                  P(DATA_AXIS)),
        out_specs=(P(), P(DATA_AXIS)), check_vma=False,
    )(B, y, valid, margin0)


@partial(jax.jit, static_argnames=("max_depth", "mesh", "use_kernel"))
def _gbt_replay_margin(B, feat, thr, internal, leaf_val, step_size, *,
                       max_depth, mesh, use_kernel=False):
    """Rebuild the boosting margin from checkpointed trees by replaying
    each round's margin update — the same sequential
    ``margin += step_size * leaf_val[descend(B)]`` fold the training
    scan performs, in the same order, so the resumed margin is
    bit-identical to the interrupted fit's carry (descent is integer
    arithmetic; the f32 accumulation order is preserved). Cost is the
    cheap descent/lookup part of each completed round — the histogram
    builds, which dominate a round, are never re-executed."""
    def shard_fn(B, feat, thr, internal, leaf_val, step_size):
        def one(margin, tree):
            f, t, it, lv = tree
            assign = _descend(B, f, t, it, max_depth,
                              use_kernel=use_kernel)
            return margin + step_size * _sel_table_blocked(lv, assign), \
                None

        margin, _ = jax.lax.scan(
            one, jnp.zeros(B.shape[0], jnp.float32),
            (feat, thr, internal, leaf_val))
        return margin

    return jax.shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(DATA_AXIS), P(), P(), P(), P(), P()),
        out_specs=P(DATA_AXIS), check_vma=False,
    )(B, feat, thr, internal, leaf_val, step_size)


@partial(jax.jit, static_argnames=("max_depth",))
def _gbt_proba_static(params, X, *, max_depth):
    B = bin_features(X, params["edges"])
    use_kernel = _use_tree_kernel()

    def tree_margin(f, t, it, lv):
        return _sel_table_blocked(lv, _descend(B, f, t, it, max_depth,
                                               use_kernel=use_kernel))

    margins = jax.vmap(tree_margin)(params["feat"], params["thr"],
                                    params["internal"], params["leaf_val"])
    margin = params["step_size"] * margins.sum(axis=0)
    p1 = jax.nn.sigmoid(margin)
    return jnp.stack([1 - p1, p1], axis=1)


@partial(jax.jit, static_argnames=("max_depth",))
def _gbt_ovr_proba_static(params, X, *, max_depth):
    """Multiclass gb probabilities: per-class booster margins (leading
    class axis on every tree param), class scores p_k = σ(margin_k),
    normalized — standard one-vs-rest calibration."""
    B = bin_features(X, params["edges"])
    use_kernel = _use_tree_kernel()

    def class_margin(feat, thr, internal, leaf_val):
        def tree_margin(f, t, it, lv):
            return _sel_table_blocked(lv, _descend(B, f, t, it, max_depth,
                                                   use_kernel=use_kernel))

        return jax.vmap(tree_margin)(feat, thr, internal,
                                     leaf_val).sum(axis=0)

    margins = jax.vmap(class_margin)(
        params["feat"], params["thr"], params["internal"],
        params["leaf_val"])                              # (C, n)
    p = jax.nn.sigmoid(params["step_size"] * margins).T  # (n, C)
    return p / jnp.maximum(p.sum(axis=1, keepdims=True), 1e-12)


def _run_gbt_checkpointed(runtime, ckpt, B_dev, y_dev, valid_dev, *,
                          max_depth, n_bins, n_rounds, step_size,
                          use_kernel):
    """Segment-at-a-time gb build with a checkpoint every
    ``ckpt.every`` boost rounds. The carried margin stays on device
    between segments (row-sharded); on resume it is REPLAYED from the
    checkpointed trees — the same sequential fold the training scan
    performs, so the continued fit is bit-identical to an uninterrupted
    one. Returns the stacked per-round tree params."""
    from learningorchestra_tpu import jobs
    from learningorchestra_tpu.utils import fitckpt

    mesh = runtime.mesh
    names = ("feat", "thr", "internal", "leaf_val")
    done = 0
    host: dict = {}
    margin = None
    loaded = ckpt.load()
    if loaded is not None:
        rounds_done, arrays, meta = loaded
        if 0 < rounds_done <= n_rounds and all(k in arrays
                                               for k in names):
            done = rounds_done
            host = {k: arrays[k] for k in names}
            margin = _gbt_replay_margin(
                B_dev, jnp.asarray(host["feat"]),
                jnp.asarray(host["thr"]), jnp.asarray(host["internal"]),
                jnp.asarray(host["leaf_val"]), step_size,
                max_depth=max_depth, mesh=mesh, use_kernel=use_kernel)
            fitckpt.count_resume()
            jobs.record_job_resume(ckpt.family, {
                "rounds": int(done), "of": int(n_rounds),
                "mesh_epoch": meta.get("mesh_epoch")})
        else:
            ckpt.clear()
    if margin is None:
        margin, _ = runtime.shard_rows(
            np.zeros(int(B_dev.shape[0]), np.float32))
    every = max(1, int(ckpt.every))
    while done < n_rounds:
        k = min(every, n_rounds - done)
        trees, margin = _fit_gbt_seg(
            B_dev, y_dev, valid_dev, margin, max_depth=max_depth,
            n_bins=n_bins, n_rounds=k, mesh=mesh, step_size=step_size,
            use_kernel=use_kernel)
        seg = {kk: np.asarray(a) for kk, a in zip(names, trees)}
        host = ({kk: np.concatenate([host[kk], seg[kk]])
                 for kk in names} if host else seg)
        done += k
        jobs.heartbeat()
        if done < n_rounds:
            ckpt.save(done, host)
    return tuple(jnp.asarray(host[kk]) for kk in names)


def fit_gb(runtime: MeshRuntime, X, y, num_classes, seed=0, *,
           n_rounds: int = 20, max_depth: int = 5, n_bins: int = 32,
           step_size: float = 0.1, edges=None, ckpt=None) -> TrainedModel:
    """Gradient-boosted trees. Binary is the reference-parity path (one
    booster, exactly Spark 2.4's GBTClassifier). ``num_classes > 2``
    goes BEYOND the reference (whose GBTClassifier refuses multiclass):
    one-vs-rest over the same binary builder — booster k fits labels
    ``y == k`` with identical bins/rounds, margins stack on a leading
    class axis, and probabilities are normalized sigmoid scores
    (``_gbt_ovr_proba_static``). Each booster's margin is bit-identical
    to a standalone binary fit on the same rest-labeled split (parity
    pinned in tests/test_models.py)."""
    validate_n_bins(n_bins)

    X = as_design(X)
    if edges is None:
        edges = _edge_prep(X, n_bins)["edges"]
    # Same device-side binning as _fit_cls_trees: shard X (cached), bin
    # row-locally on device, no host round-trip of the bin matrix.
    X_dev, n = runtime.shard_rows(X)
    B_dev = bin_features(X_dev, runtime.replicate(edges))
    padded_len = len(X) + (-len(X)) % runtime.mesh.shape[DATA_AXIS]
    valid_dev, _ = runtime.shard_rows(
        (np.arange(padded_len) < n).astype(np.float32))
    hparams = {"n_rounds": n_rounds, "max_depth": max_depth,
               "n_bins": n_bins, "step_size": step_size}
    use_kernel = _use_tree_kernel(runtime)
    if num_classes == 2:
        y_dev, _ = runtime.shard_rows(np.asarray(y, np.int32))
        if ckpt is not None and ckpt.enabled and n_rounds > 1:
            feat, thr, internal, leaf_val = _run_gbt_checkpointed(
                runtime, ckpt, B_dev, y_dev, valid_dev,
                max_depth=max_depth, n_bins=n_bins, n_rounds=n_rounds,
                step_size=step_size, use_kernel=use_kernel)
        else:
            feat, thr, internal, leaf_val = _fit_gbt(
                B_dev, y_dev, valid_dev, max_depth=max_depth,
                n_bins=n_bins, n_rounds=n_rounds, mesh=runtime.mesh,
                step_size=step_size, use_kernel=use_kernel)
        params = {"edges": jnp.asarray(edges), "feat": feat, "thr": thr,
                  "internal": internal, "leaf_val": leaf_val,
                  "step_size": jnp.float32(step_size)}
        return TrainedModel(
            kind="gb", params=params,
            predict_proba_fn=partial(_gbt_proba_static,
                                     max_depth=max_depth),
            num_classes=2, hparams=hparams)
    # One-vs-rest: C boosters over the SAME binned matrix (one transfer,
    # one binning program — only the 0/1 labels change per booster).
    # Mid-fit checkpointing stays off here (per-booster streams would
    # need per-class keys); the binary reference-parity path is the one
    # HIGGS-scale fits take.
    y_np = np.asarray(y, np.int32)
    per_class = []
    for k in range(num_classes):
        yk_dev, _ = runtime.shard_rows((y_np == k).astype(np.int32))
        per_class.append(_fit_gbt(
            B_dev, yk_dev, valid_dev, max_depth=max_depth, n_bins=n_bins,
            n_rounds=n_rounds, mesh=runtime.mesh, step_size=step_size,
            use_kernel=use_kernel))
        # Boosters enqueue back-to-back; fence the multi-process CPU rig
        # (no-op on TPU — stream order already aligns the collectives).
        spmd.serialize_collectives(per_class[-1])
    feat, thr, internal, leaf_val = (
        jnp.stack([pc[i] for pc in per_class]) for i in range(4))
    params = {"edges": jnp.asarray(edges), "feat": feat, "thr": thr,
              "internal": internal, "leaf_val": leaf_val,
              "step_size": jnp.float32(step_size)}
    return TrainedModel(
        kind="gb", params=params,
        predict_proba_fn=partial(_gbt_ovr_proba_static,
                                 max_depth=max_depth),
        num_classes=num_classes,
        hparams=dict(hparams, ovr_classes=num_classes))


fit_gb.host_prep = _edge_prep
