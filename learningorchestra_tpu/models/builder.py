"""ModelBuilder — the trainer service core (reference call stack §3.2).

The reference's ``SparkModelBuilder.build_model``: load train/test
collections, ``exec()`` user preprocessing, fit up to 5 classifiers
*concurrently* (ThreadPoolExecutor submitting into one FAIR-scheduled
SparkSession, model_builder.py:95,160-176), time each fit, evaluate F1 +
accuracy, and write one prediction collection per classifier whose metadata
carries the metrics and whose rows are the test set plus ``prediction`` and
``probability`` columns (with vector internals dropped,
model_builder.py:179-248).

TPU-native design: preprocessing is declarative (ops/preprocess; exec only
behind the opt-in flag); each classifier family is one jit-compiled program
(models/*), so "concurrent fits" become overlapped dispatch of XLA
executables. The sweep is PIPELINED on both execution paths:

- Single-process: every family runs on its own thread, but only
  ``max_concurrent_fits`` of them may sit in their *device phase* at a
  time (a semaphore, not the pool size, is the concurrency knob) — so
  host-side prep of one family (tree quantile edges, streamed chunk
  reads) and host-side finishing of another (metrics, prediction
  datasets, persistence) overlap device compute of a third, while the
  device working set stays bounded (five concurrently dispatched
  11M-row fits thrash HBM — measured 363 s vs 106 s sequential). On a
  multi-device mesh the device phase serializes outright: concurrent
  collective programs from different threads can interleave on the
  per-device streams and wedge (see ``_build_pipelined``).
- Multi-process pod: one dispatched round covers the whole build; the
  fit programs of every family are enqueued back-to-back with no host
  barrier between them (JAX dispatch is async), the probability passes
  follow in the same deterministic order, and all host-side finishing
  happens after the collective program completes — every process runs
  the identical device-op sequence (parallel/spmd.prep_build_job).

Each fit records ``device_s`` — dispatch through blocked completion of
its device programs — next to wall-clock, the split that separates
host/tunnel jitter from device compute (VERDICT r5 weak #1/#2). Output
contract is preserved: dataset ``<name>_<classifier>`` per classifier,
metrics in its metadata.
"""

from __future__ import annotations

import json
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from learningorchestra_tpu.catalog.store import DatasetStore
from learningorchestra_tpu.config import Settings, settings as global_settings
from learningorchestra_tpu.models.base import FitReport, Timer
from learningorchestra_tpu.models.metrics import classification_metrics
from learningorchestra_tpu.models.persistence import ModelRegistry
from learningorchestra_tpu.models.registry import get_trainer
from learningorchestra_tpu.ops import preprocess
from learningorchestra_tpu.parallel import spmd
from learningorchestra_tpu.parallel.mesh import MeshRuntime
from learningorchestra_tpu.utils import fitckpt, resources, tracing
from learningorchestra_tpu.utils.profiling import (
    device_span, device_trace, op_timer, timed)


class ModelBuilder:
    def __init__(self, store: DatasetStore, runtime: MeshRuntime,
                 cfg: Optional[Settings] = None):
        self.store = store
        self.runtime = runtime
        self.cfg = cfg or global_settings
        self.registry = ModelRegistry(self.cfg)

    # -- validation (reference model_builder.py:272-292) ---------------------

    def validate(self, train: str, test: str, classifiers: Sequence[str],
                 prediction_name: str) -> None:
        for ds_name in (train, test):
            if not self.store.exists(ds_name):
                raise KeyError(f"dataset not found: {ds_name}")
        for c in classifiers:
            get_trainer(c)  # raises ValueError on unknown name
        for c in classifiers:
            if self.store.exists(f"{prediction_name}_{c}"):
                raise ValueError(
                    f"prediction dataset already exists: {prediction_name}_{c}")

    def validate_tune(self, train: str, out_name: str, classifier: str,
                      configs: Sequence[Dict[str, Any]]) -> None:
        """Synchronous admission checks for a tune sweep — everything that
        must 4xx at the route instead of stranding an async job: missing
        dataset (404), duplicate output (ValueError → 406), and the full
        per-config hyperparameter validation (unknown names / out-of-range
        values name the offending key, models/registry.HPARAM_SPECS)."""
        from learningorchestra_tpu.models import tune as tune_mod

        if not self.store.exists(train):
            raise KeyError(f"dataset not found: {train}")
        if self.store.exists(out_name):
            raise ValueError(f"tune dataset already exists: {out_name}")
        tune_mod.validate_population(classifier, configs)

    # -- the main path -------------------------------------------------------

    def build(self, train: str, test: str, prediction_name: str,
              classifiers: Sequence[str], label: str,
              steps: Sequence[Dict[str, Any]] = (),
              preprocessor_code: Optional[str] = None,
              hparams: Optional[Dict[str, Dict[str, Any]]] = None,
              existing: bool = False) -> List[FitReport]:
        """Fit all requested classifiers; returns per-classifier reports.

        Synchronous core (the reference's POST /models also blocks until all
        fits finish, SURVEY.md §3.2); the serving layer may wrap it in a job.
        ``existing=True`` means the caller already created the prediction
        datasets (the async route does, metadata-first, so pollers can see
        them — and their failure flags — from the moment of submission).
        """
        train_ds = self.store.get(train)
        test_ds = self.store.get(test)
        hparams = hparams or {}
        multi = spmd.is_multiprocess()
        ck_on = int(self.cfg.fit_ckpt_rounds) > 0
        # Read-pipeline traffic of this whole build (streamed-fit scans,
        # ChunkedDesign shard reads, double-buffered device feeding) —
        # recorded on the job profile so a cache/prefetch regression
        # shows up per-job before it shows up as wall-clock.
        from learningorchestra_tpu.catalog import readpipe

        rp0 = readpipe.snapshot()

        pp_meta = None
        streamed = False
        design_t0 = time.monotonic()
        if preprocessor_code is not None:
            if multi:
                raise PermissionError(
                    "exec preprocessing cannot run SPMD (workers rebuild "
                    "inputs deterministically); use declarative steps")
            if not self.cfg.allow_exec_preprocessing:
                raise PermissionError(
                    "exec preprocessing is disabled; enable "
                    "LO_TPU_ALLOW_EXEC or use declarative steps")
            X_train, y_train, X_test, y_test = preprocess.exec_preprocess(
                preprocessor_code, train_ds, test_ds, label, cfg=self.cfg)
            feature_fields = [f"f{i}" for i in range(X_train.shape[1])]
        elif (self.cfg.stream_design or train_ds.over_budget
                or test_ds.over_budget):
            # Shard-local streamed path: the design matrix never exists
            # fully on any host — state is fitted with streaming passes
            # and each device shard materializes only its own row range
            # (preprocess.ChunkedDesign → mesh.shard_chunked). This is
            # how fits scale past one host's RAM, the reference's
            # executor residency model (model_builder.py:200). No memo:
            # memoization consolidates, which is exactly what this path
            # must never do.
            streamed = True
            fit_prof: Dict[str, Any] = {}
            # Pass-boundary checkpoints for the streamed state fit: a
            # retried build resumes the fitting scans instead of
            # re-reading the dataset from pass zero. Safe under SPMD
            # too — only process 0 ever FITS state (workers receive it
            # pinned in the dispatched spec).
            design_ckpt = fitckpt.context(
                self.cfg, dataset=train, family="design",
                config={"label": label, "steps": list(steps)},
                snapshot="") if ck_on else None
            X_train, y_train, feature_fields, state = \
                preprocess.design_matrix_streamed(train_ds, label, steps,
                                                  profile=fit_prof,
                                                  ckpt=design_ckpt)
            X_test, y_test, _, _ = preprocess.design_matrix_streamed(
                test_ds, label, steps, state=state,
                feature_fields=feature_fields)
            if fit_prof:
                # Surface the streamed-fit scan count on the job record:
                # the fused fitting passes (ops/preprocess) exist to keep
                # this at ~2 for the default pipeline, and a regression
                # shows up here before it shows up as Criteo-scale IO.
                from learningorchestra_tpu.jobs import record_job_profile

                record_job_profile(**fit_prof)
            pp_meta = {"steps": list(steps), "state": state,
                       "feature_fields": feature_fields, "label": label}
        else:
            # Memoized per dataset-snapshot: repeat builds on the same data
            # reuse the identical X arrays, so the runtime's transfer cache
            # keeps the on-device copies (re-transferring an 11M-row matrix
            # over PCIe per build would dwarf the fits themselves).
            steps_key = json.dumps(list(steps), sort_keys=True, default=str)
            X_train, y_train, feature_fields, state = train_ds.memo(
                ("design", label, steps_key),
                lambda: preprocess.design_matrix(train_ds, label, steps))
            X_test, y_test, _, _ = test_ds.memo(
                ("design_t", label, steps_key, tuple(feature_fields)),
                lambda: preprocess.design_matrix(
                    test_ds, label, steps, state=state,
                    feature_fields=feature_fields),
                token=state)
            # Everything needed to apply the identical pipeline to future
            # datasets when the fitted model is re-served (persistence.py).
            pp_meta = {"steps": list(steps), "state": state,
                       "feature_fields": feature_fields, "label": label}
        # One span covers whichever design-matrix path ran (exec /
        # streamed / memoized-resident): explicit duration, no reindent
        # of the three-way branch above.
        tracing.record_span(
            "design.build", time.monotonic() - design_t0,
            attrs={"train": train, "test": test, "streamed": streamed,
                   "rows": int(len(X_train))})
        if y_train is None:
            raise ValueError(f"label field {label!r} not in {train!r}")
        num_classes = int(max(int(y_train.max()) + 1,
                              2 if y_test is None else int(y_test.max()) + 1))

        # Create all output datasets first (metadata-first protocol), so
        # pollers see them immediately with finished=false.
        if not existing:
            for c in classifiers:
                self.store.create(f"{prediction_name}_{c}", parent=test,
                                  extra={"classifier": c, "label": label})

        # Mid-fit checkpoint contexts (utils/fitckpt.py), one per family
        # with natural segment boundaries. Keyed on everything that
        # could change the fit's arithmetic — hparams, label/steps,
        # row snapshot, mesh shape (psum summation grouping) — so a
        # resume under ANY changed configuration starts fresh. The
        # single-process paths only: the dispatched SPMD round must run
        # one identical program on every process, and a mid-fit resume
        # decision made from local disk state could diverge between
        # processes (job-level retry + the design-state checkpoint
        # above still cover the pod path).
        ckpt_ctxs: Dict[str, Any] = {}
        if ck_on and not multi:
            for c in classifiers:
                if c not in fitckpt.SEGMENTED_FAMILIES:
                    continue
                ckpt_ctxs[c] = fitckpt.context(
                    self.cfg, dataset=train, family=c,
                    config={"family": c, "hparams": hparams.get(c, {}),
                            "num_classes": num_classes, "label": label,
                            "steps": list(steps), "streamed": streamed,
                            "mesh": dict(self.runtime.mesh.shape)},
                    snapshot=f"rows={int(len(X_train))}")

        def prep_fit(c: str):
            """One family's host-side prep (the trainer's ``host_prep``
            hook — e.g. tree quantile edges from host/chunk-store reads).
            Pure host work, runs OUTSIDE the device gate so it overlaps
            other families' device compute. Returns (extra_kwargs,
            prep_s)."""
            trainer = get_trainer(c)
            hp = hparams.get(c, {})
            with Timer() as tp:
                prep = getattr(trainer, "host_prep", None)
                extra = prep(X_train, **hp) if prep is not None else {}
            return extra, tp.elapsed

        def dispatch_fit(c: str, extra: Dict[str, Any]):
            """The family's fit-program dispatch. JAX dispatch is
            asynchronous, so this returns as soon as the fit's device
            programs are enqueued — the device may still be computing.
            (The checkpointed families' segmented drivers block per
            segment — pulling params to host at each boundary IS the
            checkpoint.)"""
            kw = dict(hparams.get(c, {}), **extra)
            if c in ckpt_ctxs:
                kw["ckpt"] = ckpt_ctxs[c]
            return get_trainer(c)(self.runtime, X_train, y_train,
                                  num_classes, **kw)

        def collect_fit(c: str, model, pre_s: float):
            """The family's probability pass, blocked to completion (the
            host gather inside ``predict_proba`` consumes the fitted
            params, so its completion bounds the fit's device programs
            too). ``pre_s`` is everything before this span — host prep
            plus the trainer's dispatch wall time (which includes e.g.
            the design matrix's host→device transfer, a real per-family
            cost a serialized sweep would pay). Returns (probs,
            device_s)."""
            probs, device_s = device_span(
                lambda: model.predict_proba(self.runtime, X_test),
                name=f"fit.{c}.device")
            op_timer.record(f"fit.{c}", pre_s + device_s)
            op_timer.record(f"fit.{c}.device", device_s)
            # Progress mark for the job watchdog: a family's device
            # programs ran to completion — the build is alive.
            from learningorchestra_tpu import jobs

            jobs.heartbeat()
            return probs, device_s

        def finish_host(c: str, model, probs, fit_time: float,
                        device_s: float) -> FitReport:
            """Metrics, model persistence, prediction dataset — everything
            host-side after the device programs complete. ``fit_time`` is
            the family's per-fit time: on the single-process pipeline,
            prep + dispatch + device spans (excluding scheduler waits,
            so the sum estimates the serialized sweep); on the pod
            batched round, the family's prep-to-probabilities wall span
            (spans overlap across families, so build wall-clock below
            their sum is the overlap evidence)."""
            preds = np.argmax(probs, axis=1)
            report = FitReport(kind=c, fit_time=fit_time)
            if y_test is not None and (y_test >= 0).all():
                report.metrics = classification_metrics(
                    y_test, preds, num_classes)
            report.metrics["device_s"] = round(device_s, 6)
            if self.cfg.persist_models:
                # Best-effort: a persistence failure must not discard an
                # otherwise successful fit's predictions; surface it in the
                # persisted metrics instead.
                try:
                    self.registry.save(f"{prediction_name}_{c}", model,
                                       metrics=report.metrics,
                                       preprocess=pp_meta)
                except Exception as exc:  # noqa: BLE001 — isolation boundary
                    report.metrics["persist_error"] = (
                        f"{type(exc).__name__}: {exc}")
            self._save_predictions(f"{prediction_name}_{c}", test_ds,
                                   preds, probs, report)
            # The family reached its terminal outputs: its mid-fit
            # checkpoint stream is superseded (a retry of THIS family
            # can no longer happen — the retry machinery refits only
            # families whose datasets failed), so reclaim the disk.
            if c in ckpt_ctxs:
                ckpt_ctxs[c].clear()
            from learningorchestra_tpu import jobs

            jobs.heartbeat()
            return report

        def fail_report(c: str, exc: Exception) -> FitReport:
            self.store.fail(f"{prediction_name}_{c}",
                            f"{type(exc).__name__}: {exc}")
            return FitReport(kind=c, fit_time=0.0,
                             metrics={"error": str(exc)})

        stages = (prep_fit, dispatch_fit, collect_fit, finish_host,
                  fail_report)
        if multi:
            reports = self._build_dispatched(
                train, test, prediction_name, classifiers, label, steps,
                hparams, X_train, X_test, state, feature_fields, streamed,
                *stages)
        else:
            reports = self._build_pipelined(classifiers, *stages)
        device_s = {r.kind: r.metrics["device_s"] for r in reports
                    if "device_s" in r.metrics}
        rp1 = readpipe.snapshot()
        rp_delta = {k: rp1[k] - rp0[k]
                    for k in ("cache_hits", "cache_misses",
                              "prefetch_stalls", "prefetched_chunks")}
        if device_s or any(rp_delta.values()):
            from learningorchestra_tpu.jobs import record_job_profile

            prof: Dict[str, Any] = {}
            if device_s:
                prof["fit_device_s"] = device_s
            if any(rp_delta.values()):
                prof["read_pipeline"] = rp_delta
            record_job_profile(**prof)
        if streamed and ck_on and all("error" not in r.metrics
                                      for r in reports):
            # Every family completed: the design-state checkpoint has no
            # retry left to serve — reclaim it (a failed family keeps it
            # so the retry skips the fitted passes).
            design_ckpt.clear()
        return reports

    def _build_pipelined(self, classifiers, prep_fit, dispatch_fit,
                         collect_fit, finish_host,
                         fail_report) -> List[FitReport]:
        """Single-process pipelined sweep (reference: 5-way
        ThreadPoolExecutor + FAIR pool, model_builder.py:95,160-176).

        Every family gets a thread; a semaphore — not the pool size —
        caps how many sit in their device phase, so host prep and host
        finishing of other families overlap device compute while the
        device working set stays bounded. One device trace spans the
        whole build (JAX allows a single active trace per process, so
        per-fit tracing would collide).

        On a MULTI-DEVICE mesh the device phase serializes outright
        (gate of 1) regardless of ``max_concurrent_fits``: every fit and
        probability program carries collectives (psum/all-gather over
        the data axis), and two such programs dispatched from different
        threads can enqueue onto the per-device execution streams in
        different orders — the same cross-program interleaving deadlock
        ``dispatch_guard`` exists to prevent across processes, observed
        as a real rendezvous wedge on the simulated 8-device CPU mesh.
        Host-side prep and finishing still pipeline against device
        compute, which is where the overlap win lives; on a single
        device (the production single-chip path) programs carry no
        cross-device rendezvous and up to ``max_concurrent_fits`` may
        dispatch concurrently to keep the device queue fed."""
        n_dev = int(np.prod(list(self.runtime.mesh.shape.values())))
        gate = threading.BoundedSemaphore(
            max(1, int(self.cfg.max_concurrent_fits)) if n_dev == 1 else 1)
        # Pool threads carry no ambient trace OR job record — re-attach
        # both so each family's spans nest under the job/request span
        # (the Gantt view of the PR-3 overlap: fit.<c> spans overlap in
        # wall time; their host_prep/device/finish children show which
        # phase overlapped which) and its resource watermarks
        # (family_phase, device_span) land on the right job's profile.
        from learningorchestra_tpu import jobs

        parent_ctx = tracing.current()
        job_rec = jobs.current_job_record()

        def fit_guarded(c: str) -> FitReport:
            with tracing.attach(parent_ctx), \
                    jobs.attach_job_record(job_rec):
                try:
                    # The except sits OUTSIDE the span: a failing family
                    # must escape it so the fit.<c> span records
                    # status=error — the trace view and the report may
                    # never disagree about whether a family succeeded.
                    with tracing.span(f"fit.{c}", family=c):
                        extra, prep_s = prep_fit(c)   # outside the gate
                        tracing.record_span(f"fit.{c}.host_prep", prep_s)
                        with gate:                    # device phase
                            # family_phase attributes the fit program's
                            # compile seconds to this family; the
                            # probability pass's compiles land via
                            # collect_fit's device_span. The compile
                            # counter is process-global, so resources.
                            # device_phase attributes a window's delta
                            # only when no other phase overlapped it
                            # (a gate >1 admits concurrent families) —
                            # overlapped windows record peaks only,
                            # never a double-counted compile_s.
                            with Timer() as td, resources.family_phase(c):
                                model = dispatch_fit(c, extra)
                            pre_s = prep_s + td.elapsed
                            probs, device_s = collect_fit(c, model, pre_s)
                        # fit_time = prep + dispatch + device spans, no
                        # scheduler waits: the per-family sum estimates
                        # the serialized sweep, and the gap to build
                        # wall-clock IS the overlap won.
                        with Timer() as tf:
                            report = finish_host(c, model, probs,
                                                 pre_s + device_s,
                                                 device_s)
                        tracing.record_span(f"fit.{c}.finish", tf.elapsed)
                        return report
                except Exception as exc:  # noqa: BLE001 — per-model bound
                    return fail_report(c, exc)

        with device_trace(self.cfg), ThreadPoolExecutor(
                max_workers=max(len(classifiers), 1)) as pool:
            futures = {c: pool.submit(fit_guarded, c) for c in classifiers}
            return [fut.result() for fut in futures.values()]

    def _build_dispatched(self, train, test, prediction_name, classifiers,
                          label, steps, hparams, X_train, X_test, state,
                          feature_fields, streamed, prep_fit, dispatch_fit,
                          collect_fit, finish_host,
                          fail_report) -> List[FitReport]:
        """Multi-process SPMD: broadcast ONE build spec covering every
        classifier, then run the whole sweep as a single batched dispatch
        round. The fit programs of every family are enqueued back-to-back
        with no host barrier between them (JAX dispatch is async — family
        k+1's host prep runs while family k computes), the probability
        passes follow in the same deterministic order, and all host-side
        finishing (metrics, prediction datasets, persistence) runs after
        the collective program — exactly the worker-side device-op
        sequence (parallel/spmd.prep_build_job), so collective-program
        order is identical on every process. Per-family failures are
        caught and the family's remaining device ops skipped identically
        everywhere (deterministic inputs ⇒ deterministic failures),
        preserving alignment.

        Row counts pin the snapshot: a concurrent ingest commit between
        the save and a worker's load must not change the collective
        program's shapes (workers truncate to these counts). State +
        feature fields pin the preprocessing snapshot too: a worker
        refitting stats over a longer dataset would otherwise build
        numerically different (or wider) matrices than process 0's."""
        fitted: Dict[str, Any] = {}
        results: Dict[str, Any] = {}
        with device_trace(self.cfg), spmd.dispatch_job(
                self.store, (train, test), {
                    "op": "build", "train": train, "test": test,
                    "label": label, "steps": list(steps),
                    "classifiers": list(classifiers),
                    "hparams": hparams,
                    "n_train": int(len(X_train)),
                    "n_test": int(len(X_test)),
                    "state": spmd.jsonable_state(state),
                    "feature_fields": list(feature_fields),
                    "streamed": streamed,
                },
                outputs=[f"{prediction_name}_{c}" for c in classifiers]):
            for c in classifiers:           # phase 1: enqueue every fit
                t0 = time.time()
                try:
                    extra, prep_s = prep_fit(c)
                    tracing.record_span(f"fit.{c}.host_prep", prep_s)
                    # Same compile-attribution split as the pipelined
                    # path: fit-program compiles here, the probability
                    # pass's via collect_fit's device_span. This loop is
                    # sequential, so these windows never overlap and
                    # always attribute.
                    with resources.family_phase(c):
                        model = dispatch_fit(c, extra)
                        # No-op on TPU (stream order keeps back-to-back
                        # programs aligned); fences the CPU test rig,
                        # whose in-flight programs execute concurrently.
                        spmd.serialize_collectives(model.params)
                    fitted[c] = (model, time.time() - t0, t0)
                except Exception as exc:  # noqa: BLE001 — per-model boundary
                    fitted[c] = exc
            for c in classifiers:           # phase 2: probability passes
                if isinstance(fitted[c], Exception):
                    results[c] = fitted[c]
                    continue
                model, pre_s, t0 = fitted[c]
                try:
                    probs, device_s = collect_fit(c, model, pre_s)
                    # Per-fit time = dispatch-to-probabilities wall span.
                    # Families' spans overlap (fits enqueue back-to-back;
                    # every span covers the shared device region), so the
                    # build wall-clock landing BELOW their sum is the
                    # direct evidence the round pipelines — under the old
                    # serialized fit-per-guard-hold loop the spans were
                    # disjoint and summed to wall minus overhead.
                    results[c] = (model, probs, time.time() - t0,
                                  device_s)
                except Exception as exc:  # noqa: BLE001 — per-model boundary
                    results[c] = exc
        reports = []
        for c in classifiers:               # phase 3: host finishing
            res = results[c]
            if isinstance(res, Exception):
                reports.append(fail_report(c, res))
                continue
            try:
                with Timer() as tf:
                    reports.append(finish_host(c, *res))
                tracing.record_span(f"fit.{c}.finish", tf.elapsed)
            except Exception as exc:  # noqa: BLE001 — per-model boundary
                reports.append(fail_report(c, exc))
        return reports

    def predict(self, model_name: str, dataset: str, out_name: str,
                existing: bool = False) -> None:
        """Serve a persisted model on a stored dataset: apply its train-time
        preprocessing state, predict, and write a prediction dataset — the
        re-use path the reference lacks entirely (models were discarded,
        reference model_builder.py:227-248).

        ``existing=True``: the caller (the async route) already created the
        output dataset metadata-first, so a crash mid-predict is pollable.
        """
        man, model = self.registry.load(model_name)
        pp = man.get("preprocess")
        if pp is None:
            raise ValueError(
                f"model {model_name} was exec-preprocessed; it carries no "
                "reproducible preprocessing state to apply to new datasets")
        ds = self.store.get(dataset)
        if not existing:
            self.store.create(out_name, parent=dataset,
                              extra={"model": model_name, "kind": man["kind"]})
        streamed = ds.over_budget or self.cfg.stream_design
        with timed("model_predict"), device_trace(self.cfg):
            if streamed:
                X, _, _, _ = preprocess.design_matrix_streamed(
                    ds, pp["label"], pp["steps"], state=pp["state"],
                    feature_fields=pp["feature_fields"], need_y=False)
            else:
                X, _, _, _ = preprocess.design_matrix(
                    ds, pp["label"], pp["steps"], state=pp["state"],
                    feature_fields=pp["feature_fields"])
            with spmd.dispatch_job(
                    self.store, (dataset,),
                    {"op": "predict", "model": model_name,
                     "dataset": dataset, "n_rows": int(len(X)),
                     "streamed": streamed},
                    outputs=(out_name,)):
                probs = model.predict_proba(self.runtime, X)
        preds = np.argmax(probs, axis=1)
        self._save_predictions(out_name, ds, preds, probs,
                               FitReport(kind=man["kind"], fit_time=0.0))

    # -- device-resident hyperparameter search (models/tune.py) --------------

    def tune(self, train: str, out_name: str, classifier: str,
             configs: Sequence[Dict[str, Any]], label: str,
             steps: Sequence[Dict[str, Any]] = (),
             folds: Optional[int] = None, rungs: Optional[int] = None,
             promote: bool = False,
             existing: bool = False) -> Dict[str, Any]:
        """Run one vmapped hyperparameter sweep over ``configs`` of a
        single family against the resident design of ``train``; the
        leaderboard (per-config fold scores, fit seconds, rung survival,
        winner) lands in ``out_name``'s metadata and is returned.

        ``promote=True`` refits the winning config on ALL rows (CV fold
        masking off) and persists it under ``out_name`` in the trained-
        model registry, so the sweep's product is directly servable.
        ``existing=True`` means the async route already created the
        marker dataset metadata-first.
        """
        from learningorchestra_tpu.models import tune as tune_mod

        train_ds = self.store.get(train)
        if self.cfg.stream_design or train_ds.over_budget:
            # The member-axis fold masks multiply against ONE resident
            # (n, d) design; a streamed design never materializes, so
            # there is nothing to mask.
            raise ValueError(
                "tune sweeps need a resident design matrix; streamed "
                "designs are fit-only")
        steps_key = json.dumps(list(steps), sort_keys=True, default=str)
        with tracing.span("design.build", train=train):
            X_train, y_train, feature_fields, state = train_ds.memo(
                ("design", label, steps_key),
                lambda: preprocess.design_matrix(train_ds, label, steps))
        if y_train is None:
            raise ValueError(f"label field {label!r} not in {train!r}")
        num_classes = max(2, int(y_train.max()) + 1)
        pp_meta = {"steps": list(steps), "state": state,
                   "feature_fields": feature_fields, "label": label}

        if not existing:
            self.store.create(out_name, parent=train,
                              extra={"classifier": classifier,
                                     "label": label, "tune": True})
        ck_on = int(self.cfg.fit_ckpt_rounds) > 0
        ckpt = None
        if ck_on and not spmd.is_multiprocess():
            # Rung-boundary checkpoints: keyed on everything that changes
            # the sweep's arithmetic or orchestration (configs, folds,
            # rungs, mesh shape), so a resume under ANY changed setup
            # starts fresh instead of splicing incompatible state.
            ckpt = fitckpt.context(
                self.cfg, dataset=train, family=f"tune_{classifier}",
                config={"family": classifier, "configs": list(configs),
                        "folds": folds, "rungs": rungs, "label": label,
                        "steps": list(steps), "num_classes": num_classes,
                        "mesh": dict(self.runtime.mesh.shape)},
                snapshot=f"rows={int(len(X_train))}")
        try:
            with device_trace(self.cfg), timed("tune"), \
                    tracing.span("tune.sweep", family=classifier,
                                 configs=len(configs)):
                board = tune_mod.sweep(
                    self.runtime, X_train, y_train, num_classes,
                    classifier, configs, cfg=self.cfg,
                    folds=folds, rungs=rungs, ckpt=ckpt)
        except Exception as exc:
            self.store.fail(out_name, f"{type(exc).__name__}: {exc}")
            raise

        if promote:
            # Winner promotion: one full-data fit of the best config —
            # the same trainer entry point as build, so host_prep hooks
            # (tree quantile edges) and registry manifests match.
            hp = dict(board["winner"]["config"])
            trainer = get_trainer(classifier)
            prep = getattr(trainer, "host_prep", None)
            extra = prep(X_train, **hp) if prep is not None else {}
            with timed("tune.promote"), resources.family_phase(classifier):
                model = trainer(self.runtime, X_train, y_train,
                                num_classes, **dict(hp, **extra))
            if self.cfg.persist_models:
                try:
                    self.registry.save(
                        out_name, model,
                        metrics={"mean_score":
                                 board["winner"]["mean_score"],
                                 "tuned": True},
                        preprocess=pp_meta)
                    board["promoted"] = out_name
                except Exception as exc:  # noqa: BLE001 — best-effort
                    board["promote_error"] = (
                        f"{type(exc).__name__}: {exc}")

        self.store.finish(out_name, tune=board)
        from learningorchestra_tpu import jobs

        jobs.heartbeat()
        return board

    def _save_predictions(self, name: str, test_ds, preds: np.ndarray,
                          probs: np.ndarray, report: FitReport) -> None:
        """Write the prediction dataset: original test rows + prediction +
        probability list; metrics into metadata (reference
        model_builder.py:191-248 drops 'features'/'rawPrediction' and
        converts the probability vector to a plain list)."""
        ds = self.store.get(name)
        n = len(preds)

        def prob_objcol(block_probs: np.ndarray) -> np.ndarray:
            # Object array of Python lists (np.array(list-of-lists,
            # dtype=object) would build a 2-D array instead).
            out = np.empty(len(block_probs), dtype=object)
            for i, p in enumerate(block_probs):
                out[i] = [float(x) for x in p]
            return out

        if test_ds.over_budget or self.cfg.stream_design:
            # Out-of-core test set (or forced streaming): write the
            # prediction dataset in row blocks instead of consolidating
            # the parent — the same predicate as every other
            # streamed/resident decision, so LO_TPU_STREAM_DESIGN never
            # re-introduces the O(dataset) host spike it exists to avoid.
            block = 1 << 18
            for off in range(0, n, block):
                stop = min(off + block, n)
                cols = test_ds.read_rows(None, off, stop)
                cols["prediction"] = preds[off:stop].astype(np.int64)
                cols["probability"] = prob_objcol(probs[off:stop])
                ds.append_columns(cols)
        else:
            cols = {f: test_ds.columns[f] for f in test_ds.metadata.fields}
            cols["prediction"] = preds.astype(np.int64)
            cols["probability"] = prob_objcol(probs)
            ds.append_columns(cols)
        self.store.finish(
            name,
            fit_time=report.fit_time,
            **{k: v for k, v in report.metrics.items()})
