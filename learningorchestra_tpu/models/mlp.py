"""MLP classifier trainer ("mlp") — the framework's flagship model.

No reference analogue (the reference's zoo stops at classical pyspark.ml
families, model_builder.py:152-158); this is the TPU-idiomatic extension the
rebuild adds: a two-layer perceptron whose hidden dimension is sharded over
the mesh *model* axis while rows shard over the *data* axis — genuine
dp×tp 2-D parallelism. Parameter shardings are declared with
``NamedSharding``; XLA partitions the matmuls onto the MXU and inserts the
psum for the row-wise loss reduction and the hidden-dim contraction
(tensor-parallel W2 @ h), so the same program runs one chip or a full mesh.
``__graft_entry__.dryrun_multichip`` compiles this trainer's full train step
over an N-device mesh.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from learningorchestra_tpu.models.base import TrainedModel, as_design
from learningorchestra_tpu.parallel.mesh import (
    DATA_AXIS, MODEL_AXIS, MeshRuntime)


def init_params(key, d: int, hidden: int, num_classes: int):
    k1, k2 = jax.random.split(key)
    scale1 = jnp.sqrt(2.0 / d)
    scale2 = jnp.sqrt(2.0 / hidden)
    return {
        "W1": scale1 * jax.random.normal(k1, (d, hidden), jnp.float32),
        "b1": jnp.zeros((hidden,), jnp.float32),
        "W2": scale2 * jax.random.normal(k2, (hidden, num_classes),
                                         jnp.float32),
        "b2": jnp.zeros((num_classes,), jnp.float32),
        "mu": jnp.zeros((d,), jnp.float32),
        "sigma": jnp.ones((d,), jnp.float32),
    }


def param_specs() -> dict:
    """PartitionSpecs declaring the tensor-parallel layout: hidden dim over
    the model axis (Megatron-style column→row parallel pair)."""
    return {
        "W1": P(None, MODEL_AXIS), "b1": P(MODEL_AXIS),
        "W2": P(MODEL_AXIS, None), "b2": P(),
        "mu": P(), "sigma": P(),
    }


def forward(params, X):
    Xs = ((X - params["mu"]) / params["sigma"]).astype(jnp.bfloat16)
    h = Xs @ params["W1"].astype(jnp.bfloat16)
    h = jax.nn.relu(h.astype(jnp.float32) + params["b1"])
    logits = (h.astype(jnp.bfloat16)
              @ params["W2"].astype(jnp.bfloat16)).astype(jnp.float32)
    return logits + params["b2"]


def loss_fn(params, X, y, mask, l2):
    logits = forward(params, X)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, y[:, None], axis=1)[:, 0]
    data = jnp.sum(nll * mask) / jnp.sum(mask)
    reg = l2 * (jnp.sum(params["W1"] ** 2) + jnp.sum(params["W2"] ** 2))
    return data + reg


def make_train_step(opt):
    def train_step(params, opt_state, X, y, mask, l2):
        loss, grads = jax.value_and_grad(loss_fn)(params, X, y, mask, l2)
        updates, opt_state = opt.update(grads, opt_state)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss
    return train_step


def fit(runtime: MeshRuntime, X: np.ndarray, y: np.ndarray,
        num_classes: int, seed: int = 0, *, hidden: int = 256,
        iters: int = 300, lr: float = 1e-2, l2: float = 1e-4) -> TrainedModel:

    mesh = runtime.mesh
    X = as_design(X)
    X_dev, n = runtime.shard_rows(X)
    if isinstance(X, np.ndarray):
        mu = X.mean(axis=0).astype(np.float32)
        sigma = np.where(X.std(axis=0) < 1e-7, 1.0, X.std(axis=0)).astype(
            np.float32)
    else:
        # Lazy design (shard-local loading): the full matrix never exists
        # on the host, so compute the identical masked stats on device
        # (logistic's two-pass psum reduction).
        from learningorchestra_tpu.models.logistic import _device_stats

        mu, sigma = _device_stats(X_dev, runtime.replicate(np.int32(n)),
                                  mesh=mesh)
    # Hidden dim must divide the model axis; round up.
    m = mesh.shape[MODEL_AXIS]
    hidden = ((hidden + m - 1) // m) * m

    params = init_params(jax.random.PRNGKey(seed), X.shape[1], hidden,
                         num_classes)
    params["mu"], params["sigma"] = jnp.asarray(mu), jnp.asarray(sigma)
    specs = param_specs()
    params = {k: jax.device_put(v, NamedSharding(mesh, specs[k]))
              for k, v in params.items()}

    y_dev, _ = runtime.shard_rows(np.asarray(y, np.int32))
    mask_dev, _ = runtime.shard_rows(
        (np.arange(len(X_dev)) < n).astype(np.float32))

    opt = optax.adam(lr)
    opt_state = opt.init(params)
    train_step = make_train_step(opt)

    @partial(jax.jit, static_argnames=("iters",))
    def run(params, opt_state, X, y, mask, l2, *, iters):
        def body(carry, _):
            params, opt_state = carry
            params, opt_state, loss = train_step(
                params, opt_state, X, y, mask, l2)
            return (params, opt_state), loss
        (params, opt_state), losses = jax.lax.scan(
            body, (params, opt_state), None, length=iters)
        return params, losses

    params, _ = run(params, opt_state, X_dev, y_dev, mask_dev,
                    runtime.replicate(np.float32(l2)), iters=iters)
    return TrainedModel(kind="mlp", params=params,
                        predict_proba_fn=_predict_proba,
                        num_classes=num_classes,
                        hparams={"hidden": hidden, "iters": iters, "lr": lr})


@jax.jit
def _predict_proba(params, X):
    return jax.nn.softmax(forward(params, X), axis=-1)
