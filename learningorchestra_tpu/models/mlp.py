"""MLP classifier trainer ("mlp") — the framework's flagship model.

No reference analogue (the reference's zoo stops at classical pyspark.ml
families, model_builder.py:152-158); this is the TPU-idiomatic extension the
rebuild adds: a two-layer perceptron whose hidden dimension is sharded over
the mesh *model* axis while rows shard over the *data* axis — genuine
dp×tp 2-D parallelism. Parameter shardings are declared with
``NamedSharding``; XLA partitions the matmuls onto the MXU and inserts the
psum for the row-wise loss reduction and the hidden-dim contraction
(tensor-parallel W2 @ h), so the same program runs one chip or a full mesh.
``__graft_entry__.dryrun_multichip`` compiles this trainer's full train step
over an N-device mesh.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from learningorchestra_tpu.models.base import TrainedModel, as_design
from learningorchestra_tpu.parallel.mesh import (
    DATA_AXIS, MODEL_AXIS, MeshRuntime)


def init_params(key, d: int, hidden: int, num_classes: int):
    k1, k2 = jax.random.split(key)
    scale1 = jnp.sqrt(2.0 / d)
    scale2 = jnp.sqrt(2.0 / hidden)
    return {
        "W1": scale1 * jax.random.normal(k1, (d, hidden), jnp.float32),
        "b1": jnp.zeros((hidden,), jnp.float32),
        "W2": scale2 * jax.random.normal(k2, (hidden, num_classes),
                                         jnp.float32),
        "b2": jnp.zeros((num_classes,), jnp.float32),
        "mu": jnp.zeros((d,), jnp.float32),
        "sigma": jnp.ones((d,), jnp.float32),
    }


def param_specs() -> dict:
    """PartitionSpecs declaring the tensor-parallel layout: hidden dim over
    the model axis (Megatron-style column→row parallel pair)."""
    return {
        "W1": P(None, MODEL_AXIS), "b1": P(MODEL_AXIS),
        "W2": P(MODEL_AXIS, None), "b2": P(),
        "mu": P(), "sigma": P(),
    }


def forward(params, X):
    Xs = ((X - params["mu"]) / params["sigma"]).astype(jnp.bfloat16)
    h = Xs @ params["W1"].astype(jnp.bfloat16)
    h = jax.nn.relu(h.astype(jnp.float32) + params["b1"])
    logits = (h.astype(jnp.bfloat16)
              @ params["W2"].astype(jnp.bfloat16)).astype(jnp.float32)
    return logits + params["b2"]


def loss_fn(params, X, y, mask, l2):
    logits = forward(params, X)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, y[:, None], axis=1)[:, 0]
    data = jnp.sum(nll * mask) / jnp.sum(mask)
    reg = l2 * (jnp.sum(params["W1"] ** 2) + jnp.sum(params["W2"] ** 2))
    return data + reg


def make_train_step(opt):
    def train_step(params, opt_state, X, y, mask, l2):
        loss, grads = jax.value_and_grad(loss_fn)(params, X, y, mask, l2)
        updates, opt_state = opt.update(grads, opt_state)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss
    return train_step


def _ckpt_arrays(params, opt_state):
    """Flatten (params, opt_state) into a flat name→ndarray dict for the
    fit-checkpoint store; ``_ckpt_restore`` inverts it against template
    trees (same structure by construction — the same ``opt.init`` over
    the same param tree)."""
    out = {f"p.{k}": np.asarray(v) for k, v in params.items()}
    leaves = jax.tree_util.tree_leaves(opt_state)
    out.update({f"o.{i}": np.asarray(v) for i, v in enumerate(leaves)})
    return out


def _ckpt_restore(arrays, mesh, specs, opt):
    """Rebuild device-placed (params, opt_state) from checkpointed host
    arrays: params land on their declared tensor-parallel shardings,
    and each optimizer leaf lands on the sharding a fresh ``opt.init``
    would give it (adam's moments mirror the params' layouts)."""
    params = {k[2:]: jax.device_put(v, NamedSharding(mesh, specs[k[2:]]))
              for k, v in arrays.items() if k.startswith("p.")}
    template = opt.init(params)
    tdef = jax.tree_util.tree_structure(template)
    tleaves = jax.tree_util.tree_leaves(template)
    loaded = [arrays[f"o.{i}"] for i in range(len(tleaves))]
    # Mesh-sharded template leaves (adam moments mirror the params'
    # NamedShardings) get their layout back explicitly; scalar state
    # (step count) stays uncommitted exactly like a fresh opt.init's —
    # committing it to one device would conflict with the mesh-placed
    # params at the jit boundary.
    placed = [jax.device_put(v, t.sharding)
              if isinstance(getattr(t, "sharding", None), NamedSharding)
              else jnp.asarray(v)
              for v, t in zip(loaded, tleaves)]
    return params, jax.tree_util.tree_unflatten(tdef, placed)


def fit(runtime: MeshRuntime, X: np.ndarray, y: np.ndarray,
        num_classes: int, seed: int = 0, *, hidden: int = 256,
        iters: int = 300, lr: float = 1e-2, l2: float = 1e-4,
        ckpt=None) -> TrainedModel:

    mesh = runtime.mesh
    X = as_design(X)
    X_dev, n = runtime.shard_rows(X)
    if isinstance(X, np.ndarray):
        mu = X.mean(axis=0).astype(np.float32)
        sigma = np.where(X.std(axis=0) < 1e-7, 1.0, X.std(axis=0)).astype(
            np.float32)
    else:
        # Lazy design (shard-local loading): the full matrix never exists
        # on the host, so compute the identical masked stats on device
        # (logistic's two-pass psum reduction).
        from learningorchestra_tpu.models.logistic import _device_stats

        mu, sigma = _device_stats(X_dev, runtime.replicate(np.int32(n)),
                                  mesh=mesh)
    # Hidden dim must divide the model axis; round up.
    m = mesh.shape[MODEL_AXIS]
    hidden = ((hidden + m - 1) // m) * m

    params = init_params(jax.random.PRNGKey(seed), X.shape[1], hidden,
                         num_classes)
    params["mu"], params["sigma"] = jnp.asarray(mu), jnp.asarray(sigma)
    specs = param_specs()
    params = {k: jax.device_put(v, NamedSharding(mesh, specs[k]))
              for k, v in params.items()}

    y_dev, _ = runtime.shard_rows(np.asarray(y, np.int32))
    mask_dev, _ = runtime.shard_rows(
        (np.arange(len(X_dev)) < n).astype(np.float32))

    opt = optax.adam(lr)
    opt_state = opt.init(params)
    train_step = make_train_step(opt)

    @partial(jax.jit, static_argnames=("iters",))
    def run(params, opt_state, X, y, mask, l2, *, iters):
        def body(carry, _):
            params, opt_state = carry
            params, opt_state, loss = train_step(
                params, opt_state, X, y, mask, l2)
            return (params, opt_state), loss
        (params, opt_state), losses = jax.lax.scan(
            body, (params, opt_state), None, length=iters)
        return params, opt_state, losses

    l2_dev = runtime.replicate(np.float32(l2))
    if ckpt is not None and ckpt.enabled and iters > ckpt.every:
        # Iteration-segmented path (LO_TPU_FIT_CKPT_ROUNDS > 0): the
        # same jitted scan body runs in segments of ``every`` iters,
        # carrying (params, opt_state) on device between calls — per-
        # iteration arithmetic is identical to the single-scan oracle,
        # so the final params are bit-identical. Checkpoints persist
        # the carry at segment boundaries; a resume re-places it and
        # continues from the recorded iteration.
        from learningorchestra_tpu import jobs

        done = 0
        loaded = ckpt.load()
        if loaded is not None:
            it_done, arrays, cmeta = loaded
            if 0 < it_done < iters and any(k.startswith("o.")
                                           for k in arrays):
                done = it_done
                params, opt_state = _ckpt_restore(arrays, mesh, specs,
                                                  opt)
                from learningorchestra_tpu.utils import fitckpt

                fitckpt.count_resume()
                jobs.record_job_resume(ckpt.family, {
                    "iters": int(done), "of": int(iters),
                    "mesh_epoch": cmeta.get("mesh_epoch")})
            else:
                ckpt.clear()
        every = max(1, int(ckpt.every))
        while done < iters:
            k = min(every, iters - done)
            params, opt_state, _ = run(params, opt_state, X_dev, y_dev,
                                       mask_dev, l2_dev, iters=k)
            done += k
            jobs.heartbeat()
            if done < iters:
                ckpt.save(done, _ckpt_arrays(params, opt_state))
    else:
        params, _, _ = run(params, opt_state, X_dev, y_dev, mask_dev,
                           l2_dev, iters=iters)
    return TrainedModel(kind="mlp", params=params,
                        predict_proba_fn=_predict_proba,
                        num_classes=num_classes,
                        hparams={"hidden": hidden, "iters": iters, "lr": lr})


@jax.jit
def _predict_proba(params, X):
    return jax.nn.softmax(forward(params, X), axis=-1)


# ---------------------------------------------------------------------------
# Config-population programs (models/tune.py)
# ---------------------------------------------------------------------------

def _pop_mlp_init(seeds, hiddens, d, num_classes, mu, sigma, *,
                  model_mult):
    """Width-padded stacked init. Each member's W1/W2 are drawn at the
    member's OWN rounded hidden width — the normal draw depends on the
    array shape, so initializing at the padded width would diverge from
    the standalone fit — then zero-padded to the population max. The
    padded W1 columns / b1 entries / W2 rows receive exactly-zero
    gradients forever (relu'(0) = 0, and adam's 0/(√0+eps) update is 0),
    so they stay zero and each padded forward pass only adds exact-0.0
    terms to the hidden contraction."""
    m = int(model_mult)
    rounded = [((int(h) + m - 1) // m) * m for h in hiddens]
    h_max = max(rounded)
    stacks = {k: [] for k in ("W1", "b1", "W2", "b2", "mu", "sigma")}
    for seed, h in zip(seeds, rounded):
        p = init_params(jax.random.PRNGKey(int(seed)), d, h, num_classes)
        stacks["W1"].append(np.pad(np.asarray(p["W1"]),
                                   ((0, 0), (0, h_max - h))))
        stacks["b1"].append(np.pad(np.asarray(p["b1"]), (0, h_max - h)))
        stacks["W2"].append(np.pad(np.asarray(p["W2"]),
                                   ((0, h_max - h), (0, 0))))
        stacks["b2"].append(np.asarray(p["b2"]))
        stacks["mu"].append(np.asarray(mu, np.float32))
        stacks["sigma"].append(np.asarray(sigma, np.float32))
    params = {k: jnp.asarray(np.stack(v)) for k, v in stacks.items()}
    opt_state = jax.vmap(optax.scale_by_adam().init)(params)
    return params, opt_state, rounded


@partial(jax.jit, static_argnames=("iters",))
def _run_pop(params, opt_state, X, y, masks, lrs, l2s, iters_vec, alive,
             t0, *, iters):
    """One SEGMENT of Adam steps for a POPULATION of mlp configs — the
    serial ``run`` scan vmapped over members with per-member loss mask,
    traced learning rate (``scale_by_adam`` + manual ``u * (-lr)``, the
    exact multiply ``optax.adam``'s final ``scale(-lr)`` performs), l2
    and iteration budget. Steps past a member's budget (or a zeroed
    ``alive`` flag after a halving drop) freeze its params AND optimizer
    state via ``where``, so the member's final arithmetic is identical
    to its standalone fit."""

    def one_member(params, opt_state, mask, lr, l2, it_m, alive_m):
        tx = optax.scale_by_adam()

        def body(carry, i):
            params, opt_state = carry
            loss, grads = jax.value_and_grad(loss_fn)(params, X, y, mask,
                                                      l2)
            updates, new_state = tx.update(grads, opt_state)
            new_params = optax.apply_updates(
                params, jax.tree.map(lambda u: u * (-lr), updates))
            act = ((t0 + i) < it_m) & (alive_m > 0)
            params = jax.tree.map(
                lambda a, b: jnp.where(act, a, b), new_params, params)
            opt_state = jax.tree.map(
                lambda a, b: jnp.where(act, a, b), new_state, opt_state)
            return (params, opt_state), loss

        (params, opt_state), losses = jax.lax.scan(
            body, (params, opt_state), jnp.arange(iters))
        return params, opt_state, losses

    # lax.map, NOT vmap: both hidden-layer contractions are bf16
    # matmuls over a per-member W1/W2, and XLA tiles a batched bf16
    # matmul differently at every batch width — vmapped members drift
    # by ulps from their standalone fits and from themselves at other
    # population sizes. A scan over members runs the one unbatched
    # member program per config, which is what makes population mlp
    # bit-identical to serial mlp (tests/test_tune.py pins it).
    return jax.lax.map(
        lambda args: one_member(*args),
        (params, opt_state, masks, lrs, l2s, iters_vec, alive))


@jax.jit
def _pop_mlp_scores(params, X, y, ew_pop):
    """Per-member accuracy on per-member (eval-fold) row weights."""

    def one_member(params, ew):
        pred = jnp.argmax(forward(params, X), axis=1).astype(y.dtype)
        hit = ((pred == y).astype(jnp.float32) * ew).sum()
        return hit / jnp.maximum(ew.sum(), 1.0)

    return jax.vmap(one_member)(params, ew_pop)
